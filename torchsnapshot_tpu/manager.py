"""CheckpointManager: step-indexed snapshots with retention (beyond
reference parity — the reference leaves step naming, latest-resolution,
and retention entirely to the user; the JAX ecosystem's expectation is
orbax's ``CheckpointManager``, so a TPU-native framework should ship the
same layer).

One manager owns a base path. Each ``save(step, app_state)`` takes a
snapshot at ``<base>/step-<step>``; after the snapshot COMMITS, rank 0
records a small step marker under ``<base>/.steps/<step>`` and prunes
beyond ``max_to_keep``. Markers — not directory listings — define which
steps exist:

- an interrupted take leaves no marker, so ``latest_step()`` /
  ``restore()`` can never resolve a half-written snapshot (the marker is
  the manager-level commit point, layered above the snapshot-level
  metadata-last commit);
- listing ``.steps/`` is O(retained steps), never a scan of payload
  objects.

Multi-process discipline: every rank calls ``save``/``restore`` (they
run the usual snapshot collectives); marker writes and pruning happen on
rank 0 only, and ``restore(step=None)`` resolves the latest step on
rank 0 and broadcasts it so ranks can never pick different steps while a
prune races the listing.

``async_save`` returns a handle whose ``wait()`` finalizes the marker
and pruning after the background drain commits — the training loop
keeps the sub-second stall of ``Snapshot.async_take``.
"""

import asyncio
import logging
import os
import time
from typing import Any, List, Optional

from . import telemetry, tracing
from .telemetry import metrics as _metric_names
from .coord import Coordinator, barrier_compat, get_coordinator
from .io_types import IOReq, is_not_found_error
from .snapshot import (
    _COMPLETION_TIMEOUT_S,
    _BaseFromRank0,
    BASE_FROM_RANK0,
    PendingSnapshot,
    Snapshot,
)
from .stateful import AppState
from .storage_plugin import url_to_storage_plugin
from .utils.env import env_float

logger = logging.getLogger(__name__)

_STEP_PREFIX = ".steps/"
_PRUNING_PREFIX = ".pruning/"


def _step_dir(base_path: str, step: int) -> str:
    return f"{base_path}/step-{step}"


class CheckpointManager:
    """Step-indexed snapshot lifecycle over one base path.

    Usage::

        mgr = CheckpointManager("gs://bucket/run-7", max_to_keep=3)
        for step in range(n_steps):
            ...train...
            if step % 100 == 0:
                mgr.save(step, app_state)          # or mgr.async_save
        # resume later, possibly on a different pod shape:
        step = CheckpointManager("gs://bucket/run-7").restore(app_state)
    """

    def __init__(
        self,
        base_path: str,
        max_to_keep: Optional[int] = None,
        keep_period: Optional[int] = None,
        coord: Optional[Coordinator] = None,
        reconcile_on_init: Optional[str] = None,
        incremental: bool = False,
        full_period: Optional[int] = None,
        chunks: Optional[bool] = None,
        codec: Optional[Any] = None,
    ) -> None:
        """``max_to_keep`` bounds retained checkpoints; ``keep_period``
        additionally ARCHIVES every checkpoint whose step is a multiple
        of it — archived steps never count against ``max_to_keep`` and
        are never pruned (the orbax retention contract: a rolling recent
        window plus periodic keepers for post-hoc evaluation).

        ``incremental=True`` makes every ``save``/``async_save`` an
        incremental take based on the latest committed step (see
        incremental.py): unchanged arrays skip the device→host transfer
        and the storage write entirely, so periodic checkpointing pays
        for *changed* bytes only. Retention understands references: a
        step that newer snapshots still borrow objects from is deferred
        past ``max_to_keep`` (visibly, with a log line) until its last
        referencer is pruned. ``full_period`` forces a FULL take every
        time ``step %% full_period == 0``, bounding how long any old
        base stays pinned — without it, a never-changing array keeps
        its original writer retained for the whole run (which is
        correct, merely unbounded).

        ``chunks``/``codec`` enable the content-addressed chunk store
        for every save (chunkstore.py; defaults from
        ``TPUSNAPSHOT_CHUNKS``/``TPUSNAPSHOT_CODEC``): the manager's
        ``step-<N>`` layout puts the shared store at
        ``<base>/.chunkstore``, consecutive saves share unchanged
        chunks by content hash with no ``base=`` plumbing, and
        retention prunes free chunks through refcounted GC instead of
        the refuse-on-back-link model. Composes with
        ``incremental=True`` (leaf hits are cheaper than N chunk
        hits; chunking catches the partially-dirty remainder).

        ``reconcile_on_init`` ("adopt" or "sweep") runs
        :meth:`reconcile` once at construction — the job-startup hook
        for recovering async saves orphaned by a crash between commit
        and finalize. Construction-time reconcile is storage-only: in a
        multi-rank job, pass it on ONE rank (typically 0) or call
        :meth:`reconcile` explicitly there."""
        if max_to_keep is not None and max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        if keep_period is not None and keep_period < 1:
            raise ValueError(f"keep_period must be >= 1, got {keep_period}")
        if full_period is not None and full_period < 1:
            raise ValueError(f"full_period must be >= 1, got {full_period}")
        if full_period is not None and not incremental:
            raise ValueError("full_period requires incremental=True")
        if reconcile_on_init not in (None, "adopt", "sweep"):
            raise ValueError(
                f"reconcile_on_init must be None, 'adopt', or 'sweep'; "
                f"got {reconcile_on_init!r}"
            )
        self.base_path = base_path
        self.max_to_keep = max_to_keep
        self.keep_period = keep_period
        self.incremental = incremental
        self.full_period = full_period
        self.chunks = chunks
        self.codec = codec
        self._coord = coord
        # Last step committed THROUGH this manager instance + its
        # handle, reused as the next incremental base (seeded metadata
        # cache: no per-take base-metadata GET on rank 0).
        self._last_saved_step: Optional[int] = None
        self._last_saved: Optional[Snapshot] = None
        if reconcile_on_init is not None:
            self.reconcile(adopt=(reconcile_on_init == "adopt"))

    # ------------------------------------------------------------- steps

    def _list_steps(self, storage: Any) -> List[int]:
        markers = asyncio.run(storage.list_prefix(_STEP_PREFIX))
        if markers is None:
            raise RuntimeError(
                f"The storage backend for {self.base_path} cannot "
                f"enumerate objects; CheckpointManager requires a backend "
                f"with list_prefix support."
            )
        steps = []
        for m in markers:
            tail = m[len(_STEP_PREFIX):]
            try:
                steps.append(int(tail))
            except ValueError:
                logger.warning(f"Ignoring malformed step marker: {m}")
        return sorted(steps)

    def all_steps(self) -> List[int]:
        """Committed steps, ascending (storage-only; collective-free)."""
        storage = url_to_storage_plugin(self.base_path)
        try:
            return self._list_steps(storage)
        finally:
            storage.close()

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def reconcile(self, adopt: bool = True) -> List[int]:
        """Adopt or sweep orphaned async saves; returns the steps handled.

        If a process dies after an ``async_save``'s background drain
        commits but before ``wait()`` writes the step marker, the step's
        snapshot is fully committed yet invisible: ``latest_step()``
        cannot resolve it and retention never reclaims its bytes
        (VERDICT r3 weak #5). ``reconcile()`` scans for such orphans —
        ``step-<N>/.snapshot_metadata`` committed, ``.steps/<N>`` marker
        absent — and either *adopts* them (writes the missing marker, so
        the work done before the crash becomes restorable, then re-runs
        retention) or, with ``adopt=False``, *sweeps* them via
        :meth:`Snapshot.delete`, guarded by ``TPUSNAPSHOT_SWEEP_MIN_AGE_S``
        so an in-flight async save racing this scan is never destroyed.

        Steps with a ``.pruning/<N>`` tombstone are skipped: those are
        interrupted prunes, re-driven to deletion by the next prune —
        adopting one would resurrect a checkpoint the retention policy
        already condemned.

        Beyond orphans, reconcile is also the debris janitor (in BOTH
        modes): step prefixes holding payload objects but no committed
        metadata, no marker, and no tombstone — a take that crashed
        before its commit point, which nothing else can ever resolve or
        reclaim — are swept via ``Snapshot.delete(sweep=True)`` (each
        object individually protected by the
        ``TPUSNAPSHOT_SWEEP_MIN_AGE_S`` guard, so an in-flight take is
        never destroyed), and torn control-file debris under
        ``.steps/``/``.pruning/`` (``<n>.tmp<pid>`` leftovers from an fs
        crash mid-marker-write) is removed under the same age guard.

        Storage-only and single-process (like :meth:`all_steps`): run it
        from one rank — typically at job startup before the first
        ``restore`` — or from an offline tool. Cost is one listing of
        the whole base prefix (O(objects)), so this is a recovery
        operation, not a per-step one.
        """
        import re

        pat = re.compile(r"^step-(\d+)/" + re.escape(".snapshot_metadata") + "$")
        storage = url_to_storage_plugin(self.base_path)
        try:
            marked = set(self._list_steps(storage))
            objs = asyncio.run(storage.list_prefix("step-"))
            if objs is None:
                raise RuntimeError(
                    f"The storage backend for {self.base_path} cannot "
                    f"enumerate objects; reconcile() requires list_prefix "
                    f"support."
                )
            committed = set()
            for obj in objs:
                m = pat.match(obj)
                if m:
                    committed.add(int(m.group(1)))
            tombstoned = set()
            for t in asyncio.run(storage.list_prefix(_PRUNING_PREFIX)) or []:
                try:
                    tombstoned.add(int(t[len(_PRUNING_PREFIX):]))
                except ValueError:
                    logger.warning(f"Ignoring malformed prune tombstone: {t}")
            orphans = sorted(committed - marked - tombstoned)
            handled: List[int] = []
            if adopt:
                for step in orphans:
                    marker = IOReq(path=f"{_STEP_PREFIX}{step}")
                    marker.buf.write(
                        _step_dir(self.base_path, step).encode()
                    )
                    asyncio.run(storage.write(marker))
                    logger.info(f"reconcile: adopted orphan step {step}")
                    handled.append(step)
                if handled and self.max_to_keep is not None:
                    # Adoption may overfill the retention window.
                    self._prune(storage)
            else:
                for step in orphans:
                    # Age-guard on the commit point: a just-committed
                    # orphan may be an async save whose wait() simply
                    # has not run yet.
                    min_age_s = env_float(
                        "TPUSNAPSHOT_SWEEP_MIN_AGE_S", 3600.0
                    )
                    if min_age_s > 0:
                        age = asyncio.run(
                            storage.object_age_s(
                                f"step-{step}/.snapshot_metadata"
                            )
                        )
                        if age is None:
                            # Fail closed (ADVICE r4): the commit object
                            # was just listed, so it exists — a backend
                            # that cannot report its age must not be read
                            # as "old enough to sweep", or a
                            # just-committed async save gets destroyed.
                            # Setting TPUSNAPSHOT_SWEEP_MIN_AGE_S=0
                            # disables the guard explicitly.
                            logger.info(
                                f"reconcile: sparing orphan step {step} "
                                f"(backend cannot report age; treating "
                                f"as younger than {min_age_s:.0f}s)"
                            )
                            continue
                        if age < min_age_s:
                            logger.info(
                                f"reconcile: sparing young orphan step "
                                f"{step} (age {age:.0f}s < "
                                f"{min_age_s:.0f}s)"
                            )
                            continue
                    Snapshot(_step_dir(self.base_path, step)).delete(
                        sweep=True
                    )
                    logger.info(f"reconcile: swept orphan step {step}")
                    handled.append(step)
            handled.extend(
                self._reclaim_uncommitted(
                    storage, objs, committed, marked, tombstoned
                )
            )
            self._clean_torn_control_files(storage)
            self._clean_progress_debris(storage, objs)
            self._reconcile_hot_tier(committed, marked, tombstoned)
            self._reconcile_chunkstore(storage)
            return handled
        finally:
            storage.close()

    def _reclaim_uncommitted(
        self, storage: Any, objs, committed, marked, tombstoned
    ) -> List[int]:
        """Sweep step prefixes that hold objects but no commit point.

        A take that crashed before writing ``.snapshot_metadata`` leaves
        payloads that no marker, no metadata, and no tombstone will ever
        name — invisible to ``latest_step``/``restore`` (detectably
        incomplete, the crash-consistency invariant's "detect" arm) but
        also invisible to retention, so only this pass can reclaim the
        bytes. The sweep delete age-guards every object
        (``TPUSNAPSHOT_SWEEP_MIN_AGE_S``): a concurrent in-progress take
        at the same step is spared, and a retry later reclaims it once
        aged. Returns the steps whose prefixes came out empty."""
        import re

        reclaimed: List[int] = []
        step_pat = re.compile(r"^step-(\d+)/")
        seen = set()
        for obj in objs:
            m = step_pat.match(obj)
            if m:
                seen.add(int(m.group(1)))
        for step in sorted(seen - committed - marked - tombstoned):
            try:
                Snapshot(_step_dir(self.base_path, step)).delete(sweep=True)
                remaining = asyncio.run(
                    storage.list_prefix(f"step-{step}/")
                )
            except Exception as e:
                logger.warning(
                    f"reconcile: reclaiming uncommitted step {step} "
                    f"failed ({e!r}); retried on the next reconcile."
                )
                continue
            if remaining:
                logger.info(
                    f"reconcile: uncommitted step {step}: "
                    f"{len(remaining)} object(s) spared by the sweep age "
                    f"guard; retried on the next reconcile."
                )
            else:
                logger.info(
                    f"reconcile: reclaimed uncommitted step {step}"
                )
                reclaimed.append(step)
        return reclaimed

    def _sweep_aged_objects(self, storage: Any, objs, what: str) -> None:
        """Shared body of reconcile's debris sweeps: delete each object,
        individually protected by the ``TPUSNAPSHOT_SWEEP_MIN_AGE_S``
        guard (unknown age and failed probes both fail CLOSED — the
        object may belong to an in-flight take)."""
        min_age_s = env_float("TPUSNAPSHOT_SWEEP_MIN_AGE_S", 3600.0)
        for obj in objs:
            if min_age_s > 0:
                try:
                    age = asyncio.run(storage.object_age_s(obj))
                except Exception as e:
                    logger.warning(
                        f"reconcile: sparing {what} {obj} "
                        f"(age probe failed: {e!r})"
                    )
                    continue
                if age is None or age < min_age_s:
                    continue
            try:
                asyncio.run(storage.delete(obj))
                logger.info(f"reconcile: removed {what} {obj}")
            except Exception as e:
                if not is_not_found_error(e):
                    logger.warning(
                        f"reconcile: removing {what} {obj} "
                        f"failed ({e!r})"
                    )

    def _clean_torn_control_files(self, storage: Any) -> None:
        """Remove ``<n>.tmp<pid>`` debris under ``.steps/``/``.pruning/``
        — a crash between the fs plugin's tmp-write and rename sub-steps
        leaves one, and no marker/tombstone path ever resolves it (it
        merely triggers a malformed-marker warning on every listing).
        The telemetry ledger's ``.telemetry/`` prefix gets the same
        treatment for ``*.tmp<pid>`` append debris — but NEVER the
        ledger object itself: reconcile treats committed ledger records
        as durable metadata (telemetry/ledger.py). Age-guarded like
        every sweep."""
        import re

        from .telemetry.ledger import LEDGER_DIR

        doomed = []
        for prefix in (_STEP_PREFIX, _PRUNING_PREFIX):
            for obj in asyncio.run(storage.list_prefix(prefix)) or []:
                if re.fullmatch(r"\d+\.tmp\d+", obj[len(prefix):]):
                    doomed.append(obj)
        for obj in asyncio.run(storage.list_prefix(LEDGER_DIR + "/")) or []:
            if re.search(r"\.tmp\d+$", obj):
                doomed.append(obj)
        self._sweep_aged_objects(storage, doomed, "torn control file")

    def _reconcile_hot_tier(self, committed, marked, tombstoned) -> None:
        """Sweep orphaned hot-tier RAM buffers (hottier/): steps with
        neither committed metadata nor a step marker — a take that
        crashed pre-commit, or a prune that already condemned the step
        (tombstoned) — have buffers nothing will ever read or drain.
        Keep-set = committed ∪ marked: a COMMITTED-but-not-yet-drained
        take's replicas are structurally unreachable by this sweep (its
        metadata is its commit point), so reconcile can never reclaim
        bytes a restorable snapshot still needs; uncommitted young roots
        are spared by the same ``TPUSNAPSHOT_SWEEP_MIN_AGE_S`` guard as
        every storage sweep. Best-effort like all telemetry/tier
        bookkeeping: a tier failure must never fail reconcile."""
        try:
            from . import hottier

            keep = {
                _step_dir(self.base_path, s)
                for s in (set(committed) | set(marked)) - set(tombstoned)
            }
            for root in hottier.reconcile_hot_tier(self.base_path, keep):
                logger.info(
                    f"reconcile: dropped orphaned hot-tier buffers for "
                    f"{root}"
                )
        except Exception as e:
            logger.warning(f"reconcile: hot-tier buffer sweep failed: {e!r}")

    def _reconcile_chunkstore(self, storage: Any) -> None:
        """Sweep the run's content-addressed chunk store
        (``<base>/.chunkstore``, chunkstore.py): stale take intents,
        stale ref docs (uncommitted + aged), and chunk objects no live
        committed manifest references — the re-drive for any chunk GC a
        crashed ``Snapshot.delete`` left half-done. Cheap when the run
        never chunked (one empty listing); best-effort like every
        debris pass."""
        try:
            probe = asyncio.run(
                storage.list_prefix(".chunkstore/")
            )
            if not probe:
                return
            from . import chunkstore

            chunkstore.reconcile_store(self.base_path)
        except Exception as e:
            logger.warning(f"reconcile: chunk-store sweep failed: {e!r}")

    def _clean_progress_debris(self, storage: Any, objs) -> None:
        """Reclaim orphaned ``step-<N>/.progress/<take_id>/<rank>``
        records from crashed takes (same convention as the ``.report/``
        per-rank summaries: rank 0 deletes them at commit, so any
        survivor belongs to a take that died mid-drain — or to one still
        in flight, which the age guard protects). An uncommitted step's
        sweep reclaims them too; this pass additionally covers COMMITTED
        steps whose post-commit cleanup lost a race with a crash, which
        no sweep would ever revisit. ``step-<N>/.scope/rank<R>`` sampler
        records (telemetry/sampler.py) get the identical treatment:
        live operational state whose writer crashed is debris, and only
        this pass ever revisits a committed step."""
        import re

        pat = re.compile(r"^step-\d+/(\.progress|\.scope)/")
        self._sweep_aged_objects(
            storage,
            [obj for obj in objs if pat.match(obj)],
            "orphaned progress/scope record",
        )

    # -------------------------------------------------------------- save

    def _incremental_base(
        self, step: int, coordinator: Coordinator
    ) -> Optional[Any]:
        """The base for an incremental save, or None for a full take.
        Resolved on rank 0 only — other ranks pass the BASE_FROM_RANK0
        sentinel (``Snapshot.take`` collates the base collectively with
        rank 0 authoritative), so they need not list storage and can
        never race a prune into a different answer. When the latest
        step is the one this manager just committed, its retained
        handle is passed instead of a path: the handle's seeded
        metadata cache saves every take a base-metadata GET + parse."""
        if not self.incremental:
            return None
        if self.full_period is not None and step % self.full_period == 0:
            # step is collective, so every rank resolves "full take"
            # here without waiting for rank 0's broadcast.
            return None
        if coordinator.get_rank() != 0:
            # Ranks != 0 defer to rank 0's collated answer (no storage
            # listing, no divergence warning); the retained handle rides
            # along as a HINT — when rank 0 names the same snapshot,
            # the handle's seeded metadata cache saves this rank the
            # multi-MB base-metadata GET + parse, and when it does not
            # (stale manager, out-of-order step) the hint is ignored.
            return _BaseFromRank0(hint=self._last_saved)
        latest = self.latest_step()
        if latest is None or latest >= step:
            # No committed base, or out-of-order/re-saved step numbers:
            # take a full snapshot rather than reference "the future".
            return None
        if latest == self._last_saved_step and self._last_saved is not None:
            return self._last_saved
        return _step_dir(self.base_path, latest)

    def save(
        self,
        step: int,
        app_state: AppState,
        replicated: Optional[List[str]] = None,
        compression: Optional[str] = None,
    ) -> Snapshot:
        """Take a snapshot for ``step``; commit its marker; prune."""
        coordinator = get_coordinator(self._coord)
        snapshot = Snapshot.take(
            _step_dir(self.base_path, step),
            app_state,
            coord=coordinator,
            replicated=replicated,
            compression=compression,
            base=self._incremental_base(step, coordinator),
            fingerprint=True if self.incremental else None,
            chunks=self.chunks,
            codec=self.codec,
        )
        self._finalize(step, coordinator)
        # Every rank retains the handle: sync KV-route commits seed ALL
        # ranks' handle caches with the merged metadata, so the next
        # incremental save skips the base-metadata GET on every rank,
        # not just rank 0.
        self._last_saved_step, self._last_saved = step, snapshot
        return snapshot

    def async_save(
        self,
        step: int,
        app_state: AppState,
        replicated: Optional[List[str]] = None,
        compression: Optional[str] = None,
        stage: str = "auto",
    ) -> "PendingManagedSnapshot":
        """Async take for ``step``; the returned handle's ``wait()``
        finalizes the marker and pruning after the drain commits —
        dropping the handle without waiting leaves the step invisible
        (no marker) and unpruned."""
        coordinator = get_coordinator(self._coord)
        pending = Snapshot.async_take(
            _step_dir(self.base_path, step),
            app_state,
            coord=coordinator,
            replicated=replicated,
            compression=compression,
            stage=stage,
            base=self._incremental_base(step, coordinator),
            fingerprint=True if self.incremental else None,
            chunks=self.chunks,
            codec=self.codec,
        )
        return PendingManagedSnapshot(self, step, pending, coordinator)

    def _finalize(self, step: int, coordinator: Coordinator) -> None:
        # Marker write (rank 0) is the correctness-bearing, latency-
        # critical part: do it first, barrier, and only then prune
        # (ADVICE r3). Pruning a full step over a cloud backend can
        # itself approach the barrier timeout, and must not stall the
        # other ranks; the barrier runs in a ``finally`` so a rank-0
        # marker failure releases them promptly (they observe it as the
        # step never becoming latest) instead of stranding them in an
        # opaque store TimeoutError.
        storage = None
        try:
            try:
                if coordinator.get_rank() == 0:
                    storage = url_to_storage_plugin(self.base_path)
                    marker = IOReq(path=f"{_STEP_PREFIX}{step}")
                    marker.buf.write(
                        _step_dir(self.base_path, step).encode()
                    )
                    marker_t0 = time.monotonic()
                    asyncio.run(storage.write(marker))
                    telemetry.histogram(
                        _metric_names.MANAGER_STEP_MARKER_SECONDS
                    ).observe(time.monotonic() - marker_t0)
                    # Manager-level commit milestone (the snapshot-level
                    # one is metadata_committed): from here the step is
                    # resolvable and must restore clean under any crash.
                    tracing.instant("step_marker_committed", step=step)
            finally:
                # The marker write above can legitimately outlast the
                # store's default wait (storage retries + backoff over a
                # flaky cloud backend), so waiting ranks get the same
                # long leash as the snapshot commit barrier.
                barrier_compat(coordinator, _COMPLETION_TIMEOUT_S)
            if storage is not None and self.max_to_keep is not None:
                self._prune(storage)
        finally:
            if storage is not None:
                storage.close()

    def _prune(self, storage: Any) -> None:
        prune_t0 = time.monotonic()
        try:
            self._prune_impl(storage)
        finally:
            telemetry.histogram(
                _metric_names.MANAGER_PRUNE_SECONDS
            ).observe(time.monotonic() - prune_t0)

    def _prune_impl(self, storage: Any) -> None:
        # Two-phase with a tombstone, so an interrupted prune is
        # re-driven by the NEXT prune instead of leaking the step's
        # payloads forever (markers alone cannot re-find a step whose
        # marker was already deleted):
        #   1. write .pruning/<step> tombstone
        #   2. delete the .steps/<step> marker (step now unresolvable)
        #   3. delete the step's payloads
        #   4. delete the tombstone
        steps = self._list_steps(storage)
        if self.keep_period is not None:
            steps = [s for s in steps if s % self.keep_period != 0]
        doomed = steps[: -self.max_to_keep]
        leftovers = asyncio.run(storage.list_prefix(_PRUNING_PREFIX)) or []
        for t in leftovers:
            try:
                doomed.append(int(t[len(_PRUNING_PREFIX):]))
            except ValueError:
                logger.warning(f"Ignoring malformed prune tombstone: {t}")
        # Newest-first: an incremental chain's referencers are always
        # NEWER than their base, so pruning in reverse order releases a
        # doomed base's back-links before its own reference check runs —
        # one pass reclaims a whole doomed chain instead of deferring
        # the base to the next prune.
        for step in sorted(set(doomed), reverse=True):
            try:
                # A step that live incremental snapshots still reference
                # holds THEIR data: defer BEFORE tombstoning, so the
                # step keeps its marker (stays resolvable/restorable)
                # and max_to_keep is visibly, not silently, exceeded.
                # Deferred steps re-enter `doomed` on later prunes and
                # fall out once their referencers are pruned.
                try:
                    referenced = Snapshot(
                        _step_dir(self.base_path, step)
                    ).is_referenced()
                except Exception as e:
                    # Fail toward DEFER: proceeding would tombstone the
                    # step and delete its marker before delete()'s own
                    # re-check can refuse — leaving a live-referenced
                    # step permanently invisible to the manager. A
                    # deferred step just gets re-checked next prune.
                    logger.warning(
                        f"Prune of step {step}: reference check failed "
                        f"({e!r}); deferring."
                    )
                    referenced = True
                if referenced:
                    logger.info(
                        f"Prune of step {step} deferred: still "
                        f"referenced by incremental snapshot(s)."
                    )
                    continue
                tomb = IOReq(path=f"{_PRUNING_PREFIX}{step}")
                tomb.buf.write(b"1")
                asyncio.run(storage.write(tomb))
                try:
                    asyncio.run(storage.delete(f"{_STEP_PREFIX}{step}"))
                except Exception as e:
                    if not is_not_found_error(e):
                        raise
                Snapshot(_step_dir(self.base_path, step)).delete(sweep=True)
                telemetry.counter(_metric_names.MANAGER_STEPS_PRUNED).inc()
                # The tombstone clears only once the step prefix is
                # verifiably empty: a retry sweep may SPARE young
                # unreferenced payloads under TPUSNAPSHOT_SWEEP_MIN_AGE_S
                # (they look like an in-progress take to the guard) and
                # still return success — dropping the tombstone then
                # would make the leak permanent. Kept tombstones retry on
                # later prunes, succeeding once the guard ages out.
                remaining = asyncio.run(
                    storage.list_prefix(f"step-{step}/")
                )
                if remaining:
                    logger.info(
                        f"Prune of step {step}: {len(remaining)} "
                        f"object(s) spared by the sweep age guard; "
                        f"keeping its tombstone for a later retry."
                    )
                else:
                    asyncio.run(storage.delete(f"{_PRUNING_PREFIX}{step}"))
            except Exception as e:
                logger.warning(
                    f"Pruning step {step} failed ({e!r}); its tombstone "
                    f"remains and the next prune retries it."
                )

    # ------------------------------------------------------------ restore

    def restore(
        self,
        app_state: AppState,
        step: Optional[int] = None,
        paths: Optional[List[str]] = None,
    ) -> int:
        """Restore ``app_state`` from ``step`` (default: latest);
        returns the step restored. Latest-resolution happens on rank 0
        and is broadcast, so a racing prune cannot split ranks."""
        coordinator = get_coordinator(self._coord)
        if step is None:
            chosen = (
                self.latest_step() if coordinator.get_rank() == 0 else None
            )
            step = coordinator.broadcast_object(chosen, src=0)
            if step is None:
                raise FileNotFoundError(
                    f"No committed checkpoints under {self.base_path} "
                    f"(no {_STEP_PREFIX}* markers)."
                )
        Snapshot(_step_dir(self.base_path, step)).restore(
            app_state, coord=coordinator, paths=paths
        )
        return step


class PendingManagedSnapshot:
    """Handle for :meth:`CheckpointManager.async_save`."""

    def __init__(
        self,
        manager: CheckpointManager,
        step: int,
        pending: PendingSnapshot,
        coordinator: Coordinator,
    ) -> None:
        self._manager = manager
        self._step = step
        self._pending = pending
        self._coordinator = coordinator
        self._finalized = False

    def done(self) -> bool:
        return self._pending.done()

    def wait(self, timeout_s: float = 1800.0) -> Snapshot:
        snapshot = self._pending.wait(timeout_s=timeout_s)
        if not self._finalized:
            # Flag AFTER success: a transient marker-write failure must
            # stay retriable on the next wait(), not silently skip the
            # step's commit.
            self._manager._finalize(self._step, self._coordinator)
            self._finalized = True
            self._manager._last_saved_step = self._step
            self._manager._last_saved = snapshot
        return snapshot
