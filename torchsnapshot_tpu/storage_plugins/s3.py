"""S3 storage plugin.

TPU-native analog of reference torchsnapshot/storage_plugins/s3.py:14-53.
The reference uses aiobotocore; this environment may not ship it, so we
accept either aiobotocore (preferred, truly async) or boto3 wrapped in a
thread executor, failing with an actionable error only when neither is
installed (optional-import pattern, reference s3.py:16-22).
"""

import asyncio
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from .. import telemetry
from ..io_types import IOReq, StoragePlugin

_IO_THREADS = 8


class S3StoragePlugin(StoragePlugin):
    def __init__(self, root: str, client: Optional[Any] = None) -> None:
        """``client`` injects a pre-built (or fake) sync boto3-style
        client; the default autodetects aiobotocore, then boto3."""
        self._mode = None
        if client is not None:
            self._client = client
            self._executor = ThreadPoolExecutor(max_workers=_IO_THREADS)
            self._mode = "sync"
        else:
            try:
                from aiobotocore.session import get_session  # type: ignore

                self._session = get_session()
                self._mode = "aio"
            except ImportError:
                try:
                    import boto3  # type: ignore

                    self._client = boto3.client("s3")
                    self._executor = ThreadPoolExecutor(max_workers=_IO_THREADS)
                    self._mode = "sync"
                except ImportError as e:
                    raise RuntimeError(
                        "S3 support requires aiobotocore or boto3."
                    ) from e
        components = root.split("/", 1)
        if len(components) != 2:
            raise ValueError(f'S3 root must be a "bucket/path" pair, got "{root}".')
        self.bucket, self.root = components

    def _key(self, path: str) -> str:
        return f"{self.root}/{path}"

    async def write(self, io_req: IOReq) -> None:
        if io_req.data is not None:
            body = bytes(io_req.data)
        else:
            io_req.buf.seek(0)
            body = io_req.buf.getvalue()
        t0 = _time.monotonic()
        if self._mode == "aio":
            async with self._session.create_client("s3") as client:
                await client.put_object(
                    Bucket=self.bucket, Key=self._key(io_req.path), Body=body
                )
        else:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                self._executor,
                lambda: self._client.put_object(
                    Bucket=self.bucket, Key=self._key(io_req.path), Body=body
                ),
            )
        telemetry.record_storage_op(
            "s3", "write", _time.monotonic() - t0, len(body)
        )

    async def read(self, io_req: IOReq) -> None:
        range_hdr = None
        if io_req.byte_range is not None:
            start, end = io_req.byte_range
            range_hdr = f"bytes={start}-{end - 1}"
        t0 = _time.monotonic()
        if self._mode == "aio":
            async with self._session.create_client("s3") as client:
                kwargs = {"Bucket": self.bucket, "Key": self._key(io_req.path)}
                if range_hdr:
                    kwargs["Range"] = range_hdr
                response = await client.get_object(**kwargs)
                async with response["Body"] as stream:
                    io_req.data = await stream.read()
        else:
            loop = asyncio.get_running_loop()

            def _get() -> bytes:
                kwargs = {"Bucket": self.bucket, "Key": self._key(io_req.path)}
                if range_hdr:
                    kwargs["Range"] = range_hdr
                return self._client.get_object(**kwargs)["Body"].read()

            io_req.data = await loop.run_in_executor(self._executor, _get)
        telemetry.record_storage_op(
            "s3",
            "read",
            _time.monotonic() - t0,
            len(io_req.data) if io_req.data is not None else 0,
        )

    async def delete(self, path: str) -> None:
        t0 = _time.monotonic()
        if self._mode == "aio":
            async with self._session.create_client("s3") as client:
                await client.delete_object(Bucket=self.bucket, Key=self._key(path))
        else:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                self._executor,
                lambda: self._client.delete_object(
                    Bucket=self.bucket, Key=self._key(path)
                ),
            )
        telemetry.record_storage_op("s3", "delete", _time.monotonic() - t0)

    async def list_prefix(self, prefix: str):
        full_prefix = f"{self.root}/{prefix}" if prefix else f"{self.root}/"
        keys = []
        if self._mode == "aio":
            async with self._session.create_client("s3") as client:
                paginator = client.get_paginator("list_objects_v2")
                async for page in paginator.paginate(
                    Bucket=self.bucket, Prefix=full_prefix
                ):
                    keys.extend(o["Key"] for o in page.get("Contents", []))
        else:
            loop = asyncio.get_running_loop()

            def _list():
                out = []
                paginator = self._client.get_paginator("list_objects_v2")
                for page in paginator.paginate(
                    Bucket=self.bucket, Prefix=full_prefix
                ):
                    out.extend(o["Key"] for o in page.get("Contents", []))
                return out

            keys = await loop.run_in_executor(self._executor, _list)
        return [k[len(self.root) + 1 :] for k in keys]

    async def object_age_s(self, path: str):
        import datetime

        def _from_head(head) -> Optional[float]:
            modified = head.get("LastModified")
            if modified is None:
                return None
            now = datetime.datetime.now(datetime.timezone.utc)
            return max(0.0, (now - modified).total_seconds())

        from ..io_types import is_not_found_error

        try:
            if self._mode == "aio":
                async with self._session.create_client("s3") as client:
                    head = await client.head_object(
                        Bucket=self.bucket, Key=self._key(path)
                    )
                return _from_head(head)
            loop = asyncio.get_running_loop()
            head = await loop.run_in_executor(
                self._executor,
                lambda: self._client.head_object(
                    Bucket=self.bucket, Key=self._key(path)
                ),
            )
            return _from_head(head)
        except Exception as e:
            # Vanished object: fine to report unknown (deleting a missing
            # object is a no-op). Any OTHER failure must propagate — the
            # sweep age guard fails CLOSED on it (sparing the object)
            # rather than treating a throttled HEAD as "no age, sweep it".
            if is_not_found_error(e):
                return None
            raise

    async def object_size_bytes(self, path: str):
        from ..io_types import is_not_found_error

        def _from_head(head) -> Optional[int]:
            size = head.get("ContentLength")
            return None if size is None else int(size)

        try:
            if self._mode == "aio":
                async with self._session.create_client("s3") as client:
                    head = await client.head_object(
                        Bucket=self.bucket, Key=self._key(path)
                    )
                return _from_head(head)
            loop = asyncio.get_running_loop()
            head = await loop.run_in_executor(
                self._executor,
                lambda: self._client.head_object(
                    Bucket=self.bucket, Key=self._key(path)
                ),
            )
            return _from_head(head)
        except Exception as e:
            if is_not_found_error(e):
                return None
            raise

    def close(self) -> None:
        if self._mode == "sync":
            self._executor.shutdown(wait=True)
