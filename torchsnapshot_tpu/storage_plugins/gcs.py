"""Google Cloud Storage plugin — the TPU-VM fast path.

TPU-native analog of reference torchsnapshot/storage_plugins/gcs.py:19-68.
TPU VMs sit next to GCS, so ``gs://`` is the north-star storage target
(BASELINE.json). The sync ``google-cloud-storage`` client is wrapped in a
thread executor (reference gcs.py:41,48-50); ranged reads map to
``blob.download_as_bytes(start=, end=)`` so resharding restores fetch only
overlapping byte ranges.
"""

import asyncio
import logging
import os
import time as _time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from .. import telemetry
from ..io_types import IOReq, StoragePlugin, io_payload

logger = logging.getLogger(__name__)

_IO_THREADS = 8

# Objects at least this large upload as concurrent parts + one server-side
# compose (GCS caps compose at 32 components). A single synchronous
# upload_from_file stream tops out well below NIC bandwidth for the 512 MB
# chunks the io preparer emits; parallel part uploads are the standard GCS
# recipe for large objects (gsutil -o GSUtil:parallel_composite_upload).
_PARALLEL_UPLOAD_ENV = "TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES"
_DEFAULT_PARALLEL_UPLOAD_BYTES = 64 * 1024 * 1024
_MAX_COMPOSE_COMPONENTS = 32


def _parallel_upload_threshold() -> int:
    return int(
        os.environ.get(_PARALLEL_UPLOAD_ENV, _DEFAULT_PARALLEL_UPLOAD_BYTES)
    )


class GCSStoragePlugin(StoragePlugin):
    def __init__(self, root: str, client: Optional[Any] = None) -> None:
        """``client`` injects a pre-built (or fake) ``storage.Client`` —
        the default constructs one from ambient credentials."""
        components = root.split("/", 1)
        if len(components) != 2:
            raise ValueError(
                f'GCS root must be a "bucket/path" pair, got "{root}".'
            )
        self.bucket_name, self.root = components
        if client is None:
            try:
                from google.cloud import storage  # type: ignore
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "GCS support requires the google-cloud-storage package."
                ) from e
            client = storage.Client()
        self._client = client
        self._bucket = self._client.bucket(self.bucket_name)
        self._executor = ThreadPoolExecutor(max_workers=_IO_THREADS)

    def _blob(self, path: str):
        return self._bucket.blob(f"{self.root}/{path}")

    def _write_sync(self, io_req: IOReq) -> None:
        if io_req.data is not None:
            import io as _io

            self._blob(io_req.path).upload_from_file(_io.BytesIO(io_req.data))
        else:
            io_req.buf.seek(0)
            self._blob(io_req.path).upload_from_file(io_req.buf)

    def _upload_part_sync(self, key: str, payload) -> None:
        import io as _io

        self._bucket.blob(key).upload_from_file(_io.BytesIO(payload))

    async def _parallel_composite_upload(self, path: str, payload) -> None:
        """Upload ``payload`` as ≤32 concurrent parts + one compose.

        Part objects are nonce-named (concurrent takes to the same path
        must not collide) and best-effort deleted afterwards — a crashed
        upload's parts are swept by ``Snapshot.delete(sweep=True)``.
        """
        view = memoryview(payload)
        n_parts = min(
            _MAX_COMPOSE_COMPONENTS,
            max(1, -(-len(view) // _parallel_upload_threshold())),
        )
        bounds = [
            len(view) * i // n_parts for i in range(n_parts + 1)
        ]
        nonce = uuid.uuid4().hex[:12]
        part_keys = [
            f"{self.root}/{path}.part{i}.{nonce}" for i in range(n_parts)
        ]
        loop = asyncio.get_running_loop()
        try:
            await asyncio.gather(
                *(
                    loop.run_in_executor(
                        self._executor,
                        self._upload_part_sync,
                        part_keys[i],
                        view[bounds[i] : bounds[i + 1]],
                    )
                    for i in range(n_parts)
                )
            )

            def _compose_and_check() -> None:
                blob = self._blob(path)
                blob.compose([self._bucket.blob(k) for k in part_keys])
                # Cheap integrity cross-check (one metadata op, no
                # download): the composed object's size must equal the
                # payload's. Guards against a part silently truncated or
                # composed out of an interfering concurrent upload; a
                # mismatch surfaces here — inside the retry layer, which
                # re-runs the whole object — instead of at restore time.
                try:
                    blob.reload()
                    composed_size = blob.size
                except (AttributeError, NotImplementedError):
                    return  # fakes/backends without metadata reload
                # Transient reload errors deliberately propagate: a
                # swallowed 503 here would skip the integrity check and
                # let a truncated compose pass; the retry layer re-runs
                # the whole object instead.
                if composed_size is not None and composed_size != len(view):
                    raise RuntimeError(
                        f"GCS composite upload of {path}: composed object "
                        f"is {composed_size} bytes, expected {len(view)}"
                    )

            await loop.run_in_executor(self._executor, _compose_and_check)
        finally:

            def _best_effort_delete(k):
                try:
                    self._bucket.blob(k).delete()
                except Exception:
                    # Leaked parts cost storage, not correctness (the
                    # sweep reclaims them); log so a systematically
                    # failing cleanup is visible instead of silent.
                    logger.warning(
                        f"best-effort delete of upload part {k} failed",
                        exc_info=True,
                    )

            await asyncio.gather(
                *(
                    loop.run_in_executor(self._executor, _best_effort_delete, k)
                    for k in part_keys
                )
            )

    def _read_sync(self, io_req: IOReq) -> None:
        blob = self._blob(io_req.path)
        if io_req.byte_range is not None:
            start, end = io_req.byte_range
            data = blob.download_as_bytes(start=start, end=end - 1)
        else:
            data = blob.download_as_bytes()
        io_req.data = data

    async def write(self, io_req: IOReq) -> None:
        payload = io_payload(io_req)
        t0 = _time.monotonic()
        if len(payload) >= _parallel_upload_threshold():
            # Orchestrated from the event loop (no executor thread blocks
            # waiting on part futures — the 8 IO threads all push bytes).
            await self._parallel_composite_upload(io_req.path, payload)
        else:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(self._executor, self._write_sync, io_req)
        telemetry.record_storage_op(
            "gcs", "write", _time.monotonic() - t0, len(payload)
        )

    async def read(self, io_req: IOReq) -> None:
        loop = asyncio.get_running_loop()
        t0 = _time.monotonic()
        await loop.run_in_executor(self._executor, self._read_sync, io_req)
        telemetry.record_storage_op(
            "gcs",
            "read",
            _time.monotonic() - t0,
            len(io_req.data) if io_req.data is not None else 0,
        )

    async def delete(self, path: str) -> None:
        loop = asyncio.get_running_loop()
        t0 = _time.monotonic()
        await loop.run_in_executor(self._executor, self._blob(path).delete)
        telemetry.record_storage_op("gcs", "delete", _time.monotonic() - t0)

    def _list_sync(self, prefix: str):
        full_prefix = f"{self.root}/{prefix}" if prefix else f"{self.root}/"
        blobs = self._client.list_blobs(self.bucket_name, prefix=full_prefix)
        return [b.name[len(self.root) + 1 :] for b in blobs]

    async def list_prefix(self, prefix: str):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._executor, self._list_sync, prefix)

    def _age_sync(self, path: str):
        import datetime
        import time as _time

        blob = self._blob(path)
        blob.reload()
        updated = getattr(blob, "updated", None)
        if updated is None:
            return None
        if isinstance(updated, (int, float)):
            return max(0.0, _time.time() - updated)
        now = datetime.datetime.now(datetime.timezone.utc)
        return max(0.0, (now - updated).total_seconds())

    async def object_age_s(self, path: str):
        from ..io_types import is_not_found_error

        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                self._executor, self._age_sync, path
            )
        except Exception as e:
            # Missing object: unknown age is fine (delete is idempotent).
            # Transient failures propagate so the sweep guard fails
            # closed instead of deleting possibly-fresh objects.
            if is_not_found_error(e):
                return None
            raise

    def _size_sync(self, path: str):
        blob = self._blob(path)
        blob.reload()
        size = getattr(blob, "size", None)
        return None if size is None else int(size)

    async def object_size_bytes(self, path: str):
        from ..io_types import is_not_found_error

        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                self._executor, self._size_sync, path
            )
        except Exception as e:
            if is_not_found_error(e):
                return None
            raise

    def close(self) -> None:
        self._executor.shutdown(wait=True)
