"""Google Cloud Storage plugin — the TPU-VM fast path.

TPU-native analog of reference torchsnapshot/storage_plugins/gcs.py:19-68.
TPU VMs sit next to GCS, so ``gs://`` is the north-star storage target
(BASELINE.json). The sync ``google-cloud-storage`` client is wrapped in a
thread executor (reference gcs.py:41,48-50); ranged reads map to
``blob.download_as_bytes(start=, end=)`` so resharding restores fetch only
overlapping byte ranges.
"""

import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from ..io_types import IOReq, StoragePlugin

_IO_THREADS = 8


class GCSStoragePlugin(StoragePlugin):
    def __init__(self, root: str, client: Optional[Any] = None) -> None:
        """``client`` injects a pre-built (or fake) ``storage.Client`` —
        the default constructs one from ambient credentials."""
        components = root.split("/", 1)
        if len(components) != 2:
            raise ValueError(
                f'GCS root must be a "bucket/path" pair, got "{root}".'
            )
        self.bucket_name, self.root = components
        if client is None:
            try:
                from google.cloud import storage  # type: ignore
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "GCS support requires the google-cloud-storage package."
                ) from e
            client = storage.Client()
        self._client = client
        self._bucket = self._client.bucket(self.bucket_name)
        self._executor = ThreadPoolExecutor(max_workers=_IO_THREADS)

    def _blob(self, path: str):
        return self._bucket.blob(f"{self.root}/{path}")

    def _write_sync(self, io_req: IOReq) -> None:
        if io_req.data is not None:
            import io as _io

            self._blob(io_req.path).upload_from_file(_io.BytesIO(io_req.data))
        else:
            io_req.buf.seek(0)
            self._blob(io_req.path).upload_from_file(io_req.buf)

    def _read_sync(self, io_req: IOReq) -> None:
        blob = self._blob(io_req.path)
        if io_req.byte_range is not None:
            start, end = io_req.byte_range
            data = blob.download_as_bytes(start=start, end=end - 1)
        else:
            data = blob.download_as_bytes()
        io_req.data = data

    async def write(self, io_req: IOReq) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._executor, self._write_sync, io_req)

    async def read(self, io_req: IOReq) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._executor, self._read_sync, io_req)

    async def delete(self, path: str) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._executor, self._blob(path).delete)

    def close(self) -> None:
        self._executor.shutdown(wait=True)
