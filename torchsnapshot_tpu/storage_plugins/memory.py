"""In-memory storage plugin (beyond reference parity).

Used for unit tests and as a staging target for async snapshots; also a
handy model of an object store (flat key → bytes, ranged reads).
"""

import asyncio
import time as _time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from .. import telemetry
from ..io_types import IOReq, StoragePlugin

# Shared-store -> mtimes registry. Keyed by id() with a strong reference
# to the store alongside (keeps the id from being recycled). LRU-bounded:
# holding every store ever constructed would pin all their payload bytes
# for the process lifetime; evicted stores degrade to age-unknown, which
# sweeps unconditionally — the pre-age-guard behavior.
_MTIMES_MAX_STORES = 64
_MTIMES_BY_STORE: "OrderedDict[int, Tuple[dict, Dict[str, float]]]" = (
    OrderedDict()
)


def _mtimes_for(store: dict) -> Dict[str, float]:
    entry = _MTIMES_BY_STORE.get(id(store))
    if entry is None or entry[0] is not store:
        entry = (store, {})
        _MTIMES_BY_STORE[id(store)] = entry
    _MTIMES_BY_STORE.move_to_end(id(store))
    while len(_MTIMES_BY_STORE) > _MTIMES_MAX_STORES:
        _MTIMES_BY_STORE.popitem(last=False)
    return entry[1]


class MemoryStoragePlugin(StoragePlugin):
    def __init__(
        self,
        store: Optional[Dict[str, bytes]] = None,
        prefix: str = "",
    ) -> None:
        # A shared dict may be passed in so multiple plugin instances
        # (e.g. simulated ranks) see one "bucket". ``prefix`` makes the
        # bucket hierarchical, like a real object store (bucket + key
        # prefix): ``memory://run/step-0`` and ``memory://run`` share the
        # "run" bucket, so listing the base prefix SEES the step's
        # objects — the property CheckpointManager.reconcile() and the
        # crash-consistency harness rely on (fs and cloud backends have
        # it natively).
        self.store: Dict[str, bytes] = store if store is not None else {}
        self.prefix = f"{prefix.rstrip('/')}/" if prefix else ""
        # mtimes are keyed off the SHARED store object, not per-instance:
        # sweep resolves a fresh plugin instance for the same bucket, and
        # a per-instance dict would make its age guard a silent no-op.
        self._mtimes = _mtimes_for(self.store)
        self._lock = asyncio.Lock()

    def _key(self, path: str) -> str:
        return self.prefix + path

    async def write(self, io_req: IOReq) -> None:
        import time

        t0 = _time.monotonic()
        payload = io_req.data if io_req.data is not None else io_req.buf.getbuffer()
        async with self._lock:
            self.store[self._key(io_req.path)] = bytes(payload)
            self._mtimes[self._key(io_req.path)] = time.time()
        telemetry.record_storage_op(
            "memory", "write", _time.monotonic() - t0, len(payload)
        )

    async def read(self, io_req: IOReq) -> None:
        t0 = _time.monotonic()
        async with self._lock:
            try:
                data = self.store[self._key(io_req.path)]
            except KeyError:
                # Speak the same not-found dialect as the fs plugin so the
                # not-found classifier needs no backend-specific cases.
                raise FileNotFoundError(io_req.path) from None
        if io_req.byte_range is not None:
            start, end = io_req.byte_range
            data = data[start:end]
        io_req.data = data
        telemetry.record_storage_op(
            "memory", "read", _time.monotonic() - t0, len(data)
        )

    async def delete(self, path: str) -> None:
        t0 = _time.monotonic()
        async with self._lock:
            key = self._key(path)
            if key not in self.store:
                raise FileNotFoundError(path)
            del self.store[key]
            self._mtimes.pop(key, None)
        telemetry.record_storage_op(
            "memory", "delete", _time.monotonic() - t0
        )

    async def list_prefix(self, prefix: str):
        full = self._key(prefix)
        async with self._lock:
            return [
                k[len(self.prefix):]
                for k in self.store
                if k.startswith(full)
            ]

    async def object_age_s(self, path: str):
        import time

        async with self._lock:
            mtime = self._mtimes.get(self._key(path))
        return None if mtime is None else max(0.0, time.time() - mtime)

    async def object_size_bytes(self, path: str):
        async with self._lock:
            data = self.store.get(self._key(path))
        return None if data is None else len(data)

    def close(self) -> None:
        pass
