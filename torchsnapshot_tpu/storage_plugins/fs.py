"""Local-filesystem storage plugin.

TPU-native analog of reference torchsnapshot/storage_plugins/fs.py:19-45.
Uses ``asyncio.to_thread``-style executor offloading (via
``loop.run_in_executor``) instead of aiofiles so large writes release the
GIL in one ``file.write`` call; parent-directory creation is cached
(reference fs.py:22,27-30). Supports ranged reads for partial chunk
fetches during resharding.
"""

import asyncio
import errno
import os
import threading
import time
from typing import Optional, Set, Tuple

from .. import telemetry
from ..io_types import IOReq, StoragePlugin, emit_storage_op


def _payload_nbytes(io_req: IOReq) -> int:
    if io_req.data is not None:
        return len(io_req.data)
    return io_req.buf.getbuffer().nbytes


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError as e:
        # Some filesystems (FUSE, 9p, network mounts) reject fsync on a
        # directory fd; degrade to rename-only semantics there rather
        # than failing a write whose data is already durable.
        if e.errno not in (errno.EINVAL, errno.ENOTSUP):
            raise
    finally:
        os.close(fd)


class FSStoragePlugin(StoragePlugin):
    # Local disks lose throughput to writeback contention under parallel
    # write streams (measured ~2.5x slower at 4+ writers on cloud-VM
    # disks); two keeps the device busy across file boundaries without
    # thrashing. Reads keep the default fan-out (queue depth helps).
    max_write_concurrency = 2

    def __init__(self, root: str) -> None:
        self.root = root
        self._dir_cache: Set[str] = set()
        # Directories holding renamed-in data objects whose dirents have
        # not been fsynced yet. Data-object writes only record their
        # directory here; the fsyncs are paid once, at the next publish
        # point (see _write_sync), instead of once per object.
        self._dirty_dirs: Set[str] = set()
        self._dirty_lock = threading.Lock()

    def _prepare_dir(self, path: str) -> None:
        dir_path = os.path.dirname(os.path.join(self.root, path))
        if not dir_path or dir_path in self._dir_cache:
            return
        # Record which ancestors are about to be created BEFORE makedirs —
        # including the root itself and anything above it makedirs will
        # conjure — because afterwards there is no telling created from
        # pre-existing. The new dirents must be durable: a crash could
        # otherwise drop a directory whose (fsynced) files committed
        # metadata already references. Each created dir's parent is
        # fsynced once, top-downward; the cache makes it once per
        # directory lifetime.
        created = []
        d = dir_path
        while d and d not in self._dir_cache and not os.path.isdir(d):
            created.append(d)
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        os.makedirs(dir_path, exist_ok=True)
        for d in reversed(created):
            _fsync_dir(os.path.dirname(d))
            self._dir_cache.add(d)
        self._dir_cache.add(dir_path)

    @staticmethod
    def _is_publish_point(path: str) -> bool:
        """A write that makes previously written objects *referenced*:
        snapshot metadata, commit/step markers — everything the protocol
        keeps under dot-prefixed names. Data objects never are."""
        first = path.split("/", 1)[0]
        return first.startswith(".") or os.path.basename(path).startswith(".")

    def _flush_dirty_dirs(self) -> None:
        with self._dirty_lock:
            dirty, self._dirty_dirs = self._dirty_dirs, set()
        for d in sorted(dirty):
            _fsync_dir(d)

    def ensure_durable(self) -> None:
        # Commit-protocol hook: ranks whose commit route writes no
        # dot-prefixed marker of their own (the KV manifest-gather path)
        # call this before contributing to the commit collective, so
        # their deferred dirents are durable before rank 0 can publish
        # metadata referencing them.
        self._flush_dirty_dirs()

    @staticmethod
    def _writer_alive(pid_str: str) -> bool:
        """Whether the process that named a ``.tmp<pid>`` file still
        runs ON THIS HOST. EPERM means alive (another user's process);
        an unparseable suffix reads as alive — fail toward keeping."""
        if not (pid_str.isascii() and pid_str.isdigit()):
            return True
        try:
            os.kill(int(pid_str), 0)
        except ProcessLookupError:
            return False
        # EPERM (someone else's live process), OverflowError (a numeric
        # suffix past C long — not a real pid), and friends: keep.
        except Exception:  # snapcheck: disable=swallowed-exception -- fails toward keeping
            return True
        return True

    @classmethod
    def _clean_stale_tmp(cls, full: str, own_tmp: str) -> None:
        """Remove torn ``<name>.tmp<pid>`` siblings a CRASHED process
        left for the object about to be (re)written. Stale means the
        writer pid is dead: a live concurrent writer's in-flight tmp
        (e.g. an offline reconcile adopting the marker an async
        finalize is writing right now) must survive, or its rename
        fails with a non-retryable FileNotFoundError — before this
        cleanup existed, concurrent same-path writers were safe under
        last-rename-wins, and they must stay safe. Pid liveness is a
        same-host test; a shared-fs writer from another host may look
        dead — but then BOTH writers are re-driving the same recovery
        path, and the survivor rewrites the object anyway. Only publish
        points pay this (small directories, and they are the paths
        re-driven after a crash — markers, tombstones, metadata);
        payload debris in step directories is reclaimed by sweeps."""
        d = os.path.dirname(full)
        prefix = os.path.basename(full) + ".tmp"
        own = os.path.basename(own_tmp)
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return
        for name in names:
            if (
                name.startswith(prefix)
                and name != own
                and not cls._writer_alive(name[len(prefix):])
            ):
                try:
                    os.remove(os.path.join(d, name))
                except FileNotFoundError:
                    pass  # concurrent cleanup won the race: already gone

    def _write_sync(self, io_req: IOReq) -> None:
        self._prepare_dir(io_req.path)
        full = os.path.join(self.root, io_req.path)
        publish = self._is_publish_point(io_req.path)
        if publish:
            # Every dirent this marker/metadata may reference must be
            # durable BEFORE the publishing rename can reach disk —
            # writeback gives no ordering on its own.
            self._flush_dirty_dirs()
        # Write to a temp name then rename for per-object atomicity (the
        # reference has no partial-write protection; POSIX rename is free).
        tmp = f"{full}.tmp{os.getpid()}"
        if publish:
            self._clean_stale_tmp(full, tmp)
        payload = io_req.data if io_req.data is not None else io_req.buf.getbuffer()
        # Op-granular boundaries (faultline): a hook may raise here to
        # model a crash BETWEEN the sub-steps of the durability protocol
        # — after the tmp data landed but before it was fsynced, after
        # the fsync but before the rename published it, and after the
        # rename but before the dirent became durable.
        emit_storage_op("fs.write.tmp", io_req.path)
        with open(tmp, "wb") as f:
            f.write(payload)
            emit_storage_op("fs.write.fsync", io_req.path)
            # Data must be durable BEFORE the rename publishes the final
            # name (snapcheck durability-order): a crash shortly after an
            # un-fsynced rename can leave the published name pointing at
            # torn/empty data that the metadata (written later) already
            # references.
            f.flush()
            os.fsync(f.fileno())
        emit_storage_op("fs.write.rename", io_req.path)
        os.replace(tmp, full)
        emit_storage_op("fs.write.dirsync", io_req.path)
        # The rename's dirent must be durable too — immediately for a
        # publish point (it IS the commit), deferred to the next publish
        # point for data objects (nothing references them until then, and
        # one fsync per directory then covers every object in it).
        if publish:
            _fsync_dir(os.path.dirname(full))
        else:
            with self._dirty_lock:
                self._dirty_dirs.add(os.path.dirname(full))

    def _read_sync(self, io_req: IOReq) -> None:
        full = os.path.join(self.root, io_req.path)
        with open(full, "rb") as f:
            if io_req.byte_range is not None:
                start, end = io_req.byte_range
                f.seek(start)
                payload = f.read(end - start)
            else:
                payload = f.read()
        # Return via `data`: zero-copy for consumers. Callers that want the
        # BytesIO interface read io_req.data themselves (wrapping here
        # would memcpy every payload).
        io_req.data = payload

    async def write(self, io_req: IOReq) -> None:
        loop = asyncio.get_running_loop()
        nbytes = _payload_nbytes(io_req)
        t0 = time.monotonic()
        await loop.run_in_executor(None, self._write_sync, io_req)
        telemetry.record_storage_op(
            "fs", "write", time.monotonic() - t0, nbytes
        )

    async def read(self, io_req: IOReq) -> None:
        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        await loop.run_in_executor(None, self._read_sync, io_req)
        telemetry.record_storage_op(
            "fs", "read", time.monotonic() - t0, _payload_nbytes(io_req)
        )

    async def delete(self, path: str) -> None:
        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        await loop.run_in_executor(None, os.remove, os.path.join(self.root, path))
        telemetry.record_storage_op("fs", "delete", time.monotonic() - t0)

    def _list_sync(self, prefix: str):
        # Object-store semantics: a pure string prefix over relative
        # paths. Walk only the plugin root — never its parent — so a
        # sweep can only ever see this snapshot's own objects (walking
        # dirname(root) for prefix="" would enumerate, and let sweep
        # delete, sibling snapshots). The walk starts at the deepest
        # directory the prefix names: listing ".steps/" over a base
        # holding thousands of payload files must cost O(markers), not
        # O(all objects) — CheckpointManager lists markers on every
        # save/restore.
        found = []
        walk_dir = self.root
        rel_dir = ""
        if "/" in prefix:
            rel_dir = prefix.rsplit("/", 1)[0]
            walk_dir = os.path.join(self.root, rel_dir)
        if not os.path.isdir(walk_dir):
            return found
        for dirpath, _, filenames in os.walk(walk_dir):
            for name in filenames:
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                if rel.startswith(prefix):
                    found.append(rel)
        return found

    async def list_prefix(self, prefix: str):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._list_sync, prefix)

    async def object_age_s(self, path: str) -> Optional[float]:
        import time

        try:
            st = os.stat(os.path.join(self.root, path))
        except FileNotFoundError:
            return None  # vanished: deleting a missing object is a no-op
        # Other OSErrors (stale NFS handle, perms) propagate: the sweep
        # age guard fails closed on them instead of sweeping blind.
        return max(0.0, time.time() - st.st_mtime)

    async def object_size_bytes(self, path: str) -> Optional[int]:
        try:
            return os.stat(os.path.join(self.root, path)).st_size
        except FileNotFoundError:
            return None

    def close(self) -> None:
        # Belt-and-braces: a plugin retired without ever hitting a
        # publish point (e.g. an aborted take) still leaves every dirent
        # it created durable.
        self._flush_dirty_dirs()
