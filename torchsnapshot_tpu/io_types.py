"""IO interfaces shared by the scheduler, preparers, and storage plugins.

TPU-native analog of reference torchsnapshot/io_types.py:15-71.

- ``BufferStager`` — produces the payload for one storage write; staging is
  where device→host (HBM→RAM) transfer and serialization happen, off the
  critical path inside a thread executor.
- ``BufferConsumer`` — absorbs the payload of one storage read; consuming
  is where deserialization and host→device placement happen.
- ``WriteReq``/``ReadReq`` pair a storage path with a stager/consumer.
- ``IOReq`` is the unit handed to a ``StoragePlugin``.
- ``StoragePlugin`` — async write/read/delete + sync close; concrete
  backends live in ``torchsnapshot_tpu.storage_plugins``.
"""

import abc
import asyncio
import io
import logging
import os
import random
import time
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Union

from . import telemetry, tracing
from .telemetry import metrics as _metric_names

BufferType = Union[bytes, bytearray, memoryview]

logger = logging.getLogger(__name__)


# --------------------------------------------------------- storage-op hooks
#
# Observation seam for every storage-op boundary. Registered hooks receive
# ``(op, path)`` right before the op executes: plugin-level ops are emitted
# by wrappers (faultline's FaultPlugin emits "write"/"read"/"delete"/...),
# and backends with multi-step durability protocols emit their SUB-step
# boundaries too (fs.py emits "fs.write.tmp" → "fs.write.fsync" →
# "fs.write.rename" → "fs.write.dirsync"), so a fault-injection harness can
# place a crash BETWEEN the steps of a single logical write. The snapserve
# client announces every read-service RPC attempt as "snapserve.request"
# BEFORE touching the network, which is where kill_server/slow_server
# schedules hook in deterministically. A hook may
# raise — the exception propagates into the op exactly where a real failure
# (or process death) would strike. Zero cost when no hook is registered
# (one truthiness check per boundary).

_STORAGE_OP_HOOKS: List[Callable[[str, str], None]] = []


def add_storage_op_hook(hook: Callable[[str, str], None]) -> None:
    """Register ``hook(op, path)`` to observe every storage-op boundary."""
    _STORAGE_OP_HOOKS.append(hook)


def remove_storage_op_hook(hook: Callable[[str, str], None]) -> None:
    """Unregister a hook added by :func:`add_storage_op_hook`."""
    _STORAGE_OP_HOOKS.remove(hook)


def emit_storage_op(op: str, path: str) -> None:
    """Announce a storage-op boundary to registered hooks (may raise)."""
    if _STORAGE_OP_HOOKS:
        for hook in list(_STORAGE_OP_HOOKS):
            hook(op, path)


def _code_attr_http_status(exc: BaseException) -> Optional[int]:
    """The exception's ``.code`` as an int — but only when the exception
    plausibly comes from an HTTP client library. ``code`` is an
    overloaded attribute name (grpc status enums, library-specific error
    codes), so a bare integer match is not evidence of an HTTP status
    (ADVICE r3): misclassifying a retryable failure as a deterministic
    404/416 makes the retry layer give up and pollers misread errors.
    The gate: an ``errors``/``response`` attribute (google.api_core
    carries both) or an HTTP-flavored defining module."""
    code = getattr(exc, "code", None)
    if code is None:
        return None
    if not (
        hasattr(exc, "errors")
        or getattr(exc, "response", None) is not None
        or any(
            tok in type(exc).__module__
            for tok in ("google", "http", "urllib", "requests", "aiohttp")
        )
    ):
        return None
    try:
        return int(code)
    except (TypeError, ValueError):
        return None


def is_not_found_error(exc: BaseException) -> bool:
    """Whether a storage failure means "object does not exist".

    fs and memory plugins raise FileNotFoundError; cloud client not-found
    exception classes carry NotFound/NoSuchKey in their type name or a
    structured 404 status code. Not-found is deterministic: pollers treat
    it as "not yet", and the retry layer never retries it. Classification
    is structural (exception type + status-code attributes), never by
    message substring: a transient proxy error whose HTML body happens to
    contain "404"/"Not Found" (or a request id containing "404") must NOT
    be classified as a missing object — that would skip retries and make
    async-commit polling spin until timeout. Deliberately narrow — a
    stray KeyError from a plugin's internals is a bug to surface, not a
    missing object.
    """
    if isinstance(exc, FileNotFoundError):
        return True
    # Cloud-client exception classes: google.api_core.exceptions.NotFound,
    # botocore's NoSuchKey ClientError subclass, etc.
    for klass in type(exc).__mro__:
        if klass.__name__ in ("NotFound", "NoSuchKey", "NoSuchBucket"):
            return True
    # Structured status codes. google-api-core carries `.code` (int or
    # http.HTTPStatus); botocore ClientError carries
    # `.response["ResponseMetadata"]["HTTPStatusCode"]` and
    # `.response["Error"]["Code"]`.
    if _code_attr_http_status(exc) == 404:
        return True
    response = getattr(exc, "response", None)
    if isinstance(response, dict):
        error_code = response.get("Error", {}).get("Code")
        if error_code in ("404", "NoSuchKey", "NotFound", "NoSuchBucket"):
            return True
        status = response.get("ResponseMetadata", {}).get("HTTPStatusCode")
        if status == 404:
            return True
    return False


def is_range_not_satisfiable_error(exc: BaseException) -> bool:
    """Whether a storage failure means "requested byte range starts at or
    past the end of the object".

    GCS raises 416 RequestRangeNotSatisfiable and S3 raises InvalidRange
    (HTTP 416) when a ranged GET's start offset is >= the object length.
    ``verify()`` probes one byte past the expected end of large objects to
    detect trailing garbage — on these backends a *healthy* object answers
    that probe with 416, so the probe must classify it as "object ends
    exactly where the manifest implies", not as corruption. Like
    not-found, 416 is deterministic: the retry layer must not retry it.
    Classification is structural (exception type + status-code
    attributes), never by message substring — same rationale as
    :func:`is_not_found_error`.
    """
    for klass in type(exc).__mro__:
        if klass.__name__ in (
            "RequestRangeNotSatisfiable",  # google.api_core.exceptions
            "RequestedRangeNotSatisfiable",  # werkzeug/HTTP libs spelling
            "InvalidRange",
        ):
            return True
    response = getattr(exc, "response", None)
    if isinstance(response, dict):
        if response.get("Error", {}).get("Code") in ("416", "InvalidRange"):
            return True
        if response.get("ResponseMetadata", {}).get("HTTPStatusCode") == 416:
            return True
    return _code_attr_http_status(exc) == 416


# Storage-op retry policy (beyond reference parity: the reference has no
# retries anywhere — one transient object-store 5xx aborts the whole
# snapshot, SURVEY §5). Writes are whole-object puts, reads are (ranged)
# gets, deletes are idempotent — all safe to retry.
#
# Backoff is decorrelated-jitter (each delay drawn uniformly from
# [initial, prev*3], capped): pure exponential backoff keeps every rank
# of a pod on the SAME schedule, so after a shared-storage brownout all
# ranks re-hammer the recovering service in lockstep at exactly the
# moments it tries to come back. Jitter spreads the herd; the per-delay
# cap bounds any single wait; the elapsed budget bounds the whole retry
# episode so a permanently-failing op cannot pin a commit for
# attempts × cap seconds.
_STORAGE_RETRIES_ENV_VAR = "TPUSNAPSHOT_STORAGE_RETRIES"
_DEFAULT_STORAGE_ATTEMPTS = 3
_RETRY_BACKOFF_INITIAL_S = 0.25
_RETRY_DELAY_CAP_ENV_VAR = "TPUSNAPSHOT_STORAGE_RETRY_CAP_S"
_DEFAULT_RETRY_DELAY_CAP_S = 20.0
_RETRY_BUDGET_ENV_VAR = "TPUSNAPSHOT_STORAGE_RETRY_BUDGET_S"
_DEFAULT_RETRY_BUDGET_S = 600.0

# Deliberately unseeded: the whole point is that concurrent ranks draw
# DIFFERENT delays. Never feeds serialization or cross-rank decisions.
_retry_rng = random.Random()


def _storage_attempts() -> int:
    from .utils.env import env_int

    return 1 + max(
        0, env_int(_STORAGE_RETRIES_ENV_VAR, _DEFAULT_STORAGE_ATTEMPTS - 1)
    )


async def retry_storage_op(make_coro, desc: str):
    """Run ``await make_coro()`` with capped, decorrelated-jitter backoff
    on transient failures, under an overall elapsed budget
    (``TPUSNAPSHOT_STORAGE_RETRY_BUDGET_S``). ``make_coro`` is a zero-arg
    callable returning a fresh coroutine (a coroutine object cannot be
    awaited twice)."""
    from .utils.env import env_float

    attempts = _storage_attempts()
    cap = env_float(_RETRY_DELAY_CAP_ENV_VAR, _DEFAULT_RETRY_DELAY_CAP_S)
    if cap <= 0:
        cap = _DEFAULT_RETRY_DELAY_CAP_S
    # A cap below the initial backoff wins: the knob must keep meaning
    # "no single wait exceeds this" across its whole range, so the
    # jitter floor drops to the cap rather than the cap rising to the
    # floor (which would silently ignore sub-initial settings).
    floor = min(_RETRY_BACKOFF_INITIAL_S, cap)
    budget_s = env_float(_RETRY_BUDGET_ENV_VAR, _DEFAULT_RETRY_BUDGET_S)
    start = time.monotonic()
    prev_delay = floor
    for attempt in range(1, attempts + 1):
        attempt_start = time.monotonic()
        try:
            return await make_coro()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if (
                is_not_found_error(e)
                or is_range_not_satisfiable_error(e)
                or attempt == attempts
            ):
                raise
            # Decorrelated jitter: uniform over [floor, prev*3], capped.
            delay = min(
                cap,
                _retry_rng.uniform(floor, max(floor, prev_delay * 3.0)),
            )
            prev_delay = delay
            elapsed = time.monotonic() - start
            if elapsed + delay > budget_s:
                logger.warning(
                    f"Storage op {desc} failed (attempt {attempt}/"
                    f"{attempts}): {e!r}; retry budget exhausted "
                    f"({elapsed:.1f}s elapsed of {budget_s:g}s) — giving up"
                )
                raise
            # Always-on retry accounting next to the (tracing-gated)
            # instant, so instant-count == counter-count whenever a
            # trace is being recorded (tests/test_telemetry.py pins
            # this). The op *type* labels the counter — the full desc
            # carries a path, and paths are unbounded-cardinality.
            op_type = desc.split("(", 1)[0]
            telemetry.counter(
                _metric_names.STORAGE_RETRIES, op=op_type
            ).inc()
            telemetry.counter(
                _metric_names.STORAGE_RETRY_BACKOFF, op=op_type
            ).inc(delay)
            tracing.instant(
                "storage_retry",
                op=desc,
                attempt=attempt,
                attempt_s=round(time.monotonic() - attempt_start, 4),
                delay_s=round(delay, 4),
                error=type(e).__name__,
            )
            logger.warning(
                f"Storage op {desc} failed (attempt {attempt}/{attempts}): "
                f"{e!r}; retrying in {delay:.2f}s"
            )
            await asyncio.sleep(delay)


class BufferStager(abc.ABC):
    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        """Produce the payload bytes (device→host copy + serialize)."""

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory charged against the budget while staging."""


class BufferConsumer(abc.ABC):
    @abc.abstractmethod
    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        """Absorb the payload bytes (deserialize + host→device copy)."""

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory charged against the budget while consuming."""

    def get_deferred_cost_bytes(self) -> int:
        """The portion of :meth:`get_consuming_cost_bytes` whose backing
        allocation outlives this consumer's ``consume_buffer`` call (e.g.
        a split read's shared assembly buffer, freed only when the LAST
        sub-read lands). The scheduler refunds this portion through the
        releaser callback instead of at consume-task completion, so
        several concurrent split reads cannot overrun the budget by the
        sum of their object sizes. 0 for ordinary consumers."""
        return 0

    def set_cost_releaser(self, release: Callable[[int], None]) -> None:
        """Receive the scheduler's budget-release callback. Only called
        when :meth:`get_deferred_cost_bytes` returns non-zero; the
        consumer must invoke ``release(n)`` exactly once, when the
        deferred allocation is actually freed."""

    def get_device_cost_bytes(self) -> int:
        """Device (HBM) bytes this consume deposits that outlive the
        consume call (streamed chunks awaiting assembly). The scheduler
        gates consume DISPATCH on a device-side budget so concurrent
        large restores cannot transiently exceed device memory. 0 for
        consumers that stay on host."""
        return 0

    def set_device_cost_releaser(
        self, release: Callable[[int], None]
    ) -> None:
        """Receive the device-budget release callback. Only called when
        :meth:`get_device_cost_bytes` returns non-zero; the consumer (or
        the assembly step it feeds) must invoke ``release(n)`` once the
        deposited device bytes are freed."""


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    # Byte range within the stored object ([start, end)); None = whole
    # object. Enables partial reads of large chunks during resharding.
    byte_range: Optional[tuple] = None


@dataclass
class IOReq:
    path: str
    buf: io.BytesIO = field(default_factory=io.BytesIO)
    byte_range: Optional[tuple] = None
    # Zero-copy payload. Writes: when set, plugins write `data` directly
    # instead of draining `buf`. Reads: plugins that can, return the
    # payload here instead of memcpy-ing it into `buf`.
    data: Optional[BufferType] = None


def io_payload(io_req: "IOReq") -> BufferType:
    """The payload of a completed IOReq, whichever field carries it."""
    if io_req.data is not None:
        return io_req.data
    return io_req.buf.getbuffer()


class StoragePlugin(abc.ABC):
    # How many concurrent IO ops this backend profits from, read by the
    # scheduler as its per-pipeline concurrency caps. Object stores
    # (GCS/S3) want many parallel streams both ways; a local disk degrades
    # under parallel *writeback* (the fs plugin lowers the write cap) while
    # parallel reads still help (page cache / SSD queue depth).
    max_write_concurrency: int = 16
    max_read_concurrency: int = 16

    @abc.abstractmethod
    async def write(self, io_req: IOReq) -> None:
        ...

    @abc.abstractmethod
    async def read(self, io_req: IOReq) -> None:
        ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        ...

    async def list_prefix(self, prefix: str):
        """List stored object paths under ``prefix`` (same relative
        namespace as write/read/delete), or None when this backend cannot
        enumerate objects — sweep-style GC then degrades to
        referenced-objects-only deletion."""
        return None

    async def object_age_s(self, path: str) -> Optional[float]:
        """Seconds since ``path`` was last written, or None when the
        backend cannot tell. Sweep-style GC uses this to spare objects a
        concurrent in-progress take wrote moments ago; None means the
        object is swept unconditionally (pre-age-guard behavior)."""
        return None

    async def object_size_bytes(self, path: str) -> Optional[int]:
        """Stored size of ``path`` in bytes (a stat/HEAD, not a read), or
        None when the backend cannot tell. ``copy_to`` admits object
        entries — whose size the manifest does not record — against its
        host-memory budget with this; unknown sizes degrade to
        copy-alone admission."""
        return None

    def ensure_durable(self) -> None:
        """Make everything written through this plugin so far
        crash-durable. The commit protocol calls this on EVERY rank
        before the collective that leads to metadata publication, so a
        backend may defer per-object durability work (e.g. directory
        fsyncs) and settle it here in one batch. Default no-op: object
        stores are durable on write-ack."""

    @abc.abstractmethod
    def close(self) -> None:
        ...


class RetryingStoragePlugin(StoragePlugin):
    """Decorator adding transparent retries to every op of a plugin.

    Applied by ``url_to_storage_plugin`` so *all* storage traffic —
    payloads, the metadata commit, async-completion markers, random-access
    reads, deletes — shares one retry policy. A failed read attempt may
    have partially filled the request buffer, so reads reset it per
    attempt. Not-found propagates immediately (see
    :func:`is_not_found_error`).
    """

    def __init__(self, inner: StoragePlugin) -> None:
        self._inner = inner
        # Scheduler concurrency caps pass through to the real backend's.
        self.max_write_concurrency = inner.max_write_concurrency
        self.max_read_concurrency = inner.max_read_concurrency

    async def write(self, io_req: IOReq) -> None:
        await retry_storage_op(
            lambda: self._inner.write(io_req), f"write({io_req.path})"
        )

    async def read(self, io_req: IOReq) -> None:
        async def _attempt() -> None:
            io_req.buf.seek(0)
            io_req.buf.truncate()
            io_req.data = None
            await self._inner.read(io_req)

        await retry_storage_op(_attempt, f"read({io_req.path})")

    async def delete(self, path: str) -> None:
        await retry_storage_op(
            lambda: self._inner.delete(path), f"delete({path})"
        )

    async def list_prefix(self, prefix: str):
        return await retry_storage_op(
            lambda: self._inner.list_prefix(prefix), f"list({prefix})"
        )

    async def object_age_s(self, path: str) -> Optional[float]:
        # Retried like reads; a final failure propagates so the sweep
        # age guard can fail closed (spare the object) instead of
        # treating a throttled probe as "unknown age, sweep it".
        return await retry_storage_op(
            lambda: self._inner.object_age_s(path), f"age({path})"
        )

    async def object_size_bytes(self, path: str) -> Optional[int]:
        return await retry_storage_op(
            lambda: self._inner.object_size_bytes(path), f"size({path})"
        )

    def ensure_durable(self) -> None:
        self._inner.ensure_durable()

    def close(self) -> None:
        self._inner.close()
