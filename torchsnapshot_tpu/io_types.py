"""IO interfaces shared by the scheduler, preparers, and storage plugins.

TPU-native analog of reference torchsnapshot/io_types.py:15-71.

- ``BufferStager`` — produces the payload for one storage write; staging is
  where device→host (HBM→RAM) transfer and serialization happen, off the
  critical path inside a thread executor.
- ``BufferConsumer`` — absorbs the payload of one storage read; consuming
  is where deserialization and host→device placement happen.
- ``WriteReq``/``ReadReq`` pair a storage path with a stager/consumer.
- ``IOReq`` is the unit handed to a ``StoragePlugin``.
- ``StoragePlugin`` — async write/read/delete + sync close; concrete
  backends live in ``torchsnapshot_tpu.storage_plugins``.
"""

import abc
import io
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import Optional, Union

BufferType = Union[bytes, bytearray, memoryview]


class BufferStager(abc.ABC):
    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        """Produce the payload bytes (device→host copy + serialize)."""

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory charged against the budget while staging."""


class BufferConsumer(abc.ABC):
    @abc.abstractmethod
    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        """Absorb the payload bytes (deserialize + host→device copy)."""

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory charged against the budget while consuming."""


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    # Byte range within the stored object ([start, end)); None = whole
    # object. Enables partial reads of large chunks during resharding.
    byte_range: Optional[tuple] = None


@dataclass
class IOReq:
    path: str
    buf: io.BytesIO = field(default_factory=io.BytesIO)
    byte_range: Optional[tuple] = None
    # Zero-copy payload. Writes: when set, plugins write `data` directly
    # instead of draining `buf`. Reads: plugins that can, return the
    # payload here instead of memcpy-ing it into `buf`.
    data: Optional[BufferType] = None


def io_payload(io_req: "IOReq") -> BufferType:
    """The payload of a completed IOReq, whichever field carries it."""
    if io_req.data is not None:
        return io_req.data
    return io_req.buf.getbuffer()


class StoragePlugin(abc.ABC):
    # How many concurrent IO ops this backend profits from, read by the
    # scheduler as its per-pipeline concurrency caps. Object stores
    # (GCS/S3) want many parallel streams both ways; a local disk degrades
    # under parallel *writeback* (the fs plugin lowers the write cap) while
    # parallel reads still help (page cache / SSD queue depth).
    max_write_concurrency: int = 16
    max_read_concurrency: int = 16

    @abc.abstractmethod
    async def write(self, io_req: IOReq) -> None:
        ...

    @abc.abstractmethod
    async def read(self, io_req: IOReq) -> None:
        ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        ...

    @abc.abstractmethod
    def close(self) -> None:
        ...
