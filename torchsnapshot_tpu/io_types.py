"""IO interfaces shared by the scheduler, preparers, and storage plugins.

TPU-native analog of reference torchsnapshot/io_types.py:15-71.

- ``BufferStager`` — produces the payload for one storage write; staging is
  where device→host (HBM→RAM) transfer and serialization happen, off the
  critical path inside a thread executor.
- ``BufferConsumer`` — absorbs the payload of one storage read; consuming
  is where deserialization and host→device placement happen.
- ``WriteReq``/``ReadReq`` pair a storage path with a stager/consumer.
- ``IOReq`` is the unit handed to a ``StoragePlugin``.
- ``StoragePlugin`` — async write/read/delete + sync close; concrete
  backends live in ``torchsnapshot_tpu.storage_plugins``.
"""

import abc
import io
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import Optional, Union

BufferType = Union[bytes, bytearray, memoryview]


class BufferStager(abc.ABC):
    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        """Produce the payload bytes (device→host copy + serialize)."""

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory charged against the budget while staging."""


class BufferConsumer(abc.ABC):
    @abc.abstractmethod
    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        """Absorb the payload bytes (deserialize + host→device copy)."""

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory charged against the budget while consuming."""


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    # Byte range within the stored object ([start, end)); None = whole
    # object. Enables partial reads of large chunks during resharding.
    byte_range: Optional[tuple] = None


@dataclass
class IOReq:
    path: str
    buf: io.BytesIO = field(default_factory=io.BytesIO)
    byte_range: Optional[tuple] = None
    # Write-path payload. When set, plugins write `data` directly (zero-copy
    # from the staged host buffer) instead of draining `buf`.
    data: Optional[BufferType] = None


class StoragePlugin(abc.ABC):
    @abc.abstractmethod
    async def write(self, io_req: IOReq) -> None:
        ...

    @abc.abstractmethod
    async def read(self, io_req: IOReq) -> None:
        ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        ...

    @abc.abstractmethod
    def close(self) -> None:
        ...
