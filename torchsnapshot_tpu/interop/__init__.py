"""Interop: migrate between reference torchsnapshot snapshots/statefuls
and this framework.

Two migration paths for users switching from the reference
(mary-lau/torchsnapshot):

- :class:`ReferenceSnapshotReader` — read a snapshot **written by the
  reference library** (YAML ``.snapshot_metadata`` + ``torch_save``
  payloads; reference manifest.py:14-154, io_preparer.py:196-242) and
  restore it into JAX statefuls or convert it to this framework's native
  format.
- :class:`TorchStateful` — wrap a torch-style stateful (``nn.Module``,
  optimizer, anything with ``state_dict``/``load_state_dict`` holding CPU
  ``torch.Tensor`` leaves) so it snapshots/restores through this
  framework bit-exactly, bfloat16 included.

A third path covers the JAX ecosystem's incumbent checkpointer:
``interop.orbax_format.convert_from_orbax`` / ``convert_to_orbax``
migrate between orbax ``PyTreeCheckpointer`` checkpoints and native
snapshots (see that module).

torch and orbax are optional dependencies of this subpackage only; the
core framework never imports them. ``reference_writer.convert_back``
(the reverse torch migration) likewise lives in its own module.
"""

from .reference_format import ReferenceSnapshotReader
from .torch_stateful import TorchStateful, numpy_to_torch_tree, torch_to_numpy_tree

__all__ = [
    "ReferenceSnapshotReader",
    "TorchStateful",
    "numpy_to_torch_tree",
    "torch_to_numpy_tree",
]
