"""Write-side reference-format interop: export a NATIVE snapshot as a
snapshot the **reference** torchsnapshot can restore (VERDICT r2 ask #8 —
migration must be reversible; a torch shop rolling back after a trial
migration needs a path home).

Emitted format (all cited from the reference):
- ``.snapshot_metadata`` YAML ``{version, world_size, manifest}``
  (manifest.py:111-118) with entry dicts exactly as the reference's
  ``SnapshotMetadata.from_yaml`` reconstructs them (manifest.py:120-154);
- one ``torch.save`` blob per leaf (io_preparer.py:218, 279), under the
  reference's location policy ``<rank>/…`` / ``replicated/…``
  (io_preparer.py:336-342); serializer is always ``"torch_save"``
  (io_preparer.py:250, 317).

Mapping notes (lossy in documented, deliberate ways):
- Sharded arrays are ASSEMBLED DENSE and emitted as replicated Tensor
  entries — every reference rank can restore them into a plain tensor,
  but the sharded layout itself is not round-tripped (the reference's
  ShardedTensor restore path requires a live ShardedTensor in the target
  state dict, which a migrating-back app no longer has).
- Tuples flatten as lists (the reference has no tuple entry).
- Primitive entries (beyond-parity inline scalars) become reference
  object entries with ``torch.save`` payloads.
- bf16 and other ml_dtypes arrays convert bitwise via the same
  bit-reinterpretation used on the read side (_torch_convert).

The exporter is collective-free and single-process: run it from one rank
or an offline tool. Values are materialized to host memory one at a time
(peak RAM ~ largest single leaf, plus the dense size of the largest
sharded array).
"""

import asyncio
import io
import logging
from typing import Any, Dict, Tuple

import numpy as np
import yaml

from ..io_types import IOReq
from ..manifest import (
    ArrayEntry,
    DictEntry,
    ListEntry,
    ObjectEntry,
    OrderedDictEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
    TupleEntry,
    get_available_entries,
)
from ..io_preparer import prepare_read
from ..scheduler import execute_read_reqs, get_local_memory_budget_bytes
from ..storage_plugin import url_to_storage_plugin
from ._torch_convert import _require_torch, numpy_to_torch_tensor

logger = logging.getLogger(__name__)

_METADATA_FNAME = ".snapshot_metadata"
_REFERENCE_VERSION = "0.0.3"  # reference version.py:17


def _torch_save_bytes(obj: Any) -> bytes:
    torch = _require_torch()
    buf = io.BytesIO()
    torch.save(obj, buf)
    return buf.getvalue()


def _to_torch_payload_and_dtype(value: np.ndarray) -> Tuple[bytes, str]:
    tensor = numpy_to_torch_tensor(np.asarray(value))
    return _torch_save_bytes(tensor), str(tensor.dtype)


def convert_back(native_path: str, dest_path: str) -> None:
    """Export the native snapshot at ``native_path`` to a
    reference-torchsnapshot-readable snapshot at ``dest_path``."""
    from ..snapshot import Snapshot

    # _open_storage routes incremental references ("@base<N>/…"
    # locations an incremental snapshot's decorated manifest carries) to
    # their base roots; the export below then materializes those
    # payloads, so the reference-format copy is always self-contained.
    src = Snapshot(native_path)
    storage_in = src._open_storage()
    storage_out = url_to_storage_plugin(dest_path)
    budget = get_local_memory_budget_bytes()
    try:
        metadata = src._read_snapshot_metadata(storage_in)
        world_size = metadata.world_size

        ref_manifest: Dict[str, Dict[str, Any]] = {}
        # ref_location -> (native entry to read, logical path); each
        # payload is read+written once even when its entry is mirrored
        # into every rank namespace (replicated) or unioned (sharded).
        pending: Dict[str, Tuple[Any, str]] = {}

        for rank in range(world_size):
            available = get_available_entries(metadata.manifest, rank)
            for logical, entry in sorted(available.items()):
                full = f"{rank}/{logical}"
                if isinstance(entry, ListEntry):
                    ref_manifest[full] = {"type": "list"}
                    continue
                if isinstance(entry, TupleEntry):
                    # The reference has no tuple entry; lists inflate in
                    # the same positions.
                    ref_manifest[full] = {"type": "list"}
                    continue
                if isinstance(entry, OrderedDictEntry):
                    ref_manifest[full] = {
                        "type": "OrderedDict",
                        "keys": list(entry.keys),
                    }
                    continue
                if isinstance(entry, DictEntry):
                    ref_manifest[full] = {
                        "type": "dict",
                        "keys": list(entry.keys),
                    }
                    continue
                if isinstance(entry, PrimitiveEntry):
                    replicated = bool(entry.replicated)
                    loc = (
                        f"replicated/{logical}"
                        if replicated
                        else f"{rank}/{logical}"
                    )
                    ref_manifest[full] = {
                        "type": "object",
                        "location": loc,
                        "serializer": "torch_save",
                        "obj_type": entry.ptype,
                        "replicated": replicated,
                    }
                    pending.setdefault(loc, (entry, logical))
                    continue
                if isinstance(entry, ShardedArrayEntry):
                    # Assembled dense, visible to every rank.
                    loc = f"replicated/{logical}"
                    ref_manifest[full] = {
                        "type": "Tensor",
                        "location": loc,
                        "serializer": "torch_save",
                        "dtype": None,  # patched after conversion
                        "shape": [int(s) for s in entry.shape],
                        "replicated": True,
                    }
                    pending.setdefault(loc, (entry, logical))
                    continue
                if isinstance(entry, ArrayEntry):
                    replicated = bool(entry.replicated)
                    loc = (
                        f"replicated/{logical}"
                        if replicated
                        else f"{rank}/{logical}"
                    )
                    ref_manifest[full] = {
                        "type": "Tensor",
                        "location": loc,
                        "serializer": "torch_save",
                        "dtype": None,  # patched after conversion
                        "shape": [int(s) for s in entry.shape],
                        "replicated": replicated,
                    }
                    pending.setdefault(loc, (entry, logical))
                    continue
                if isinstance(entry, ObjectEntry):
                    replicated = bool(getattr(entry, "replicated", False))
                    loc = (
                        f"replicated/{logical}"
                        if replicated
                        else f"{rank}/{logical}"
                    )
                    ref_manifest[full] = {
                        "type": "object",
                        "location": loc,
                        "serializer": "torch_save",
                        "obj_type": getattr(entry, "obj_type", "object"),
                        "replicated": replicated,
                    }
                    pending.setdefault(loc, (entry, logical))
                    continue
                logger.warning(
                    f"convert_back: skipping {full} (unmapped entry type "
                    f"{type(entry).__name__})"
                )

        # Read each unique payload from the native snapshot, convert,
        # and write it to the destination — one at a time to bound RAM,
        # all under ONE event loop (per-leaf asyncio.run would build and
        # tear down ~2 loops per entry — ~100k for a 7B-shaped manifest).
        dtypes_by_loc: Dict[str, str] = {}

        async def _convert_payloads() -> None:
            for loc, (entry, logical) in sorted(pending.items()):
                if isinstance(entry, PrimitiveEntry):
                    payload = _torch_save_bytes(entry.get_value())
                else:
                    holder: Dict[str, Any] = {}
                    reqs, finalizers = prepare_read(
                        entry=entry,
                        template=None,
                        callback=lambda v: holder.update(v=v),
                    )
                    await execute_read_reqs(reqs, storage_in, budget, rank=0)
                    for fin in finalizers:
                        fin()
                    value = holder["v"]
                    if isinstance(entry, (ArrayEntry, ShardedArrayEntry)):
                        if getattr(entry, "prng_impl", None) is not None:
                            # PRNG key arrays cannot convert to numpy
                            # directly; export the raw uint32 key data
                            # (which the manifest's shape/dtype already
                            # describe) — torch has no key-array notion.
                            import jax as _jax

                            value = _jax.random.key_data(value)
                        payload, dtype = _to_torch_payload_and_dtype(value)
                        dtypes_by_loc[loc] = dtype
                    else:
                        payload = _torch_save_bytes(value)
                await storage_out.write(IOReq(path=loc, data=payload))

            for entry_dict in ref_manifest.values():
                if entry_dict.get("type") == "Tensor":
                    entry_dict["dtype"] = dtypes_by_loc[
                        entry_dict["location"]
                    ]

            doc = yaml.dump(
                {
                    "version": _REFERENCE_VERSION,
                    "world_size": world_size,
                    "manifest": ref_manifest,
                },
                sort_keys=False,
            )
            meta_req = IOReq(path=_METADATA_FNAME)
            meta_req.buf.write(doc.encode("utf-8"))
            await storage_out.write(meta_req)

        asyncio.run(_convert_payloads())
    finally:
        storage_in.close()
        storage_out.close()
