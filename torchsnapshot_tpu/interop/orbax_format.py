"""Orbax interop: migrate between orbax checkpoints and native snapshots.

The reference's ecosystem boundary is torch (`reference_format.py` /
`reference_writer.py`); the JAX ecosystem's incumbent checkpointer is
**orbax** (`orbax.checkpoint`), so a TPU-native framework owes its users
the same two-way path there:

- :func:`convert_from_orbax` — read an orbax ``PyTreeCheckpointer``
  checkpoint (OCDBT/tensorstore on disk — parsed by orbax itself, never
  by hand) and write a native snapshot, gaining this framework's
  surface over the same state: per-leaf random access
  (``read_object``), integrity scrub (``verify``), GC
  (``delete(sweep=True)``), reference-format export (``convert_back``).
- :func:`convert_to_orbax` — materialize a native snapshot's state to
  host values and save it as an orbax checkpoint, so a team trialing
  this framework can roll back to orbax as easily as a torch shop can
  roll back to the reference.

Both are single-process offline tools (collective-free): sharded arrays
resolve through the manifest's availability union, so any rank layout
converts. orbax is an optional dependency of this module only; the core
framework never imports it.
"""

import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)

_DEFAULT_STATEFUL_KEY = "state"


def _require_orbax() -> Any:
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "orbax interop requires the orbax-checkpoint package."
        ) from e
    return ocp


class _TreeHolder:
    """Stateful over a plain pytree (state_dict IS the tree)."""

    def __init__(self, tree: Any) -> None:
        self.tree = tree

    def state_dict(self) -> Any:
        return self.tree

    def load_state_dict(self, tree: Any) -> None:
        self.tree = tree


def convert_from_orbax(
    orbax_path: str,
    native_path: str,
    stateful_key: str = _DEFAULT_STATEFUL_KEY,
    compression: Optional[str] = None,
) -> "Any":
    """Convert an orbax ``PyTreeCheckpointer`` checkpoint to a native
    snapshot; returns the :class:`Snapshot` handle.

    The restored pytree becomes the state of one stateful named
    ``stateful_key`` (leaves appear as ``"<stateful_key>/<path>"`` in
    the native manifest, matching how an app that owned the tree would
    have snapshotted it)."""
    from ..snapshot import Snapshot

    ocp = _require_orbax()
    tree = ocp.PyTreeCheckpointer().restore(orbax_path)
    return Snapshot.take(
        native_path, {stateful_key: _TreeHolder(tree)}, compression=compression
    )


def convert_to_orbax(
    native_path: str,
    orbax_path: str,
    stateful_key: Optional[str] = None,
    rank: int = 0,
    allow_partial: bool = False,
) -> None:
    """Export a native snapshot as an orbax checkpoint.

    ``stateful_key`` selects one top-level stateful to export as the
    checkpoint's pytree (the natural shape when the snapshot came from
    :func:`convert_from_orbax` or holds a single train state). With
    ``None``, every top-level stateful exports under its own key —
    ``{key: tree, ...}`` — so multi-stateful app states round-trip too.

    Values are materialized to HOST (numpy/objects): replicated values
    resolve for every rank and sharded arrays assemble dense through
    the availability union, so those layouts export from any world
    size. An orbax checkpoint is ONE pytree with no rank dimension, so
    the export is ``rank``'s view — and it REFUSES (like
    ``ReferenceSnapshotReader.convert``) when other ranks own per-rank
    values that would be silently dropped. To deliberately export one
    rank's view anyway (e.g. each rank to its own checkpoint), pass
    ``allow_partial=True``.
    """
    from ..manifest import ShardedArrayEntry, is_replicated
    from ..snapshot import Snapshot

    ocp = _require_orbax()
    snap = Snapshot(native_path)
    manifest = snap.get_manifest()

    # Per-rank = carries a replicated flag that is False and is not
    # sharded. (Containers carry no flag; primitives are INLINE — no
    # location — but a per-rank primitive is still another rank's data.)
    foreign = sorted(
        full
        for full, entry in manifest.items()
        if "/" in full
        and full.split("/", 1)[0] != str(rank)
        and not isinstance(entry, ShardedArrayEntry)
        and hasattr(entry, "replicated")
        and not is_replicated(entry)
    )
    if foreign and not allow_partial:
        preview = ", ".join(foreign[:5])
        raise RuntimeError(
            f"This snapshot holds per-rank values owned by ranks other "
            f"than {rank} (e.g. {preview}); an orbax checkpoint is one "
            f"pytree with no rank dimension, so exporting rank {rank}'s "
            f"view would silently drop them. Pass allow_partial=True to "
            f"deliberately export this rank's view (e.g. each rank to "
            f"its own checkpoint via rank=R)."
        )

    # Top-level stateful keys, rank-agnostic: "0/model/..." -> "model".
    top_keys = sorted(
        {full.split("/", 2)[1] for full in manifest if "/" in full}
    )
    if stateful_key is not None:
        if stateful_key not in top_keys:
            raise KeyError(
                f'"{stateful_key}" is not a top-level stateful of this '
                f"snapshot; available: {top_keys}"
            )
        tree = snap.read_object(stateful_key, rank=rank)
    else:
        tree = {}
        for key in top_keys:
            try:
                tree[key] = snap.read_object(key, rank=rank)
            except KeyError:
                # A stateful (or leaves of one) owned entirely by another
                # rank does not resolve for `rank`. Under allow_partial
                # that is exactly the data the caller agreed to drop;
                # without it, the foreign check above already raised.
                if not allow_partial:
                    raise
                logger.warning(
                    f"convert_to_orbax: skipping stateful {key!r} "
                    f"(not resolvable for rank {rank})"
                )
    ocp.PyTreeCheckpointer().save(orbax_path, tree)
