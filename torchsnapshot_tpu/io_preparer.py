"""IO preparers: map values ⇄ manifest entries + write/read requests.

TPU-native analog of reference torchsnapshot/io_preparer.py:37-401. Three
value classes:

- **dense arrays** (``numpy.ndarray``, fully-replicated or single-device
  ``jax.Array``) → ``ArrayEntry`` + one write of raw payload bytes;
- **sharded arrays** (``jax.Array`` partitioned over a mesh) →
  ``ShardedArrayEntry``; every addressable shard with ``replica_id == 0``
  is persisted by the process that owns it (this generalizes the
  reference's ShardedTensor handling, which has no replica dimension —
  SURVEY §7 "hard parts" #1), subdivided into ≤ ``MAX_CHUNK_SIZE_BYTES``
  chunks (reference io_preparer.py:38,40-72);
- **objects** (anything else picklable) → ``ObjectEntry`` (reference
  io_preparer.py:290-323), with small scalars inlined into the manifest as
  ``PrimitiveEntry`` (beyond parity — the reference writes one storage
  object per scalar).

Staging performs the HBM→host copy inside a thread executor; for
unsubdivided shards the async device→host copy is kicked off at prepare
time (``copy_to_host_async``) so transfers overlap with scheduling —
the TPU analog of the reference's CUDA-stream staging thread pool
(io_preparer.py:199-210).

Restore routes *all* array entries — dense or sharded — through a single
:class:`ArrayRestorePlan`, which computes the overlap of saved chunks with
the *target sharding's* addressable shards (``resharding.py``), reads only
the needed chunks (with ranged reads for contiguous overlaps), assembles
per-device host buffers, and builds the result with
``jax.make_array_from_single_device_arrays``. Elastic restore onto a
different mesh/pod shape is therefore the same code path as same-sharding
restore (reference analog: resharding.py:135-199 + io_preparer.py:113-163).
"""

import asyncio
import logging
import os
import threading
from concurrent.futures import Executor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import staging_pool, telemetry, tracing
from .telemetry import consume_profile as _cprof
from .telemetry import metrics as _metric_names
from .io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from .utils.env import env_int
from .ops.transfer import (
    chunked_device_put,
    device_clone,
    h2d_chunk_bytes,
    h2d_pipeline,
    parallel_device_get,
    should_chunk_h2d,
    should_chunk_transfer,
)
from .manifest import (
    ArrayEntry,
    Entry,
    ObjectEntry,
    PrimitiveEntry,
    Shard,
    ShardedArrayEntry,
)
from .resharding import (
    Overlap,
    compute_overlap,
    contiguous_byte_range,
    index_to_offsets_sizes,
    subdivide,
)
from .serialization import (
    ARRAY_SERIALIZER,
    OBJECT_SERIALIZER,
    StreamingCrc32,
    bytes_to_object,
    compress_payload,
    compute_checksum,
    decompress_payload,
    dtype_to_str,
    object_to_bytes,
    str_to_dtype,
    verify_checksum,
)

logger = logging.getLogger(__name__)

# Reference: io_preparer.py:38 (512 MB max shard chunk).
MAX_CHUNK_SIZE_BYTES: int = 512 * 1024 * 1024

# Whole-object reads above this size are split into concurrent ranged
# sub-reads reassembled on host (VERDICT r3 weak #3: a dense ArrayEntry
# is ONE storage object of unbounded size, and a single-stream download
# caps restore far below the link ceiling on object stores — the
# read-side mirror of the GCS composite upload; reference analog: 100 MB
# download chunks, reference gcs.py:55). Also the sub-read size.
_PARALLEL_READ_THRESHOLD_ENV_VAR = "TPUSNAPSHOT_PARALLEL_READ_THRESHOLD"
_DEFAULT_PARALLEL_READ_THRESHOLD = 64 * 1024 * 1024


def _parallel_read_threshold() -> int:
    return env_int(
        _PARALLEL_READ_THRESHOLD_ENV_VAR, _DEFAULT_PARALLEL_READ_THRESHOLD
    )


_DEVICE_BUDGET_ENV_VAR = "TPUSNAPSHOT_DEVICE_BUDGET_BYTES"


def get_device_restore_budget_bytes() -> Optional[int]:
    """HBM bytes the restore pipeline may hold as in-flight streamed
    chunks awaiting assembly (SURVEY §7 hard-part 5). Explicit env knob
    wins (0 = unbounded); otherwise 90% of the device's currently free
    memory when the runtime reports it (TPUs do; CPU/virtual devices
    usually return None → unbounded)."""
    raw = os.environ.get(_DEVICE_BUDGET_ENV_VAR)
    if raw is not None:
        # Sentinel default: a malformed value falls THROUGH to the
        # autodetect below (r5 review finding — mapping it to
        # "unbounded" would strip exactly the protection the operator
        # explicitly asked for). An explicit 0 means unbounded.
        value = env_int(_DEVICE_BUDGET_ENV_VAR, -1)
        if value > 0:
            return value
        if value == 0:
            return None
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if limit:
            return max(int(0.9 * (limit - in_use)), 256 * 1024 * 1024)
    # memory_stats is an optional backend capability; absence means
    # "no device budget", the documented unbounded default.
    except Exception:  # snapcheck: disable=swallowed-exception -- capability probe
        pass
    return None

_PRIMITIVE_TYPES = (int, float, bool, str, complex, type(None))


def get_storage_path(rank: int, logical_path: str, replicated: bool) -> str:
    """Reference analog: io_preparer.py:336-342."""
    if replicated:
        return f"replicated/{logical_path}"
    return f"{rank}/{logical_path}"


def chunk_location(logical_path: str, offsets: List[int]) -> str:
    suffix = "_".join(str(o) for o in offsets)
    return f"sharded/{logical_path}_{suffix}" if suffix else f"sharded/{logical_path}_0"


def _is_jax_array(obj: Any) -> bool:
    return isinstance(obj, jax.Array)


def _is_prng_key_array(obj: Any) -> bool:
    return _is_jax_array(obj) and jax.dtypes.issubdtype(
        obj.dtype, jax.dtypes.prng_key
    )


def _is_partitioned(arr: jax.Array) -> bool:
    """True if the array's data is split across devices (vs replicated)."""
    return not arr.is_fully_replicated


# Chunked-transfer + clone primitives live in ops/transfer.py; private
# aliases keep this module's call sites short.
_should_chunk_transfer = should_chunk_transfer
_parallel_device_get = parallel_device_get


# Finalize executor: an eager finalize triggered from an H2D engine
# done-callback must NOT run on the engine worker itself —
# _await_pipeline blocks on futures queued on that same depth-limited
# pool, and at depth 1 (or N concurrent restores ≥ depth) the worker
# would wait on work only it can run. Engine-triggered finalizes hop
# here instead; the pool only ever waits ON the engine, never the
# reverse, so there is no cycle.
_finalize_pool: Optional[Any] = None
_finalize_pool_lock = threading.Lock()


def _get_finalize_pool():
    global _finalize_pool
    with _finalize_pool_lock:
        if _finalize_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _finalize_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="tpusnapshot-finalize"
            )
        return _finalize_pool


def _on_h2d_engine_thread() -> bool:
    return threading.current_thread().name.startswith("tpusnapshot-h2d")


class ArrayBufferStager(BufferStager):
    """Stages a device (or host) array into raw payload bytes.

    ``data`` is a single-device ``jax.Array`` (a shard's ``.data``) or a
    ``numpy.ndarray``. When ``chunk_slices`` is given, only that sub-box is
    staged (used when a shard is subdivided): the slice executes on device
    so only chunk-sized host memory is allocated.
    """

    def __init__(
        self,
        data: Any,
        chunk_slices: Optional[Tuple[slice, ...]] = None,
        nbytes: Optional[int] = None,
        entry: Optional[ArrayEntry] = None,
        compression: Optional[str] = None,
        eager_host_copy: bool = True,
    ) -> None:
        self._data = data
        self._chunk_slices = chunk_slices
        self._compression = compression
        self._entry = entry  # back-patched with the payload checksum
        self._owns_data = False  # True once rebound to a private copy
        if nbytes is None:
            nbytes = int(np.dtype(data.dtype).itemsize * np.prod(data.shape))
        self._nbytes = nbytes
        if eager_host_copy:
            # Small arrays: start the whole-array async copy now so the
            # transfer overlaps with scheduling. Large arrays skip this —
            # they stage via parallel chunked transfers instead, and a
            # prepare-time whole-array copy would occupy the link with a
            # slow single stream. Async takes pass eager_host_copy=False:
            # a device-staged cut rebinds stagers to on-device clones, and
            # a transfer started on the original would never be consumed.
            # Incremental takes also pass False — a dedup hit must skip
            # the transfer entirely; apply_incremental kicks off copies
            # for the SURVIVING requests afterwards.
            self.kickoff_host_copy()

    def kickoff_host_copy(self) -> None:
        """Dispatch the async device→host copy for a small whole-array
        payload (no-op for chunked/sliced/host data or once staged)."""
        data = self._data
        if (
            data is not None
            and _is_jax_array(data)
            and self._chunk_slices is None
            and not _should_chunk_transfer(data)
        ):
            try:
                data.copy_to_host_async()
            # Pure prefetch hint: the later synchronous stage re-runs
            # the transfer and surfaces any real failure.
            except Exception:  # pragma: no cover; snapcheck: disable=swallowed-exception -- prefetch hint
                pass

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        loop = asyncio.get_running_loop()
        if executor is None:
            # Inline-staging escape hatch: every pipeline path passes an
            # executor; a caller opting out owns the stall trade-off.
            return self._stage_sync()  # snapcheck: disable=event-loop-blocking -- executor=None is the caller-owned inline path; all pipeline call sites pass an executor
        return await loop.run_in_executor(executor, self._stage_sync)

    def _stage_sync(self) -> BufferType:
        data = self._data
        if self._chunk_slices is not None:
            data = data[self._chunk_slices]
        if _should_chunk_transfer(data):
            host = _parallel_device_get(data)
        else:
            host = np.asarray(data)  # D2H for jax arrays; no-op for numpy
        host = np.ascontiguousarray(host)
        if (
            isinstance(self._data, np.ndarray)
            and not self._owns_data
            and np.shares_memory(host, self._data)
        ):
            # User-owned mutable host memory: copy so the staged buffer is
            # a consistent cut (jax.Arrays are immutable — no copy needed).
            host = host.copy()
        # Drop the source reference: once the payload is on host, the
        # device buffer (ours after a device-staged async take, or the
        # caller's) no longer needs to be pinned by this stager.
        self._data = None
        # Reinterpret as raw bytes: ml_dtypes dtypes (bfloat16, float8_*)
        # don't export the buffer protocol directly, but a uint8 view does,
        # and it is zero-copy.
        payload = memoryview(host.reshape(-1).view(np.uint8))
        if self._compression is not None:
            payload = compress_payload(payload, self._compression)
            if self._entry is not None:
                self._entry.compression = self._compression
        if self._entry is not None:
            # The checksum reaches the persisted metadata because staging
            # always precedes the manifest consolidation: sync takes write
            # (hence stage) before the manifest all-gather; async takes
            # serialize each rank's manifest into its completion marker
            # only after execute_write_reqs finishes (snapshot.py _drain) —
            # staging may run entirely in that background drain under a
            # device-staged cut.
            self._entry.checksum = compute_checksum(payload)
        return payload

    def get_staging_cost_bytes(self) -> int:
        return self._nbytes


def device_clone_write_reqs(write_reqs: List[WriteReq]) -> bool:
    """Rebind every array stager to a private on-device copy of its data.

    The consistent-cut primitive behind device-staged async snapshots: an
    HBM→HBM copy runs at memory bandwidth (orders of magnitude faster than
    device→host), so cloning the checkpoint state on device and draining
    the device→host staging in the background reduces the training stall
    from "one full D2H of the app state" to "one HBM copy". The clones own
    their buffers, so a subsequent training step that donates/deletes the
    source arrays (jit donation) cannot invalidate the snapshot.

    Host-side numpy data is copied on host (it is mutable user memory).
    Returns False — with all partial clones released — if the device ran
    out of memory; the caller falls back to host staging.
    """
    sources: Dict[int, Any] = {}
    rebinds: List[Tuple[ArrayBufferStager, int]] = []
    host_copies: Dict[int, Any] = {}
    for wr in write_reqs:
        stager = wr.buffer_stager
        if not isinstance(stager, ArrayBufferStager) or stager._data is None:
            continue
        data = stager._data
        if _is_jax_array(data):
            sources.setdefault(id(data), data)
            rebinds.append((stager, id(data)))
        elif isinstance(data, np.ndarray):
            # Dedupe by identity: a chunked dense array shares ONE
            # source across its chunk stagers — copy it once, not once
            # per chunk.
            key = id(data)
            if key not in host_copies:
                host_copies[key] = np.array(data, copy=True)
            stager._data = host_copies[key]
            stager._owns_data = True
    order = list(sources)
    clones = device_clone([sources[k] for k in order])
    if clones is None:
        logger.warning(
            "Device-staged snapshot does not fit in device memory; "
            "falling back to host staging."
        )
        return False
    clone_by_key = dict(zip(order, clones))
    for stager, key in rebinds:
        stager._data = clone_by_key[key]
        stager._owns_data = True
    return True


class ObjectBufferStager(BufferStager):
    def __init__(
        self,
        obj: Any,
        entry: Optional[ObjectEntry] = None,
        compression: Optional[str] = None,
    ) -> None:
        # Objects are small (counters, RNG states, dataloader cursors);
        # pickle eagerly so the staging cost is exact. Compression and
        # checksum are deferred to stage time: non-owner ranks of a
        # replicated object drop their write request without staging, so
        # they never pay those costs (their manifest entry legitimately
        # carries checksum/compression = None; the restore path prefers
        # the stripe owner's checksum-bearing entry).
        self._buf: BufferType = object_to_bytes(obj)
        self._entry = entry
        self._compression = compression
        self._staged = False

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        if not self._staged:
            self._staged = True
            if self._compression is not None:
                self._buf = compress_payload(self._buf, self._compression)
                if self._entry is not None:
                    self._entry.compression = self._compression
            if self._entry is not None:
                self._entry.checksum = compute_checksum(self._buf)
        return self._buf

    def get_staging_cost_bytes(self) -> int:
        return len(self._buf)


class ObjectBufferConsumer(BufferConsumer):
    """Materializes a pickled object and hands it back via callback
    (reference io_preparer.py:290-304: objects cannot be restored in place).
    """

    def __init__(
        self,
        callback: Callable[[Any], None],
        size_hint: int = 1 << 20,
        checksum: Optional[str] = None,
        compression: Optional[str] = None,
    ):
        self._callback = callback
        self._size_hint = size_hint
        self._checksum = checksum
        self._compression = compression
        # Consume micro-profile scope, captured at plan-build time (the
        # restoring thread) so executor-thread notes attribute to the
        # right restore (telemetry/consume_profile.py).
        self._profile = _cprof.current()

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        def _load() -> Any:
            with _cprof.consume_section():
                with _cprof.substep(self._profile, "verify", len(buf)):
                    verify_checksum(buf, self._checksum)
                if self._compression is not None:
                    with _cprof.substep(self._profile, "decode", len(buf)):
                        raw = decompress_payload(buf, self._compression)
                else:
                    raw = buf
                with _cprof.substep(
                    self._profile, "deserialize", len(raw)
                ):
                    return bytes_to_object(raw)

        if executor is not None:
            loop = asyncio.get_running_loop()
            obj = await loop.run_in_executor(executor, _load)
        else:
            obj = _load()
        self._callback(obj)

    def get_consuming_cost_bytes(self) -> int:
        return self._size_hint


class _TargetRegion:
    """One distinct region of the global array needed on restore, with the
    devices that need it (replicas share one host buffer).

    The host buffer is LAZY and (for device-template restores) pooled:
    it materializes from the staging pool on the first scatter into it,
    so regions that end up streaming to device or adopting a zero-copy
    payload view never allocate one, and the ones that do allocate
    reuse a prior restore's buffer of the same size."""

    def __init__(
        self,
        offsets: List[int],
        sizes: List[int],
        dtype: np.dtype,
        poolable: bool = False,
    ):
        self.offsets = offsets
        self.sizes = sizes
        self.dtype = np.dtype(dtype)
        self.devices: List[Any] = []
        self.nbytes = int(self.dtype.itemsize * np.prod(sizes))
        # Lazily materialized host buffer (None until first needed). A
        # zero-copy adoption replaces it with a read-payload view
        # without ever touching the pool; host-template restores
        # allocate plain arrays (the buffer is handed to the app, so
        # pool reuse would alias user memory).
        self.buffer: Optional[np.ndarray] = None
        self._poolable = poolable
        self._lease: Optional[staging_pool.StagingLease] = None
        self._buf_lock = threading.Lock()
        # Whether the scheduler's device budget already holds this
        # region's reservation (charged once, by the first admitted
        # streaming sub-read; the unit of HBM occupancy is the region —
        # its chunks stay deposited until assembly).
        self.device_charged = False
        # Streaming reads leave the region's data on device as 1-D
        # chunks keyed by their flat byte offset within the region
        # (finalize concatenates + reshapes on device instead of a host
        # device_put). Distinct keys, so concurrent chunk streams
        # deposit without a region lock (GIL-atomic dict writes).
        self.device_chunks: Optional[Dict[int, Any]] = None
        # (release_cb, nbytes) pairs invoked by finalize once the
        # deposited chunks are concatenated and freed — returns the
        # streamed bytes to the scheduler's device-memory budget.
        self.device_releases: List[Tuple[Callable[[int], None], int]] = []
        # Chunk-copies still expected to scatter into this region; set
        # by the plan at build time. When the count drains the plan may
        # dispatch this region's H2D on the overlap engine instead of
        # waiting for plan finalize (chunk-granular overlap).
        self.pending_copies = 0
        # Future from the overlap engine's early dispatch (single-
        # device regions); finalize collects it instead of device_put.
        self.early_put: Optional[Any] = None

    def ensure_buffer(self, profile: Optional[Any] = None) -> np.ndarray:
        with self._buf_lock:
            if self.buffer is None:
                pool = (
                    staging_pool.get_staging_pool()
                    if self._poolable
                    else None
                )
                if pool is not None:
                    self._lease = pool.acquire(self.nbytes, profile)
                    self.buffer = self._lease.as_array(
                        self.dtype, list(self.sizes)
                    )
                else:
                    self.buffer = np.empty(self.sizes, dtype=self.dtype)
            return self.buffer

    def release_lease(self) -> None:
        """Return the pooled backing (if any) — only safe once no
        pending transfer still reads from ``buffer``."""
        with self._buf_lock:
            lease, self._lease = self._lease, None
            self.buffer = None if lease is not None else self.buffer
        if lease is not None:
            lease.release()


class _ChunkCopyConsumer(BufferConsumer):
    """Consumes one saved chunk's payload (possibly a ranged read) and
    scatters it into the overlapping target-region buffers."""

    def __init__(
        self,
        view_shape: List[int],
        dtype: np.dtype,
        copies: List[Tuple[_TargetRegion, Tuple[slice, ...], Tuple[slice, ...]]],
        checksum: Optional[str] = None,
        compression: Optional[str] = None,
        on_done: Optional[Callable[[], None]] = None,
        allow_adopt: bool = True,
        region_notify: Optional[Callable[[_TargetRegion], None]] = None,
    ) -> None:
        # copies: (region, region_slices, view_slices)
        self._view_shape = view_shape
        self._dtype = dtype
        self._copies = copies
        self._checksum = checksum
        self._compression = compression
        self._on_done = on_done
        # False when the payload handed to consume_buffer is a view over
        # a POOLED assembly buffer (split/content-chunk read states):
        # adopting such a view would pin pool memory past its release
        # and corrupt a later restore that reuses it.
        self._allow_adopt = allow_adopt
        # Plan hook: fired once per (this chunk, region) scatter so the
        # plan can early-dispatch a fully-populated region's H2D on the
        # overlap engine instead of waiting for finalize.
        self._region_notify = region_notify
        self._cost = int(np.dtype(dtype).itemsize * np.prod(view_shape))
        self._profile = _cprof.current()

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        def _copy() -> None:
            with _cprof.substep(self._profile, "verify", len(buf)):
                verify_checksum(buf, self._checksum)
            if self._compression is not None:
                with _cprof.substep(self._profile, "decode", len(buf)):
                    buf_raw = decompress_payload(buf, self._compression)
            else:
                buf_raw = buf
            with _cprof.substep(self._profile, "reassemble", self._cost):
                view = np.frombuffer(buf_raw, dtype=self._dtype).reshape(
                    self._view_shape
                )
                for region, region_slices, view_slices in self._copies:
                    if (
                        self._allow_adopt
                        and len(self._copies) == 1
                        and region.buffer is None
                        and list(view.shape) == list(region.sizes)
                        and all(
                            sl.start == 0 and sl.stop == dim
                            for sl, dim in zip(region_slices, region.sizes)
                        )
                        and all(
                            sl.start == 0 and sl.stop == dim
                            for sl, dim in zip(view_slices, view.shape)
                        )
                    ):
                        # The chunk exactly covers this region: adopt the
                        # zero-copy view instead of memcpy-ing into a
                        # staging buffer (np.frombuffer views are
                        # read-only, which device_put accepts).
                        region.buffer = view
                    else:
                        region.ensure_buffer(self._profile)[
                            region_slices
                        ] = view[view_slices]

        def _copy_and_signal() -> None:
            with _cprof.consume_section():
                _copy()
                if self._region_notify is not None:
                    for region, _rs, _vs in self._copies:
                        self._region_notify(region)
                # Runs in the executor thread: a finalize triggered here
                # (host→device assembly) overlaps with reads still in
                # flight instead of blocking the event loop.
                if self._on_done is not None:
                    self._on_done()

        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, _copy_and_signal)
        else:
            _copy_and_signal()

    def get_consuming_cost_bytes(self) -> int:
        return self._cost


class _PooledAssemblyState:
    """Shared lease/budget plumbing for read states that assemble ONE
    stored object in a host buffer drawn from the staging pool
    (``staging_pool.py``): the scheduler's deferred-cost releaser
    (charged as the first sub-read's/chunk's deferred cost) is
    re-credited exactly ONCE — when the buffer actually returns to the
    pool — whichever of buffer acquisition and the scheduler's
    dispatch hook lands first, so concurrent reads cannot overrun the
    budget and a pooled, multi-sub-read buffer cannot over-credit it.
    One implementation, two subclasses: the split whole-object path and
    the content-chunk (chunkstore) path must never diverge on this
    contract."""

    def __init__(self, nbytes: int) -> None:
        self.nbytes = nbytes
        self._buf: Optional[bytearray] = None  # allocated on first absorb
        self._lease: Optional[staging_pool.StagingLease] = None
        self._lock = threading.Lock()
        self._profile = _cprof.current()
        self._cost_release: Optional[Callable[[int], None]] = None

    def set_cost_releaser(self, release: Callable[[int], None]) -> None:
        with self._lock:
            lease = self._lease
            if lease is None:
                self._cost_release = release
        if lease is not None:
            # Acquisition raced ahead of the scheduler's dispatch hook:
            # hand the credit to the lease (fired once, at release).
            lease.set_budget_release(release, self.nbytes)

    def _ensure_buf(self) -> None:
        """Materialize the shared assembly buffer (pooled when the
        staging pool is enabled; the lease then carries the budget
        re-credit and fires it exactly once at pool return)."""
        with self._lock:
            if self._buf is not None:
                return
            pool = staging_pool.get_staging_pool()
            if pool is None:
                self._buf = bytearray(self.nbytes)
                return
            lease = pool.acquire(self.nbytes, self._profile)
            # Store the lease before touching anything else: until it
            # is reachable from self, an exception here would orphan
            # the pooled buffer (and its exactly-once budget re-credit).
            self._lease = lease
            self._buf = lease.buffer
            release, self._cost_release = self._cost_release, None
        if release is not None:
            lease.set_budget_release(release, self.nbytes)

    def _release_assembly_buffer(self) -> None:
        """Free the assembly buffer: pooled buffers return to the pool
        (which fires the budget re-credit once); plain ones re-credit
        through the releaser directly. Idempotent either way."""
        with self._lock:
            lease, self._lease = self._lease, None
            release, self._cost_release = self._cost_release, None
            self._buf = None
        if lease is not None:
            if release is not None:
                # _ensure_buf stored the lease but raised before
                # handing it the releaser: attach before releasing so
                # the budget re-credit still fires (exactly once — the
                # lease owns it from here).
                lease.set_budget_release(release, self.nbytes)
            lease.release()
        elif release is not None:
            release(self.nbytes)


class _SplitObjectReadState(_PooledAssemblyState):
    """Reassembles concurrent ranged sub-reads of ONE stored object into
    a single host buffer, then runs the real consumer on the whole
    payload. Checksum verification still covers the complete object (the
    inner consumer sees exactly the bytes a whole-object read would
    have), so splitting is integrity-preserving — unlike partial ranged
    reads, which skip verification."""

    def __init__(self, nbytes: int, inner: BufferConsumer) -> None:
        super().__init__(nbytes)
        self._inner = inner
        self._remaining = 0

    def extra_first_cost_bytes(self) -> int:
        """Cost charged on top of the first sub-read's payload: the
        shared host assembly buffer."""
        return self.nbytes

    def deferred_cost_bytes(self, first: bool, part_nbytes: int) -> int:
        """Portion of a sub-read's consuming cost whose allocation
        outlives its consume: the assembly buffer, carried by the first
        sub-read, freed when the LAST one lands."""
        return self.nbytes if first else 0

    def add_sub_reads(self, path: str, part_size: int) -> List[ReadReq]:
        reqs = []
        starts = list(range(0, self.nbytes, part_size))
        self._remaining = len(starts)
        for i, start in enumerate(starts):
            end = min(start + part_size, self.nbytes)
            reqs.append(
                ReadReq(
                    path=path,
                    buffer_consumer=_SubRangeConsumer(
                        self, start, end, first=(i == 0)
                    ),
                    byte_range=(start, end),
                )
            )
        return reqs

    async def absorb(
        self,
        start: int,
        end: int,
        buf: BufferType,
        executor: Optional[Executor] = None,
    ) -> None:
        def _copy() -> None:
            with _cprof.consume_section():
                self._ensure_buf()
                with _cprof.substep(
                    self._profile, "reassemble", end - start
                ):
                    if len(buf) != end - start:
                        raise RuntimeError(
                            f"Ranged sub-read returned {len(buf)} bytes for "
                            f"[{start}, {end}) — object shorter than the manifest "
                            f"implies (truncated or torn)."
                        )
                    # Disjoint ranges: concurrent executor threads never overlap.
                    memoryview(self._buf)[start:end] = buf

        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, _copy)
        else:
            _copy()
        with self._lock:
            self._remaining -= 1
            last = self._remaining == 0
        if last:
            try:
                await self._inner.consume_buffer(
                    memoryview(self._buf)[: self.nbytes], executor
                )
            finally:
                with _cprof.consume_section(), _cprof.substep(
                    self._profile, "staging_release", self.nbytes
                ):
                    # Pool return fires the scheduler budget re-credit
                    # exactly once, however many sub-reads shared it.
                    self._release_assembly_buffer()


class _StreamingSplitState(_SplitObjectReadState):
    """Split read of one large object that STREAMS each completed
    sub-range to the target device instead of waiting for full host
    reassembly — overlapping storage reads with H2D transfers, which a
    reassemble-then-put split serializes (measured: a pure 640 MiB
    restore reached only 0.74 of the bracketed H2D ceiling because the
    last sub-read gated the entire device transfer).

    Fastlane: the H2D itself runs on the overlap ENGINE
    (ops/transfer.py H2DPipeline), not inside the consume executor — a
    consume here is only the length check, the incremental crc fold,
    and the transfer submit, so consume wall tracks host work while the
    double-buffered engine keeps the link saturated. The engine's
    done-callback deposits the device chunk and fires the plan's
    on_done once every part has BOTH crc-verified and landed on device.

    Only used when one uncompressed chunk exactly covers one
    single-device region (the dominant shape: restoring a large dense
    parameter). Integrity is unchanged: the crc32 is folded INCREMENTALLY
    over the in-order byte stream as sub-ranges land (out-of-order
    arrivals stash until their prefix completes — no full host
    reassembly, and no end-of-stream hash pass on the critical path) and
    checked BEFORE the plan's finalize exposes the array; the device
    chunks are unreachable until then, and a mismatch raises with
    nothing exposed."""

    def __init__(
        self,
        nbytes: int,
        region: "_TargetRegion",
        dtype: np.dtype,
        checksum: Optional[str],
        on_done: Callable[[], None],
        flat_base: int = 0,
        register_transfer: Optional[Callable[[Any], None]] = None,
    ) -> None:
        super().__init__(nbytes, inner=None)  # inner unused
        self._region = region
        self._np_dtype = dtype
        self._checksum = checksum
        self._on_done = on_done
        self._device = region.devices[0]
        # Byte offset of this stored object within the region's flat
        # layout: format-chunked dense arrays stream SEVERAL objects
        # into one region, each depositing at flat_base + sub-offset
        # (VERDICT r4 #2 — streaming used to engage only when one object
        # exactly covered the region).
        self._flat_base = flat_base
        if region.device_chunks is None:
            region.device_chunks = {}
        # Incremental crc (same no-op contract as verify_checksum for
        # absent/unknown-algorithm checksums).
        self._crc: Optional[StreamingCrc32] = (
            StreamingCrc32()
            if checksum and checksum.startswith("crc32:")
            else None
        )
        self._next_off = 0
        self._stash: Dict[int, BufferType] = {}
        self._released = 0  # deferred bytes already re-credited
        self._device_release: Optional[Callable[[int], None]] = None
        self._deposited = 0  # device bytes charged by the scheduler
        # Plan hook: every engine future is registered so finalize can
        # surface a transfer failure before publishing anything.
        self._register_transfer = register_transfer
        # Per-part budget refcounts: a part's payload is re-credited
        # only after BOTH holds drop — the crc prefix drain (the
        # out-of-order stash) and the overlap engine's transfer.
        self._part_refs: Dict[int, int] = {}
        self._transfers_remaining = 0
        self._crc_ok = self._crc is None
        self._completed = False
        self._failed = False

    def set_device_cost_releaser(self, release: Callable[[int], None]) -> None:
        self._device_release = release

    def note_device_cost(self, nbytes: int) -> None:
        with self._lock:
            self._deposited += nbytes

    def extra_first_cost_bytes(self) -> int:
        # No host assembly buffer: parts go straight to device. Charging
        # the whole object on the first sub-read would serialize
        # concurrent large streaming restores under a tight budget —
        # defeating the read/H2D overlap this class exists for.
        return 0

    def deferred_cost_bytes(self, first: bool, part_nbytes: int) -> int:
        # Every part's payload outlives its consume: the overlap engine
        # holds it until the transfer completes, and (with an
        # incremental crc) the out-of-order stash may hold it until the
        # prefix drains. Released per-part once both holds drop.
        return part_nbytes

    def add_sub_reads(self, path: str, part_size: int) -> List[ReadReq]:
        reqs = super().add_sub_reads(path, part_size)
        self._transfers_remaining = len(reqs)
        return reqs

    def _release_assembly_cost(self) -> None:
        # Error-path safety net: re-credit whatever the per-part
        # refcounts have not already released (on success they cover the
        # whole object and this is a no-op).
        release, self._cost_release = self._cost_release, None
        if release is not None:
            with self._lock:
                remaining = self.nbytes - self._released
                self._released = self.nbytes
            if remaining > 0:
                release(remaining)

    def _part_release(self, start: int, nbytes: int) -> None:
        release = None
        with self._lock:
            refs = self._part_refs.get(start)
            if refs is None:
                return
            refs -= 1
            if refs:
                self._part_refs[start] = refs
                return
            del self._part_refs[start]
            release = self._cost_release
            if release is not None:
                self._released += nbytes
        if release is not None:
            release(nbytes)

    def _transfer_done(self, start: int, nbytes: int, fut: Any) -> None:
        if fut.cancelled() or fut.exception() is not None:
            # The restore is failing: finalize (or the plan's safety
            # net) re-raises the registered future's error before
            # anything is published. Mark failed so on_done never fires
            # over a partial deposit — and release the stream's
            # remaining deferred-budget holds NOW, so the doomed
            # restore's other reads don't crawl through forced
            # admission against a starved budget until the finalizer
            # surfaces the error.
            with self._lock:
                self._failed = True
            self._release_assembly_cost()
            return
        # Deposit straight into the region, keyed by region-flat byte
        # offset (distinct keys across all of the region's chunk
        # streams; GIL-atomic dict write). The chunks stay unreachable
        # to the application until the plan's finalize assembles them —
        # which only runs after every chunk's crc verified.
        self._region.device_chunks[self._flat_base + start] = fut.result()
        with self._lock:
            self._transfers_remaining -= 1
        self._part_release(start, nbytes)
        self._maybe_complete()

    def _maybe_complete(self) -> None:
        with self._lock:
            if (
                self._completed
                or self._failed
                or not self._crc_ok
                or self._remaining != 0
                or self._transfers_remaining != 0
            ):
                return
            self._completed = True
            # Hand the scheduler's device-budget reservation to the
            # region: finalize releases it once the concat frees the
            # per-chunk arrays.
            if self._device_release is not None and self._deposited:
                self._region.device_releases.append(
                    (self._device_release, self._deposited)
                )
                self._device_release = None
        try:
            self._on_done()
        finally:
            self._release_assembly_cost()

    async def absorb(
        self,
        start: int,
        end: int,
        buf: BufferType,
        executor: Optional[Executor] = None,
    ) -> None:
        def _consume_part() -> None:
            with _cprof.consume_section():
                if len(buf) != end - start:
                    raise RuntimeError(
                        f"Ranged sub-read returned {len(buf)} bytes for "
                        f"[{start}, {end}) — object shorter than the manifest "
                        f"implies (truncated or torn)."
                    )
                flat = np.frombuffer(buf, dtype=self._np_dtype)
                with self._lock:
                    self._part_refs[start] = (
                        2 if self._crc is not None else 1
                    )
                # Submit the H2D on the overlap engine FIRST: the
                # transfer rides the link while the crc fold below runs
                # on host and later sub-reads are still arriving.
                fut = h2d_pipeline().submit(
                    flat, self._device, profile=self._profile
                )
                if self._register_transfer is not None:
                    self._register_transfer(fut)
                fut.add_done_callback(
                    lambda f, s=start, n=len(buf): self._transfer_done(
                        s, n, f
                    )
                )
                if self._crc is not None:
                    with _cprof.substep(self._profile, "verify", len(buf)):
                        drained: List[Tuple[int, int]] = []
                        with self._lock:
                            self._stash[start] = buf
                            while self._next_off in self._stash:
                                off = self._next_off
                                b = self._stash.pop(off)
                                self._crc.update(b)
                                self._next_off += len(b)
                                drained.append((off, len(b)))
                            stream_done = self._next_off >= self.nbytes
                        # Re-credit drained parts outside the state lock
                        # (the budget cell takes its own lock).
                        for off, n in drained:
                            self._part_release(off, n)
                        if stream_done:
                            actual = self._crc.tag()
                            if actual != self._checksum:
                                with self._lock:
                                    self._failed = True
                                raise RuntimeError(
                                    f"Checksum mismatch: stored object is "
                                    f"corrupt (expected {self._checksum}, "
                                    f"got {actual})."
                                )
                            with self._lock:
                                self._crc_ok = True

        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, _consume_part)
        else:
            _consume_part()
        with self._lock:
            self._remaining -= 1
        self._maybe_complete()


class _SubRangeConsumer(BufferConsumer):
    """One ranged sub-read of a split whole-object read."""

    def __init__(
        self, state: _SplitObjectReadState, start: int, end: int, first: bool
    ) -> None:
        self._state = state
        self._start = start
        self._end = end
        self._first = first

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        await self._state.absorb(self._start, self._end, buf, executor)

    def get_consuming_cost_bytes(self) -> int:
        # Each sub-read charges its own payload; the first additionally
        # carries the state's shared-allocation cost (the host assembly
        # buffer — zero for streaming states, which have none). The
        # scheduler dispatches reads in list order, so the first is
        # admitted before the others. The inner consumer's view is
        # zero-copy over the assembly buffer, so its cost is not
        # double-charged.
        extra = self._state.extra_first_cost_bytes() if self._first else 0
        return (self._end - self._start) + extra

    def get_deferred_cost_bytes(self) -> int:
        # The deferred portion's allocation outlives this consume (the
        # assembly buffer until the LAST sub-read; a streamed part's
        # stash entry until the crc prefix drains), so its reservation is
        # released through the scheduler's callback when actually freed,
        # not at consume completion.
        return self._state.deferred_cost_bytes(
            self._first, self._end - self._start
        )

    def set_cost_releaser(self, release: Callable[[int], None]) -> None:
        self._state.set_cost_releaser(release)

    @property
    def sort_key_bytes(self) -> int:
        # Scheduler dispatch ordering: all of one object's sub-reads
        # share the object's size, keeping the group contiguous under
        # the largest-first stable sort (the first sub-read's consuming
        # COST carries the assembly surcharge and must not be used as
        # the ordering key).
        return self._state.nbytes

    def get_device_cost_bytes(self) -> int:
        # Streaming sub-reads put their payload in device memory the
        # moment they consume, and it stays there until the REGION
        # assembles — so the whole region is charged up front by its
        # first admitted sub-read (SURVEY §7 hard-part 5: the scheduler
        # gates consume dispatch on a device-side budget; per-part
        # charges could not hold it, since releases only arrive at
        # region finalize). The charge is TWICE the region: deposited
        # chunks + the concatenated result coexist during assembly, and
        # after it the restored array stays RESIDENT — finalize releases
        # only the transient half, so the budget keeps tracking
        # cumulative HBM the restore now occupies (r5 review finding:
        # recrediting the full region let admissions run ~2x past the
        # free-HBM snapshot the budget came from). Sub-reads of an
        # already-charged region cost 0 — completing a started region is
        # always admissible, which is the progress property the
        # pipeline needs.
        if not isinstance(self._state, _StreamingSplitState):
            return 0
        region = self._state._region
        return 0 if region.device_charged else 2 * region.nbytes

    def set_device_cost_releaser(
        self, release: Callable[[int], None]
    ) -> None:
        region = self._state._region
        region.device_charged = True
        self._state.set_device_cost_releaser(release)
        # The transient half, returned by finalize once the concat's
        # buffers settle; the resident half stays charged.
        self._state.note_device_cost(region.nbytes)


class _ContentChunksReadState(_PooledAssemblyState):
    """Reassembles the content-addressed chunks of ONE stored object
    (chunkstore.py manifest entries) into its logical payload, then
    runs the real consumer on the whole payload — the chunk-store
    mirror of :class:`_SplitObjectReadState`, with per-chunk codec
    decode and content verification fused into the consume executor so
    decodes overlap reads still in flight.

    Integrity per chunk, independent of which take wrote it:
    losslessly-coded chunks must fingerprint back to the content key
    (xs128 of the logical bytes — stronger than a crc, and available
    even for chunks this manifest only references); lossy (int8)
    chunks verify their self-checking frame. Stored-size and stored-crc
    checks additionally apply where this manifest recorded them (the
    chunks its own take wrote)."""

    def __init__(
        self,
        inner: BufferConsumer,
        records: List[Dict[str, Any]],
        dtype_name: str,
        store_base: Optional[int],
        selected: Optional[List[int]] = None,
    ) -> None:
        super().__init__(sum(int(r["n"]) for r in records))
        self._inner = inner
        self._records = records
        self._dtype_name = dtype_name
        self._store_base = store_base
        # Chunk pushdown (snapfleet): when set, only these record
        # indices are fetched — the rest of the assembly buffer stays
        # unwritten, which is safe because the scatter only ever reads
        # the slice boxes whose byte hulls selected these records
        # (pushdown.select_records). Offsets stay the ORIGINAL
        # cumulative offsets so selected bytes land where the scatter
        # expects them.
        self._selected = (
            list(range(len(records))) if selected is None else selected
        )
        self._remaining = len(self._selected)

    def build_reads(self) -> List[ReadReq]:
        from .chunkstore import chunk_object_path
        from .storage_plugin import make_ref_location

        offsets = [0]
        for rec in self._records:
            offsets.append(offsets[-1] + int(rec["n"]))
        reqs: List[ReadReq] = []
        for j, i in enumerate(self._selected):
            rec = self._records[i]
            path = chunk_object_path(rec["k"])
            if self._store_base is not None:
                path = make_ref_location(self._store_base, path)
            reqs.append(
                ReadReq(
                    path=path,
                    buffer_consumer=_ContentChunkConsumer(
                        self, rec, offsets[i], first=(j == 0)
                    ),
                )
            )
        return reqs

    async def absorb(
        self,
        rec: Dict[str, Any],
        offset: int,
        buf: BufferType,
        executor: Optional[Executor] = None,
    ) -> None:
        def _consume_part() -> None:
            from .chunkstore import decode_and_verify_chunk

            with _cprof.consume_section():
                self._ensure_buf()
                n = int(rec["n"])
                # Disjoint offsets: concurrent executor threads never
                # overlap. Identity-coded chunks decode ZERO-COPY
                # straight into the pooled assembly buffer (one verify
                # + one memcpy); codec chunks decode to a transient
                # then splice.
                out = memoryview(self._buf)[offset : offset + n]
                logical = decode_and_verify_chunk(
                    rec,
                    self._dtype_name,
                    buf,
                    profile=self._profile,
                    out=out,
                )
                if logical is not None:
                    with _cprof.substep(
                        self._profile, "reassemble", len(logical)
                    ):
                        out[: len(logical)] = logical

        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, _consume_part)
        else:
            _consume_part()
        with self._lock:
            self._remaining -= 1
            last = self._remaining == 0
        if last:
            try:
                await self._inner.consume_buffer(
                    memoryview(self._buf)[: self.nbytes], executor
                )
            finally:
                with _cprof.consume_section(), _cprof.substep(
                    self._profile, "staging_release", self.nbytes
                ):
                    # Pool return fires the scheduler budget re-credit
                    # exactly once, however many chunks shared it.
                    self._release_assembly_buffer()


class _ContentChunkConsumer(BufferConsumer):
    """One content chunk of a chunk-stored object."""

    def __init__(
        self,
        state: _ContentChunksReadState,
        rec: Dict[str, Any],
        offset: int,
        first: bool,
    ) -> None:
        self._state = state
        self._rec = rec
        self._offset = offset
        self._first = first

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        await self._state.absorb(self._rec, self._offset, buf, executor)

    def _part_cost(self) -> int:
        # Stored bytes held during the read + the decoded transient.
        rec = self._rec
        return int(rec.get("sn") or rec["n"]) + int(rec["n"])

    def get_consuming_cost_bytes(self) -> int:
        # The first chunk additionally carries the shared assembly
        # buffer (released when the LAST chunk lands) — the same
        # charging discipline as split whole-object reads.
        return self._part_cost() + (self._state.nbytes if self._first else 0)

    def get_deferred_cost_bytes(self) -> int:
        return self._state.nbytes if self._first else 0

    def set_cost_releaser(self, release: Callable[[int], None]) -> None:
        self._state.set_cost_releaser(release)

    @property
    def sort_key_bytes(self) -> int:
        # All of one object's chunk reads share the object's logical
        # size so the largest-first stable sort keeps the group
        # contiguous (same convention as split sub-reads).
        return self._state.nbytes


class ArrayRestorePlan:
    """Plans and finalizes the restore of one array entry into a template.

    The template supplies the target placement: a ``jax.Array`` template's
    sharding decides which global regions land on which local devices; a
    numpy/None template restores the full array on host.
    """

    def __init__(self, entry: Entry, template: Any, callback: Callable[[Any], None]):
        # Tuple tail: the stored object's own ArrayEntry — needed by the
        # content-chunk branch (chunkstore.py entries read per content
        # chunk instead of per stored object).
        if isinstance(entry, ShardedArrayEntry):
            dtype_name, shape = entry.dtype, list(entry.shape)
            chunks = [
                (
                    list(s.offsets),
                    list(s.sizes),
                    s.array.location,
                    s.array.checksum,
                    s.array.compression,
                    s.array,
                )
                for s in entry.shards
            ]
        elif isinstance(entry, ArrayEntry):
            dtype_name, shape = entry.dtype, list(entry.shape)
            chunks = [
                (
                    [0] * len(shape),
                    list(shape),
                    entry.location,
                    entry.checksum,
                    entry.compression,
                    entry,
                )
            ]
        else:
            raise TypeError(f"Not an array entry: {type(entry)}")
        self._entry = entry
        self._callback = callback
        self._dtype = str_to_dtype(dtype_name)
        self._shape = shape
        self._prng_impl = getattr(entry, "prng_impl", None)
        # Plan-build runs in the restoring thread, under the restore's
        # trace scope; finalize may instead run on the finalize pool or
        # an engine done-callback thread, whose fresh contexts would
        # attribute the assemble span to no trace. Capture now, adopt
        # in _finalize_now.
        self._trace_id = tracing.current_trace_id()

        if (
            self._prng_impl is not None
            and _is_jax_array(template)
            and _is_prng_key_array(template)
        ):
            # Saved payload is uint32 key data (trailing impl dim). The key
            # data view shares the keys' device layout, so use it as the
            # placement template and re-wrap after assembly.
            template = jax.random.key_data(template)
        self._template_is_jax = _is_jax_array(template) and not isinstance(
            template, np.ndarray
        )
        self._sharding = None
        regions: Dict[Tuple[Tuple[int, ...], Tuple[int, ...]], _TargetRegion] = {}
        if self._template_is_jax:
            if list(template.shape) != shape:
                raise RuntimeError(
                    f"Cannot restore array of shape {shape} into a template "
                    f"of shape {list(template.shape)}. Shapes must match; "
                    f"resharding (different mesh/partitioning) is supported, "
                    f"reshaping is not."
                )
            self._sharding = template.sharding
            for shard in template.addressable_shards:
                off, sz = index_to_offsets_sizes(shard.index, shape)
                key = (tuple(off), tuple(sz))
                if key not in regions:
                    # Device-template region buffers are pool-backed:
                    # device_put copies out of them, so the backing can
                    # be donated back to the pool at finalize. Host
                    # templates hand the buffer to the app — never
                    # pooled.
                    regions[key] = _TargetRegion(
                        off, sz, self._dtype, poolable=True
                    )
                regions[key].devices.append(shard.device)
        else:
            if template is not None and hasattr(template, "shape"):
                if list(template.shape) != shape and self._prng_impl is None:
                    raise RuntimeError(
                        f"Cannot restore array of shape {shape} into a template "
                        f"of shape {list(template.shape)}."
                    )
            off = [0] * len(shape)
            regions[(tuple(off), tuple(shape))] = _TargetRegion(off, shape, self._dtype)
        self._regions = list(regions.values())
        # Host-backed (CPU) devices can ALIAS a device_put numpy buffer
        # instead of copying it — donating such a region's pooled
        # backing would let a later restore overwrite the "restored"
        # array through the alias. Pool region buffers only when every
        # consumer device actually copies across a link.
        for region in self._regions:
            if any(
                getattr(d, "platform", None) == "cpu"
                for d in region.devices
            ):
                region._poolable = False
        self._chunks = chunks
        # Eager-finalize bookkeeping: the last chunk consumer to complete
        # triggers finalize() from its executor thread (or the overlap
        # engine's done-callback thread), so host→device assembly of
        # this array overlaps with other arrays' reads.
        self._outstanding = 0
        self._finalized = False
        self._lock = threading.Lock()
        self._profile = _cprof.current()
        # Overlap-engine bookkeeping: every engine future (streamed
        # chunks + early region puts) is registered here so finalize
        # surfaces transfer failures before publishing, and the
        # completion event closes the tiny future-resolved→callback-ran
        # window for the safety-net finalizer.
        self._transfers: List[Any] = []
        self._complete = threading.Event()
        self._finalize_done = threading.Event()
        self._finalize_error: Optional[BaseException] = None

    def _register_transfer(self, fut: Any) -> None:
        with self._lock:
            self._transfers.append(fut)

    def _on_req_done(self) -> None:
        with self._lock:
            self._outstanding -= 1
            if self._outstanding != 0:
                return
        self._complete.set()
        self.finalize()

    def _note_region_copy(self, region: _TargetRegion) -> None:
        """A chunk-copy consumer finished scattering into ``region``.
        When the region's last copy lands — and it is a single-device,
        engine-worthy region — dispatch its H2D on the overlap engine
        NOW instead of at plan finalize, so transfers of completed
        regions overlap chunks still reading/decoding."""
        with self._lock:
            region.pending_copies -= 1
            ready = region.pending_copies == 0
        if (
            not ready
            or not self._template_is_jax
            or region.device_chunks is not None
            or len(region.devices) != 1
            or region.buffer is None
            or region.nbytes < 2 * h2d_chunk_bytes()
        ):
            return
        fut = h2d_pipeline().submit(
            region.buffer, region.devices[0], profile=self._profile
        )
        region.early_put = fut
        self._register_transfer(fut)
        fut.add_done_callback(
            lambda f, region=region: self._early_put_done(region, f)
        )

    def _early_put_done(self, region: _TargetRegion, fut: Any) -> None:
        # The engine block_until_ready'd the transfer (or it failed):
        # either way the pooled backing is no longer read — donate it
        # back promptly so concurrent restores stop waiting on pool
        # capacity. The device array lives in the future for finalize.
        region.release_lease()

    def build_read_reqs(self) -> List[ReadReq]:
        reqs: List[ReadReq] = []
        n_logical = 0  # finalize triggers: one per chunk consumed
        split_threshold = _parallel_read_threshold()
        itemsize = np.dtype(self._dtype).itemsize
        strict = os.environ.get("TPUSNAPSHOT_STRICT_INTEGRITY") == "1"

        # Pass 1: overlaps of every chunk against every region.
        planned = []  # (chunk fields..., copies)
        for chunk_off, chunk_sz, location, chunk_checksum, compression, aentry in self._chunks:
            copies: List[Tuple[_TargetRegion, Tuple[slice, ...], Overlap]] = []
            for region in self._regions:
                ov = compute_overlap(chunk_off, chunk_sz, region.offsets, region.sizes)
                if ov is not None:
                    copies.append((region, ov.target_slices, ov))
            if copies:
                planned.append(
                    (chunk_off, chunk_sz, location, chunk_checksum,
                     compression, aentry, copies)
                )

        # Pass 2: pick the regions whose ENTIRE payload can stream to
        # device as it lands (VERDICT r4 #2: streaming used to engage
        # only when one object exactly covered one region; with
        # format-chunked dense arrays the dominant shape is SEVERAL
        # whole chunks tiling one single-device region, each chunk a
        # contiguous byte run of the region's flat layout). Streaming is
        # all-or-nothing per region — mixing streamed chunks with
        # host-buffer chunks would need a partial host buffer AND a
        # device concat for the same region.
        stream_region: Dict[int, Dict[int, int]] = {}  # id(region) -> {id(ov): flat_base}
        by_region: Dict[int, List] = {}
        for item in planned:
            for region, _, ov in item[6]:
                by_region.setdefault(id(region), []).append((item, ov))
        region_by_id = {id(r): r for r in self._regions}
        for rid, items in by_region.items():
            region = region_by_id[rid]
            if not (self._template_is_jax and len(region.devices) == 1):
                continue
            total = sum(
                _chunk_nbytes(it[1], itemsize) for it, _ in items
            )
            if total <= split_threshold:
                # Small regions keep the batched-device_put path: one
                # put per tiny shard beats many micro-streams.
                continue
            flat_bases: Dict[int, int] = {}
            ok = True
            for (chunk_off, chunk_sz, _, _, compression, aentry,
                 copies), ov in items:
                run = contiguous_byte_range(
                    region.sizes, ov.target_slices, itemsize
                )
                if (
                    compression is not None
                    # Content-chunked stored objects (chunkstore.py)
                    # assemble from per-chunk decodes on host — they
                    # cannot stream raw byte ranges to device.
                    or getattr(aentry, "chunks", None)
                    or len(copies) != 1
                    or run is None
                    or any(
                        sl.start != 0 or sl.stop != dim
                        for sl, dim in zip(ov.chunk_slices, chunk_sz)
                    )
                ):
                    ok = False
                    break
                flat_bases[id(ov)] = run[0]
            if ok:
                stream_region[rid] = flat_bases
                # The host-side region buffer is never touched on this
                # path (and, being lazy, was never allocated); the
                # device-chunk dict marks the region as streaming.
                region.device_chunks = {}

        # Pass 3: emit read requests. Adopting a zero-copy view is only
        # safe when the payload handed to the consumer is NOT a pooled
        # assembly buffer (the view would pin pool memory past its
        # release); direct read payloads always qualify.
        adopt_from_state_ok = staging_pool.get_staging_pool() is None
        for (chunk_off, chunk_sz, location, chunk_checksum, compression,
             aentry, copies) in planned:
            chunk_nbytes = _chunk_nbytes(chunk_sz, itemsize)
            content = getattr(aentry, "chunks", None)
            if content:
                # Content-chunked stored object (chunkstore.py): one
                # read per content chunk, each decoded (codec) and
                # content-verified in the consume executor — decode
                # overlaps the remaining reads — then scattered into
                # the overlapping regions exactly like a whole-object
                # read would be.
                for region, _rs, _ov in copies:
                    region.pending_copies += 1
                inner = _ChunkCopyConsumer(
                    view_shape=list(chunk_sz),
                    dtype=self._dtype,
                    copies=[
                        (region, region_slices, ov.chunk_slices)
                        for region, region_slices, ov in copies
                    ],
                    on_done=self._on_req_done,
                    allow_adopt=adopt_from_state_ok,
                    region_notify=self._note_region_copy,
                )
                n_logical += 1
                # Chunk pushdown: when this process's target slices
                # cover only part of the stored object (a differently-
                # meshed restore), cut the record list to those whose
                # byte ranges intersect the slices' C-order byte hulls
                # — each client fetches ≈ its shard fraction instead of
                # the whole object. Conservative (hull ⊇ strided
                # footprint) and disabled under strict integrity (the
                # skipped records can't be verified if never read).
                selected = None
                if (
                    not strict
                    and os.environ.get("TPUSNAPSHOT_CHUNK_PUSHDOWN")
                    != "0"
                ):
                    from .snapserve import pushdown

                    sizes = [int(r["n"]) for r in content]
                    sel = pushdown.select_records(
                        sizes,
                        pushdown.needed_intervals(
                            tuple(chunk_sz),
                            [
                                tuple(
                                    (sl.start, sl.stop)
                                    for sl in ov.chunk_slices
                                )
                                for _r, _rs, ov in copies
                            ],
                            itemsize,
                        ),
                    )
                    if 0 < len(sel.indices) < len(content):
                        selected = sel.indices
                        telemetry.counter(
                            _metric_names.CHUNK_PUSHDOWN_SKIPPED_BYTES
                        ).inc(sum(sizes) - sel.selected_bytes)
                state = _ContentChunksReadState(
                    inner,
                    content,
                    dtype_name=aentry.dtype,
                    store_base=getattr(aentry, "base", None),
                    selected=selected,
                )
                reqs.extend(state.build_reads())
                continue
            # Sub-range boundaries must land on element boundaries for
            # streaming device chunks.
            part = max(
                itemsize, split_threshold - (split_threshold % itemsize)
            )
            if (
                len(copies) == 1
                and id(copies[0][0]) in stream_region
            ):
                # Whole chunk streams into its region at its flat
                # offset, overlapping storage reads with H2D transfers.
                # The crc verifies incrementally over the in-order byte
                # stream — valid under TPUSNAPSHOT_STRICT_INTEGRITY.
                region0, _, ov0 = copies[0]
                stream = _StreamingSplitState(
                    chunk_nbytes,
                    region=region0,
                    dtype=np.dtype(self._dtype),
                    checksum=chunk_checksum,
                    on_done=self._on_req_done,
                    flat_base=stream_region[id(region0)][id(ov0)],
                    register_transfer=self._register_transfer,
                )
                n_logical += 1
                reqs.extend(stream.add_sub_reads(location, part))
                continue
            ranges = [
                contiguous_byte_range(chunk_sz, ov.chunk_slices, itemsize)
                for _, _, ov in copies
            ]
            partial = len(copies) > 1 or (
                ranges[0] is not None and (ranges[0][1] - ranges[0][0]) < chunk_nbytes
            )
            # Compressed chunks admit no ranged reads (byte offsets into the
            # compressed stream are meaningless): always read whole. Ranged
            # reads also cannot verify the chunk's checksum (it covers the
            # whole stored object) — TPUSNAPSHOT_STRICT_INTEGRITY=1 trades
            # the ranged-read bandwidth savings for full verification.
            if (
                compression is None
                and not strict
                and all(r is not None for r in ranges)
                and partial
            ):
                # Every overlap is a contiguous byte run of the chunk: issue
                # one ranged read per target region (parallel, and each
                # process/device fetches only the bytes it needs).
                for (region, region_slices, ov), rng in zip(copies, ranges):
                    full = tuple(slice(0, s) for s in ov.sizes)
                    sub_nbytes = rng[1] - rng[0]
                    split = sub_nbytes > split_threshold
                    region.pending_copies += 1
                    consumer = _ChunkCopyConsumer(
                        view_shape=list(ov.sizes),
                        dtype=self._dtype,
                        copies=[(region, region_slices, full)],
                        on_done=self._on_req_done,
                        # Split payloads arrive as pooled assembly
                        # views; direct ranged payloads may adopt.
                        allow_adopt=(not split) or adopt_from_state_ok,
                        region_notify=self._note_region_copy,
                    )
                    n_logical += 1
                    if split:
                        # A large contiguous sub-range is still one
                        # stream: split it the same way as whole objects
                        # (offsets shifted by the range start).
                        state = _SplitObjectReadState(sub_nbytes, consumer)
                        for sub in state.add_sub_reads(
                            location, split_threshold
                        ):
                            sub.byte_range = (
                                rng[0] + sub.byte_range[0],
                                rng[0] + sub.byte_range[1],
                            )
                            reqs.append(sub)
                    else:
                        reqs.append(
                            ReadReq(
                                path=location,
                                buffer_consumer=consumer,
                                byte_range=rng,
                            )
                        )
            else:
                # Non-contiguous overlap somewhere: read the chunk once and
                # scatter into every overlapping region. Whole-object reads
                # can verify the stored checksum (ranged reads cannot).
                def _whole_consumer(allow_adopt: bool = True):
                    for region, _rs, _ov in copies:
                        region.pending_copies += 1
                    return _ChunkCopyConsumer(
                        view_shape=list(chunk_sz),
                        dtype=self._dtype,
                        copies=[
                            (region, region_slices, ov.chunk_slices)
                            for region, region_slices, ov in copies
                        ],
                        checksum=chunk_checksum,
                        compression=compression,
                        on_done=self._on_req_done,
                        allow_adopt=allow_adopt,
                        region_notify=self._note_region_copy,
                    )

                n_logical += 1
                if compression is None and chunk_nbytes > split_threshold:
                    # Large whole-object read → concurrent ranged
                    # sub-reads; the checksum is verified over the
                    # assembled payload, so this stays valid under
                    # TPUSNAPSHOT_STRICT_INTEGRITY. (Compressed objects
                    # can't split: their stored size is not derivable
                    # from the manifest shape. Streaming-to-device was
                    # decided per-REGION in pass 2; chunks landing here
                    # reassemble on host.)
                    state = _SplitObjectReadState(
                        chunk_nbytes, _whole_consumer(adopt_from_state_ok)
                    )
                    reqs.extend(state.add_sub_reads(location, part))
                else:
                    reqs.append(
                        ReadReq(
                            path=location,
                            buffer_consumer=_whole_consumer(),
                        )
                    )
        with self._lock:
            # One finalize trigger per logical chunk (a split chunk's
            # inner consumer fires on_done once, not once per sub-read).
            self._outstanding = n_logical
        if n_logical == 0:
            self._complete.set()
        return reqs

    def finalize(self) -> None:
        # Normally triggered eagerly by the last chunk consumer (or the
        # overlap engine's last done-callback); the finalizer returned
        # by prepare_read is the safety net for plans with zero read
        # requests — and, post-fastlane, the thread that surfaces a
        # failed overlap-engine transfer. The latch is BLOCKING, not
        # merely idempotent: an eager finalize may be mid-assembly on
        # an engine thread the scheduler never awaited, so a losing
        # caller must wait for publication (and re-raise the winner's
        # failure) before the restore continues past its finalizers.
        run = False
        with self._lock:
            if not self._finalized:
                self._finalized = True
                run = True
        if not run:
            self._finalize_done.wait()
            err = self._finalize_error
            if err is not None:
                raise err
            return
        if _on_h2d_engine_thread():
            # Never block an engine worker in _await_pipeline: it may
            # be the only worker able to run the futures being awaited
            # (deadlock at depth 1). Hop to the finalize pool; waiters
            # block on _finalize_done as usual and re-raise any error.
            _get_finalize_pool().submit(self._finalize_now)
            return
        self._finalize_now()

    def _finalize_now(self) -> None:
        try:
            self._await_pipeline()
            with tracing.adopt_trace(self._trace_id), tracing.span(
                "assemble"
            ):
                self._finalize_impl()
        except BaseException as e:  # noqa: BLE001 — SimulatedCrash must surface
            # When this runs on the finalize pool the raise lands in an
            # unobserved future; the error still reaches the restore
            # thread via _finalize_error at the safety-net finalizer.
            self._finalize_error = e
            raise
        finally:
            self._finalize_done.set()

    def _await_pipeline(self) -> None:
        """Wait out (and surface errors from) every overlap-engine
        transfer this plan dispatched, BEFORE anything is published. A
        transfer failure (including faultline's SimulatedCrash) or an
        incomplete pipeline raises here — the restore fails with the
        template untouched, never with a torn leaf."""
        with self._lock:
            transfers = list(self._transfers)
        for fut in transfers:
            fut.result()  # re-raises transfer errors
        with self._lock:
            outstanding = self._outstanding
        if outstanding == 0:
            return
        # All registered futures resolved; the only legitimate gap is a
        # done-callback still running on another thread. Anything past
        # a generous wait is a pipeline bug — refuse to assemble.
        if not self._complete.wait(timeout=60.0):
            raise RuntimeError(
                f"streaming restore pipeline incomplete: "
                f"{outstanding} chunk(s) never finished "
                f"decode/verify/transfer — refusing to publish a torn "
                f"leaf"
            )

    def _finalize_impl(self) -> None:
        if self._template_is_jax:
            # Streamed regions (device_chunks set) noted their H2D as
            # per-chunk h2d_overlap on the engine, and early-dispatched
            # regions (early_put set) likewise — counting them again
            # here would double the profile's transfer bytes. Only
            # regions still placed from host buffers at finalize
            # transfer bytes now.
            with _cprof.substep(
                self._profile,
                "device_put",
                sum(
                    r.nbytes * max(1, len(r.devices))
                    for r in self._regions
                    if r.device_chunks is None and r.early_put is None
                ),
            ):
                self._finalize_jax()
            return
        out = self._regions[0].ensure_buffer(self._profile)
        if not out.flags.writeable:
            # Adopted zero-copy payload views are read-only; host
            # restores hand back writable arrays (apps mutate restored
            # numpy state in place).
            out = out.copy()
        if self._prng_impl is not None:
            out = jax.random.wrap_key_data(out, impl=self._prng_impl)
        self._callback(out)

    def _finalize_jax(self) -> None:
        # One batched device_put for all shards: the runtime issues the
        # host→device transfers in parallel (a serial per-shard loop is
        # memcpy/PCIe-latency bound). Large buffers route through the
        # chunked H2D path instead — a single big transfer leaves
        # ~40% of the measured link bandwidth on the table
        # (ops/transfer.py chunked_device_put).
        buffers = []
        devices = []
        prebuilt: Dict[int, Any] = {}
        lease_slots: List[Tuple[_TargetRegion, int]] = []
        for region in self._regions:
            for device in region.devices:
                if region.early_put is not None:
                    # The overlap engine already placed this region
                    # (chunk-granular overlap: dispatched the moment its
                    # last copy landed); the future is resolved — errors
                    # were surfaced by _await_pipeline — and the pooled
                    # backing was donated back in the done-callback.
                    prebuilt[len(buffers)] = region.early_put.result()
                    buffers.append(None)
                    devices.append(device)
                    continue
                if region.device_chunks is not None:
                    # Streaming reads: the bytes are already on
                    # device as 1-D chunks keyed by flat offset —
                    # concatenate in offset order + reshape there
                    # instead of a host device_put.
                    ordered = [
                        region.device_chunks[k]
                        for k in sorted(region.device_chunks)
                    ]
                    flat = (
                        jnp.concatenate(ordered)
                        if len(ordered) > 1
                        else ordered[0]
                    )
                    assembled = jnp.reshape(flat, tuple(region.sizes))
                    prebuilt[len(buffers)] = assembled
                    # Free the per-chunk arrays eagerly and return
                    # the TRANSIENT half of the device reservation
                    # (the assembled array's half stays charged — it
                    # remains resident). Wait for the concat to
                    # actually execute first: releasing at dispatch
                    # time would re-admit new streams while chunks
                    # and result still coexist.
                    region.device_chunks = None
                    del flat, ordered
                    if region.device_releases:
                        try:
                            assembled.block_until_ready()
                        # Only times the budget release; a real
                        # failure re-raises at device_put below.
                        except Exception:  # snapcheck: disable=swallowed-exception -- timing wait
                            pass
                        releases, region.device_releases = (
                            region.device_releases,
                            [],
                        )
                        for cb, nbytes in releases:
                            cb(nbytes)
                if region._lease is not None:
                    lease_slots.append((region, len(buffers)))
                buffers.append(region.buffer)
                devices.append(device)
        chunk_mask = [
            False
            if i in prebuilt
            else should_chunk_h2d(buf, dev)
            for i, (buf, dev) in enumerate(zip(buffers, devices))
        ]
        arrays: List[Any] = [None] * len(buffers)
        for i, arr in prebuilt.items():
            arrays[i] = arr
        # Large buffers stream chunked; the small remainder still
        # goes in ONE batched device_put (a per-buffer loop over
        # many small shards is exactly the latency-bound path the
        # batching exists to avoid).
        small = [
            i
            for i, chunked in enumerate(chunk_mask)
            if not chunked and i not in prebuilt
        ]
        if small:
            put = jax.device_put(
                [buffers[i] for i in small],
                [devices[i] for i in small],
            )
            for i, arr in zip(small, put):
                arrays[i] = arr
        for i, chunked in enumerate(chunk_mask):
            if chunked:
                arrays[i] = chunked_device_put(buffers[i], devices[i])
        out = jax.make_array_from_single_device_arrays(
            tuple(self._shape), self._sharding, arrays
        )
        if self._prng_impl is not None:
            out = jax.random.wrap_key_data(out, impl=self._prng_impl)
        self._callback(out)
        if lease_slots:
            # Batched donation: pooled region buffers return to the
            # pool in ONE pass — after the runtime finished reading
            # them (device_put can return before the copy-out), so a
            # reuse by a concurrent restore can never alias an
            # in-flight transfer. Publication (the callback above) was
            # not delayed by this wait.
            with _cprof.substep(
                self._profile,
                "staging_release",
                sum(r.nbytes for r, _ in lease_slots),
            ):
                try:
                    jax.block_until_ready(
                        [arrays[i] for _, i in lease_slots]
                    )
                except Exception:  # snapcheck: disable=swallowed-exception -- donation wait; a transfer failure keeps the lease unreleased (GC net)
                    return
                seen = set()
                for region, _ in lease_slots:
                    if id(region) not in seen:
                        seen.add(id(region))
                        region.release_lease()


def _chunk_nbytes(sizes: List[int], itemsize: int) -> int:
    n = itemsize
    for s in sizes:
        n *= s
    return n


def _prepare_dense_array_write(
    arr: Any,
    logical_path: str,
    rank: int,
    replicated: bool,
    compression: Optional[str] = None,
    eager_host_copy: bool = True,
) -> Tuple[Entry, List[WriteReq]]:
    prng_impl = None
    if _is_prng_key_array(arr):
        prng_impl = str(jax.random.key_impl(arr))
        arr = jax.random.key_data(arr)
    dtype = np.dtype(arr.dtype)
    dtype_name = dtype_to_str(arr.dtype)
    nbytes = _chunk_nbytes(list(arr.shape), dtype.itemsize)
    if nbytes > MAX_CHUNK_SIZE_BYTES:
        # Large dense arrays chunk at the FORMAT level into multiple
        # storage objects, exactly like sharded shards (VERDICT r4 #3:
        # a single multi-GiB object means single-stream writes and
        # full-buffer staging; split/streaming reads and GCS composite
        # uploads only papered over it per-backend). Reference analog:
        # the ≤512 MB shard subdivision at io_preparer.py:38,40-72 —
        # applied here to the dense path the reference never chunks.
        return _prepare_chunked_dense_write(
            arr,
            logical_path,
            rank,
            replicated,
            dtype,
            prng_impl,
            compression,
            eager_host_copy,
        )
    location = get_storage_path(rank, logical_path, replicated)
    entry = ArrayEntry(
        location=location,
        serializer=ARRAY_SERIALIZER,
        dtype=dtype_name,
        shape=list(arr.shape),
        replicated=replicated,
    )
    if prng_impl is not None:
        entry.prng_impl = prng_impl
    stager = ArrayBufferStager(
        arr, entry=entry, compression=compression, eager_host_copy=eager_host_copy
    )
    return entry, [WriteReq(path=location, buffer_stager=stager)]


def _prepare_chunked_dense_write(
    arr: Any,
    logical_path: str,
    rank: int,
    replicated: bool,
    dtype: np.dtype,
    prng_impl: Optional[str],
    compression: Optional[str],
    eager_host_copy: bool,
) -> Tuple[ShardedArrayEntry, List[WriteReq]]:
    """Plan a > ``MAX_CHUNK_SIZE_BYTES`` dense array as a chunked
    ``ShardedArrayEntry`` whose one-region shards are ordinary storage
    objects: staging holds chunk-sized host memory, writes fan out
    across the backend's concurrency, and restores split/stream without
    backend tricks. The entry's ownership category (``replicated`` /
    ``per_rank``) preserves the dense entry's elasticity semantics —
    chunk locations stay inside the owner's storage namespace
    (``<rank>/…`` / ``replicated/…``), so two ranks' same-named per-rank
    values can never collide on storage paths."""
    shape = list(arr.shape)
    # Chunk objects live under their own top-level namespace
    # ("chunked/<owner>/…"), disjoint from every dense leaf location
    # ("<rank>/…", "replicated/…") — a leaf literally named
    # "<path>_<offsets>" must never collide with a sibling's chunk
    # (r5 review finding). The ordinal suffix "__chunk_<i>" is
    # unambiguous by construction: every chunk location ends with it,
    # and stripping the final suffix recovers the logical path even
    # when another leaf's name embeds a chunk-like suffix.
    owner = "replicated" if replicated else str(rank)
    base = f"chunked/{owner}/{logical_path}"
    pieces = subdivide(
        [0] * len(shape), shape, dtype.itemsize, MAX_CHUNK_SIZE_BYTES
    )
    shards: List[Shard] = []
    reqs: List[WriteReq] = []
    for i, (c_off, c_sz) in enumerate(pieces):
        location = f"{base}__chunk_{i}"
        chunk_entry = ArrayEntry(
            location=location,
            serializer=ARRAY_SERIALIZER,
            dtype=dtype_to_str(arr.dtype),
            shape=list(c_sz),
            replicated=False,
        )
        shards.append(
            Shard(offsets=list(c_off), sizes=list(c_sz), array=chunk_entry)
        )
        local = tuple(slice(o, o + s) for o, s in zip(c_off, c_sz))
        stager = ArrayBufferStager(
            arr,
            chunk_slices=local,
            nbytes=_chunk_nbytes(c_sz, dtype.itemsize),
            entry=chunk_entry,
            compression=compression,
            eager_host_copy=eager_host_copy,
        )
        reqs.append(WriteReq(path=location, buffer_stager=stager))
    entry = ShardedArrayEntry(
        dtype=dtype_to_str(arr.dtype),
        shape=shape,
        shards=shards,
        prng_impl=prng_impl,
        replicated=replicated,
        per_rank=not replicated,
    )
    return entry, reqs


def _prepare_sharded_array_write(
    arr: jax.Array,
    logical_path: str,
    compression: Optional[str] = None,
    eager_host_copy: bool = True,
) -> Tuple[ShardedArrayEntry, List[WriteReq]]:
    prng_impl = None
    if _is_prng_key_array(arr):
        # Persist sharded key arrays through their uint32 key data, which
        # shares the keys' sharding (the trailing impl dim is unsharded).
        prng_impl = str(jax.random.key_impl(arr))
        arr = jax.random.key_data(arr)
    dtype = np.dtype(arr.dtype)
    dtype_name = dtype_to_str(dtype)
    global_shape = list(arr.shape)
    shards: List[Shard] = []
    reqs: List[WriteReq] = []
    for shard in arr.addressable_shards:
        if shard.replica_id != 0:
            continue  # exactly one process/device persists each region
        off, sz = index_to_offsets_sizes(shard.index, global_shape)
        pieces = subdivide(off, sz, dtype.itemsize, MAX_CHUNK_SIZE_BYTES)
        whole = len(pieces) == 1
        for c_off, c_sz in pieces:
            location = chunk_location(logical_path, c_off)
            entry = ArrayEntry(
                location=location,
                serializer=ARRAY_SERIALIZER,
                dtype=dtype_name,
                shape=list(c_sz),
                replicated=False,
            )
            shards.append(Shard(offsets=list(c_off), sizes=list(c_sz), array=entry))
            if whole:
                stager = ArrayBufferStager(
                    shard.data,
                    entry=entry,
                    compression=compression,
                    eager_host_copy=eager_host_copy,
                )
            else:
                local = tuple(
                    slice(co - o, co - o + cs) for co, cs, o in zip(c_off, c_sz, off)
                )
                stager = ArrayBufferStager(
                    shard.data,
                    chunk_slices=local,
                    nbytes=_chunk_nbytes(c_sz, dtype.itemsize),
                    entry=entry,
                    compression=compression,
                )
            reqs.append(WriteReq(path=location, buffer_stager=stager))
    return (
        ShardedArrayEntry(
            dtype=dtype_name,
            shape=global_shape,
            shards=shards,
            prng_impl=prng_impl,
        ),
        reqs,
    )


def prepare_write(
    obj: Any,
    logical_path: str,
    rank: int,
    replicated: bool = False,
    compression: Optional[str] = None,
    eager_host_copy: bool = True,
) -> Tuple[Entry, List[WriteReq]]:
    """Plan the persistence of one leaf value.

    Reference analog: io_preparer.py:345-374. Returns the manifest entry
    and the write requests this process is responsible for. For replicated
    values the caller (Snapshot) drops the write reqs on non-owner ranks.
    ``eager_host_copy=False`` (async takes) suppresses prepare-time
    device→host copy kickoff — a device-staged cut would never consume it.
    """
    # numpy scalars subclass Python numbers (np.float64 is a float), so the
    # array check must run before the primitive check.
    if isinstance(obj, (np.generic, np.ndarray)):
        return _prepare_dense_array_write(
            np.asarray(obj), logical_path, rank, replicated, compression
        )
    if isinstance(obj, _PRIMITIVE_TYPES):
        return PrimitiveEntry.from_value(obj, replicated=replicated), []
    if _is_jax_array(obj) and _is_partitioned(obj):
        return _prepare_sharded_array_write(
            obj, logical_path, compression, eager_host_copy
        )
    if _is_jax_array(obj):
        return _prepare_dense_array_write(
            obj, logical_path, rank, replicated, compression, eager_host_copy
        )
    location = get_storage_path(rank, logical_path, replicated)
    entry = ObjectEntry(
        location=location, serializer=OBJECT_SERIALIZER, replicated=replicated
    )
    stager = ObjectBufferStager(obj, entry=entry, compression=compression)
    return entry, [WriteReq(path=location, buffer_stager=stager)]


def prepare_read(
    entry: Entry,
    template: Any,
    callback: Callable[[Any], None],
) -> Tuple[List[ReadReq], List[Callable[[], None]]]:
    """Plan the restore of one leaf value into ``template``'s placement.

    Reference analog: io_preparer.py:377-401. Returns read requests plus
    finalizers to run after all reads complete (device assembly).
    """
    if isinstance(entry, PrimitiveEntry):
        callback(entry.get_value())
        return [], []
    if isinstance(entry, ObjectEntry):
        consumer = ObjectBufferConsumer(
            callback, checksum=entry.checksum, compression=entry.compression
        )
        return [ReadReq(path=entry.location, buffer_consumer=consumer)], []
    if isinstance(entry, (ArrayEntry, ShardedArrayEntry)):
        plan = ArrayRestorePlan(entry, template, callback)
        return plan.build_read_reqs(), [plan.finalize]
    raise TypeError(f"Cannot prepare read for entry type {type(entry)}")
