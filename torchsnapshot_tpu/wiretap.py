"""snapflight — shared wire observability for every transport.

One layer, three stacks: the snapserve read plane (server + client,
including the fleet ladder), the snapwire hot-tier transport/peer pair,
and the repair/membership probes all report RPCs here instead of
growing per-stack copies. Per ``(transport, op)`` — the same key the
snapproto contract map (``docs/PROTOCOL.md``) prints as *telemetry
key* — the layer records:

- log2-bucketed latency histograms and bytes in/out,
- a bounded result taxonomy (``ok`` / error kind / ``deadline_miss`` /
  per-attempt retries),
- **deadline margin**: the fraction of the per-RPC budget the call
  consumed (1.0 == the whole deadline). Margin is the signal that says
  which hand-tuned ``TPUSNAPSHOT_*_DEADLINE_S`` /
  ``TPUSNAPSHOT_*_TIMEOUT_S`` knobs are mis-sized *before* an op blows
  its budget — doctor's ``deadline-margin-collapsing`` rule and the
  ops CLI's deadline-pressure table read it.

Everything mirrors into the process metrics registry (the
``tpusnapshot_wire_*`` catalog entries) AND into module-local
aggregates that support cheap windowed deltas (``window_begin`` /
``window_collect``) for flight reports and bench blocks, mirroring the
hot tier's ``replication_stats_begin`` pattern.

**Flight recorder.** Always on: a bounded ring of the last N RPC
events (trace id, op, peer, latency, outcome, attempt). On fault /
degrade / process-exit hooks the ring dumps to a
``*.blackbox.jsonl`` statusfile so a crash leaves evidence in the
*survivors* — the SIGKILL'd process never gets to write anything, its
peers' blackboxes carry its last known RPCs. Dump lines use the
ledger's crc envelope (``telemetry.ledger.encode_line``), so a torn
tail from a dump interrupted mid-write is skipped by the same
discipline ``parse_ledger_bytes`` applies to the ledger itself, and
events are joinable to a merged snapxray trace by trace id.

Hot-path cost is one lock acquire + dict bumps per RPC; the blackbox
only touches disk on the hooks. Recording must never take a transport
down: callers wrap ``record`` in best-effort guards or call it after
the RPC outcome is already decided.
"""

import atexit
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from . import tracing
from .telemetry import memwatch
from .telemetry.metrics import (
    REGISTRY,
    WIRE_BLACKBOX_DUMPS,
    WIRE_DEADLINE_MARGIN,
    WIRE_DEADLINE_MISSES,
    WIRE_OP_BYTES,
    WIRE_OP_RESULTS,
    WIRE_OP_SECONDS,
    WIRE_RETRIES,
    bucket_le,
)
from .utils.env import env_float, env_int

logger = logging.getLogger(__name__)

# Ring capacity (events kept in memory for the blackbox dump).
_RING_ENV_VAR = "TPUSNAPSHOT_WIRETAP_RING"
_DEFAULT_RING = 512
# Blackbox directory; falls back to the live-ops statusfile directory.
_DIR_ENV_VAR = "TPUSNAPSHOT_WIRETAP_DIR"
_PROGRESS_DIR_ENV_VAR = "TPUSNAPSHOT_PROGRESS_DIR"
# Degrade storms (a dying peer fails every ladder rung) must not turn
# into a dump-per-failure disk storm: dumps are rate-limited per path.
_DUMP_INTERVAL_ENV_VAR = "TPUSNAPSHOT_WIRETAP_DUMP_INTERVAL_S"
_DEFAULT_DUMP_INTERVAL_S = 1.0

_TRACE_ROLE_ENV_VAR = "TPUSNAPSHOT_TRACE_ROLE"

# Bounded result taxonomy. Wire error kinds map 1:1; anything novel is
# clamped to "error" so the label set stays enumerable.
OUTCOMES = frozenset(
    {
        "ok",
        "deadline_miss",
        "transport",
        "not_found",
        "range",
        "bad_request",
        "backend",
        "bad_frame",
        "stale_basis",
        "corrupt_push",
        "error",
    }
)

# Server-reported error kinds that pass through as outcome labels:
# the wire taxonomy (error_to_wire) plus the snapwire push verdicts.
_WIRE_ERROR_KINDS = frozenset(
    {
        "not_found",
        "range",
        "bad_request",
        "backend",
        "bad_frame",
        "stale_basis",
        "corrupt_push",
    }
)


def classify_error(exc: BaseException) -> str:
    """Map a client-side RPC failure into the bounded outcome taxonomy
    using the same structural taxonomy :mod:`.wire` marshals."""
    import asyncio

    from . import wire

    if isinstance(exc, FileNotFoundError):
        return "not_found"
    if isinstance(exc, wire.InvalidRange):
        return "range"
    if isinstance(exc, wire.RemoteServerError):
        return "backend"
    if isinstance(exc, wire.ProtocolError):
        return "bad_frame"
    # Before the OSError umbrella: an expired per-RPC wait IS a
    # deadline miss (builtins.TimeoutError subclasses OSError).
    if isinstance(exc, (asyncio.TimeoutError, TimeoutError)):
        return "deadline_miss"
    if isinstance(
        exc,
        (
            ConnectionError,
            OSError,
            EOFError,
            asyncio.IncompleteReadError,
        ),
    ):
        return "transport"
    return "error"


def outcome_from_wire_error(error: Optional[Dict[str, Any]]) -> str:
    """The outcome label for a server-reported wire error dict."""
    kind = (error or {}).get("kind")
    return kind if kind in _WIRE_ERROR_KINDS else "error"


def _new_agg() -> Dict[str, Any]:
    return {
        "count": 0,
        "seconds": 0.0,
        "bytes_in": 0,
        "bytes_out": 0,
        "lat_buckets": {},
        "outcomes": {},
        "retries": 0,
        "deadline_misses": 0,
        "margin_buckets": {},
        "margin_sum": 0.0,
        "margin_max": 0.0,
        "margin_count": 0,
        "deadline_s": None,
    }


_LOCK = threading.Lock()
_AGG: Dict[Tuple[str, str], Dict[str, Any]] = {}
_RING: Deque[Dict[str, Any]] = deque(maxlen=env_int(_RING_ENV_VAR, _DEFAULT_RING))
_ATEXIT_REGISTERED = False
_LAST_DUMP: Dict[str, float] = {}

# snapmem: the flight-recorder ring is a real (if small) RAM consumer —
# a few hundred event dicts. Report it as a polled domain with a fixed
# per-event estimate; the point is the registry's completeness (every
# byte-capped structure visible in one table), not byte-exact dict
# sizing. Evictable: the ring drops its tail by design.
_RING_EVENT_EST_BYTES = 512


def _mem_provider() -> Tuple[int, int, Optional[int]]:
    with _LOCK:
        used = len(_RING) * _RING_EVENT_EST_BYTES
        cap = (_RING.maxlen or 0) * _RING_EVENT_EST_BYTES
    return used, 0, cap


memwatch.register_provider("wiretap.ring", _mem_provider)


def reset() -> None:
    """Drop all aggregates and ring contents; re-read the ring size
    (tests flip the env knobs between cases)."""
    global _RING
    with _LOCK:
        _AGG.clear()
        _LAST_DUMP.clear()
        _RING = deque(maxlen=env_int(_RING_ENV_VAR, _DEFAULT_RING))
    memwatch.register_provider("wiretap.ring", _mem_provider)


def _register_atexit() -> None:
    global _ATEXIT_REGISTERED
    if _ATEXIT_REGISTERED:
        return
    _ATEXIT_REGISTERED = True
    atexit.register(_dump_at_exit)


def _dump_at_exit() -> None:
    try:
        dump_blackbox("exit")
    except Exception as e:  # pragma: no cover - exit path must never raise
        logger.debug(f"exit blackbox dump failed: {e!r}")


def record(
    transport: str,
    op: str,
    *,
    seconds: float,
    outcome: str = "ok",
    bytes_in: int = 0,
    bytes_out: int = 0,
    attempt: int = 0,
    deadline_s: Optional[float] = None,
    peer: Optional[str] = None,
    trace_id: Optional[str] = None,
) -> None:
    """Record one wire RPC (one attempt, client- or server-side).

    ``attempt`` is 0 for a first try, N for the Nth retry — retried
    attempts are individually attributable instead of folding into one
    span. ``deadline_s`` is the per-RPC budget this attempt ran under;
    when present the deadline margin ``seconds / deadline_s`` is
    recorded too. ``trace_id`` defaults to the ambient snapxray trace.
    """
    if outcome not in OUTCOMES:
        outcome = "error"
    if trace_id is None:
        trace_id = tracing.current_trace_id()
    seconds = max(0.0, float(seconds))
    margin: Optional[float] = None
    if deadline_s is not None and deadline_s > 0:
        margin = seconds / deadline_s
        if outcome == "deadline_miss" and margin < 1.0:
            margin = 1.0

    key = (transport, op)
    event = {
        "t": round(time.time(), 3),
        "transport": transport,
        "op": op,
        "peer": peer,
        "seconds": round(seconds, 6),
        "outcome": outcome,
        "attempt": attempt,
        "trace": trace_id,
        "bytes_in": int(bytes_in),
        "bytes_out": int(bytes_out),
        "margin": None if margin is None else round(margin, 4),
    }

    with _LOCK:
        agg = _AGG.get(key)
        if agg is None:
            agg = _AGG[key] = _new_agg()
        agg["count"] += 1
        agg["seconds"] += seconds
        agg["bytes_in"] += int(bytes_in)
        agg["bytes_out"] += int(bytes_out)
        le = bucket_le(seconds)
        agg["lat_buckets"][le] = agg["lat_buckets"].get(le, 0) + 1
        agg["outcomes"][outcome] = agg["outcomes"].get(outcome, 0) + 1
        if attempt > 0:
            agg["retries"] += 1
        if outcome == "deadline_miss":
            agg["deadline_misses"] += 1
        if margin is not None:
            mle = bucket_le(margin)
            agg["margin_buckets"][mle] = agg["margin_buckets"].get(mle, 0) + 1
            agg["margin_sum"] += margin
            agg["margin_count"] += 1
            if margin > agg["margin_max"]:
                agg["margin_max"] = margin
        if deadline_s is not None:
            agg["deadline_s"] = float(deadline_s)
        _RING.append(event)

    REGISTRY.histogram(WIRE_OP_SECONDS, transport=transport, op=op).observe(
        seconds
    )
    if bytes_in:
        REGISTRY.counter(
            WIRE_OP_BYTES, transport=transport, op=op, dir="in"
        ).inc(int(bytes_in))
    if bytes_out:
        REGISTRY.counter(
            WIRE_OP_BYTES, transport=transport, op=op, dir="out"
        ).inc(int(bytes_out))
    REGISTRY.counter(
        WIRE_OP_RESULTS, transport=transport, op=op, result=outcome
    ).inc()
    if attempt > 0:
        REGISTRY.counter(WIRE_RETRIES, transport=transport, op=op).inc()
    if outcome == "deadline_miss":
        REGISTRY.counter(
            WIRE_DEADLINE_MISSES, transport=transport, op=op
        ).inc()
    if margin is not None:
        REGISTRY.histogram(
            WIRE_DEADLINE_MARGIN, transport=transport, op=op
        ).observe(margin)

    _register_atexit()


def note_degrade(reason: str, peer: Optional[str] = None) -> None:
    """A transport latched a peer/member down (or the repair plane
    declared a host lost): stamp a mark into the ring and flush the
    blackbox — this is exactly the moment postmortem evidence is worth
    a statusfile write."""
    mark = {
        "t": round(time.time(), 3),
        "mark": reason,
        "peer": peer,
        "trace": tracing.current_trace_id(),
    }
    with _LOCK:
        _RING.append(mark)
    dump_blackbox(reason)


# --------------------------------------------------------------- windows


def _copy_agg() -> Dict[Tuple[str, str], Dict[str, Any]]:
    with _LOCK:
        return {
            key: {
                **agg,
                "lat_buckets": dict(agg["lat_buckets"]),
                "outcomes": dict(agg["outcomes"]),
                "margin_buckets": dict(agg["margin_buckets"]),
            }
            for key, agg in _AGG.items()
        }


def window_begin() -> Dict[Tuple[str, str], Dict[str, Any]]:
    """Opaque token for :func:`window_collect` — flight reports open
    one per take/restore, bench blocks one per block."""
    return _copy_agg()


def _quantile_from_buckets(
    buckets: Dict[float, int], count: int, q: float
) -> Optional[float]:
    """Conservative quantile: the log2 bucket upper bound at rank
    ``ceil(q * count)``."""
    if count <= 0:
        return None
    rank = max(1, int(q * count + 0.9999999))
    seen = 0
    for le in sorted(buckets):
        seen += buckets[le]
        if seen >= rank:
            return le
    return max(buckets) if buckets else None


def _diff_buckets(
    now: Dict[float, int], then: Dict[float, int]
) -> Dict[float, int]:
    out = {}
    for le, n in now.items():
        d = n - then.get(le, 0)
        if d > 0:
            out[le] = d
    return out


def _diff_counts(now: Dict[str, int], then: Dict[str, int]) -> Dict[str, int]:
    out = {}
    for k, n in now.items():
        d = n - then.get(k, 0)
        if d > 0:
            out[k] = d
    return out


def _op_summary(
    agg: Dict[str, Any], base: Optional[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    if base is None:
        base = _new_agg()
    count = agg["count"] - base["count"]
    if count <= 0:
        return None
    lat = _diff_buckets(agg["lat_buckets"], base["lat_buckets"])
    out: Dict[str, Any] = {
        "count": count,
        "seconds": round(agg["seconds"] - base["seconds"], 6),
        "bytes_in": agg["bytes_in"] - base["bytes_in"],
        "bytes_out": agg["bytes_out"] - base["bytes_out"],
        "p50_s": _quantile_from_buckets(lat, count, 0.50),
        "p99_s": _quantile_from_buckets(lat, count, 0.99),
        "outcomes": _diff_counts(agg["outcomes"], base["outcomes"]),
        "retries": agg["retries"] - base["retries"],
        "deadline_misses": agg["deadline_misses"] - base["deadline_misses"],
    }
    if agg["deadline_s"] is not None:
        out["deadline_s"] = agg["deadline_s"]
    mcount = agg["margin_count"] - base["margin_count"]
    if mcount > 0:
        mbuckets = _diff_buckets(agg["margin_buckets"], base["margin_buckets"])
        out["margin_p99"] = _quantile_from_buckets(mbuckets, mcount, 0.99)
        # max over the window is unknowable from cumulative state once
        # the baseline saw a larger value; the cumulative max is still
        # the honest upper bound.
        out["margin_max"] = round(agg["margin_max"], 4)
    return out


def window_collect(
    token: Dict[Tuple[str, str], Dict[str, Any]],
) -> Dict[str, Any]:
    """Per-op deltas since ``window_begin``, keyed by telemetry key
    (``transport/op``). Empty dict when nothing crossed the wire."""
    now = _copy_agg()
    ops: Dict[str, Any] = {}
    for key, agg in sorted(now.items()):
        block = _op_summary(agg, token.get(key))
        if block:
            ops["/".join(key)] = block
    return ops


def summary() -> Dict[str, Any]:
    """Cumulative per-op summaries since process start (or reset)."""
    now = _copy_agg()
    ops: Dict[str, Any] = {}
    for key, agg in sorted(now.items()):
        block = _op_summary(agg, None)
        if block:
            ops["/".join(key)] = block
    return ops


def sample_block() -> Dict[str, Any]:
    """Compact block for the runtime sampler and the stats RPCs: the
    per-op summaries plus the headline pressure numbers the slo/ops
    consumers sort by."""
    ops = summary()
    misses = sum(b.get("deadline_misses", 0) for b in ops.values())
    retries = sum(b.get("retries", 0) for b in ops.values())
    worst_op = None
    worst_margin = 0.0
    for key, block in ops.items():
        m = block.get("margin_p99")
        if m is not None and m > worst_margin:
            worst_margin = m
            worst_op = key
    out: Dict[str, Any] = {
        "ops": ops,
        "deadline_misses": misses,
        "retries": retries,
    }
    if worst_op is not None:
        out["worst_margin_p99"] = worst_margin
        out["worst_op"] = worst_op
    return out


# -------------------------------------------------------------- blackbox


def blackbox_dir() -> Optional[str]:
    return os.environ.get(_DIR_ENV_VAR) or os.environ.get(
        _PROGRESS_DIR_ENV_VAR
    )


def blackbox_path() -> Optional[str]:
    """This process's blackbox statusfile path (None → recording stays
    in-memory only). Role-prefixed like snapxray's per-process trace
    shards so fleet members and peers land distinct files."""
    base = blackbox_dir()
    if not base:
        return None
    role = os.environ.get(_TRACE_ROLE_ENV_VAR)
    prefix = f"{role}." if role else ""
    return os.path.join(base, f"{prefix}pid{os.getpid()}.blackbox.jsonl")


def dump_blackbox(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Flush the flight recorder to its statusfile. Overwrites — the
    file is always the *latest* ring, one dump per fault/degrade/exit
    hook (rate-limited per path). Returns the path written, or None
    when no directory is configured or the ring is empty."""
    if path is None:
        path = blackbox_path()
    if path is None:
        return None
    with _LOCK:
        events = list(_RING)
        if not events:
            return None
        now = time.monotonic()
        last = _LAST_DUMP.get(path)
        min_interval = env_float(
            _DUMP_INTERVAL_ENV_VAR, _DEFAULT_DUMP_INTERVAL_S
        )
        if last is not None and reason != "exit" and (
            now - last
        ) < min_interval:
            return None
        _LAST_DUMP[path] = now
    from .telemetry import ledger

    header = {
        "kind": "blackbox_header",
        "reason": reason,
        "ts": round(time.time(), 3),
        "pid": os.getpid(),
        "role": os.environ.get(_TRACE_ROLE_ENV_VAR),
        "events": len(events),
    }
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(ledger.encode_line(header) + "\n")
            for event in events:
                f.write(ledger.encode_line(event) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        logger.debug(f"blackbox dump to {path} failed: {e!r}")
        return None
    REGISTRY.counter(WIRE_BLACKBOX_DUMPS, reason=reason).inc()
    return path


def read_blackbox(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse a blackbox dump with the ledger's crc discipline: returns
    ``(records, skipped)`` where a torn final record (a dump cut off
    mid-write) is counted in ``skipped``, never surfaced as data."""
    from .telemetry import ledger

    with open(path, "rb") as f:
        raw = f.read()
    records, _valid_len, skipped = ledger.parse_ledger_bytes(raw)
    return records, skipped


def ring_events() -> List[Dict[str, Any]]:
    """Snapshot of the in-memory ring (tests and the ops CLI)."""
    with _LOCK:
        return list(_RING)


def _self_test() -> None:
    """Exercise the aggregate/window/blackbox machinery hermetically."""
    import tempfile

    reset()
    record("snapwire", "put", seconds=0.01, bytes_out=1024, deadline_s=1.0)
    record(
        "snapwire",
        "put",
        seconds=1.2,
        outcome="deadline_miss",
        attempt=1,
        deadline_s=1.0,
    )
    record("snapserve", "read", seconds=0.002, bytes_in=4096, deadline_s=60.0)
    s = summary()
    assert set(s) == {"snapwire/put", "snapserve/read"}, s
    put = s["snapwire/put"]
    assert put["count"] == 2 and put["deadline_misses"] == 1, put
    assert put["retries"] == 1 and put["margin_max"] >= 1.0, put
    token = window_begin()
    record("snapserve", "read", seconds=0.004, deadline_s=60.0)
    w = window_collect(token)
    assert set(w) == {"snapserve/read"} and w["snapserve/read"]["count"] == 1, w
    block = sample_block()
    assert block["deadline_misses"] == 1 and block["worst_op"] == "snapwire/put"
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "t.blackbox.jsonl")
        assert dump_blackbox("test", path=path) == path
        records, skipped = read_blackbox(path)
        assert skipped == 0 and records[0]["kind"] == "blackbox_header"
        assert len(records) == 1 + records[0]["events"]
        # Torn tail: truncate mid-record → skipped, prefix intact.
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[:-7])
        records2, skipped2 = read_blackbox(path)
        assert skipped2 == 1 and len(records2) == len(records) - 1
    reset()
    print(json.dumps({"wiretap_self_test": "ok"}))


if __name__ == "__main__":
    _self_test()
