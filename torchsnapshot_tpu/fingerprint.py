"""Content fingerprints for incremental (deduplicated) snapshots.

Beyond reference parity: torchsnapshot rewrites every byte of every
tensor on every ``Snapshot.take`` — checkpointing a fine-tune whose
backbone is frozen pays the full device→host transfer and storage write
for data that has not changed since the previous snapshot. This module
provides a cheap, deterministic 128-bit content fingerprint that can be
computed **on device** (so an unchanged array is detected *before* any
device→host transfer) or on host for numpy-resident state.

Algorithm — ``xs128``: the logical payload (the uncompressed
little-endian C-order bytes that would be stored), zero-padded to a
multiple of 4 bytes, is viewed as a vector of uint32 words ``w_i``. For
four lanes ``k ∈ {0,1,2,3}``::

    F_k = sum_i  w_i * mix(i * GOLD + k * SALT + 1)   (mod 2^32)

where ``mix`` is the murmur3 finalizer (xor-shift / multiply
avalanche). Each lane is a random-weighted linear checksum: a change in
any word survives into ``F_k`` unless the weighted difference cancels
mod 2^32 — probability ~2^-32 per lane for non-adversarial changes,
~2^-128 over four independent lanes. Position-dependent weights make
the fingerprint sensitive to permutations as well as value changes
(a plain sum would not be).

Why linear instead of a cryptographic hash: the weighted sum is one
fused elementwise-multiply + reduce, which XLA compiles to a single
HBM-bandwidth pass on TPU with the ``iota``-derived weights fused in
(never materialized), and the identical arithmetic vectorizes in numpy
for host arrays. Collision resistance against an *adversary* is not a
goal — the fingerprint gates deduplication of a process's own training
state, the same trust model as rsync's rolling checksums.

Determinism contract: fingerprints are only ever compared
device-computed ↔ device-computed or host-computed ↔ host-computed for
the same leaf across successive takes (a leaf migrating between host
and device between takes may miss a dedup — never corrupt). The device
and host implementations follow the same spec and agree bit-for-bit on
the CPU backend (asserted in tests); agreement across platforms is not
load-bearing because a fingerprint MISMATCH always degrades to a full
write.
"""

from functools import partial
from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

FINGERPRINT_ALGO = "xs128"

_GOLD = np.uint32(0x9E3779B1)  # 2^32 / golden ratio (Weyl increment)
_SALT = np.uint32(0x85EBCA77)  # per-lane offset
_N_LANES = 4

# murmur3 finalizer constants
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)


def format_fingerprint(lanes: Any) -> str:
    """``"xs128:<32 hex>"`` from four uint32 lane values."""
    vals = np.asarray(lanes, dtype=np.uint64)
    return FINGERPRINT_ALGO + ":" + "".join(f"{int(v) & 0xFFFFFFFF:08x}" for v in vals)


# ----------------------------------------------------------------- device


def _mix_u32(h):
    """murmur3 finalizer on uint32 (jnp)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_M1)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(_M2)
    h = h ^ (h >> 16)
    return h


def _device_words(x: jax.Array) -> jax.Array:
    """Reinterpret an array's data as a flat uint32 word vector.

    Sub-4-byte dtypes pack groups of ``4/itemsize`` elements into one
    word via a trailing-dimension bitcast; the tail is zero-padded. The
    exact word order within a group is whatever
    ``lax.bitcast_convert_type`` produces on this platform — stable for
    a given platform/jax version, which is all the determinism contract
    needs (see module docstring).
    """
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    itemsize = np.dtype(x.dtype).itemsize
    if itemsize not in (1, 2, 4, 8) or np.issubdtype(
        np.dtype(x.dtype), np.complexfloating
    ):
        # complex / exotic widths: no defined word view. Callers catch
        # and degrade to a full (un-deduplicated) write.
        raise ValueError(
            f"no device fingerprint for dtype {x.dtype} "
            f"(itemsize {itemsize})"
        )
    flat = x.reshape(-1)
    if itemsize == 4:
        return lax.bitcast_convert_type(flat, jnp.uint32)
    if itemsize == 8:
        return lax.bitcast_convert_type(flat, jnp.uint32).reshape(-1)
    # itemsize in (1, 2): pack ratio elements per uint32 word.
    ratio = 4 // itemsize
    narrow = lax.bitcast_convert_type(
        flat, jnp.uint8 if itemsize == 1 else jnp.uint16
    )
    pad = (-narrow.shape[0]) % ratio
    if pad:
        narrow = jnp.concatenate(
            [narrow, jnp.zeros((pad,), dtype=narrow.dtype)]
        )
    return lax.bitcast_convert_type(narrow.reshape(-1, ratio), jnp.uint32)


@partial(jax.jit, static_argnames=("slices",))
def _fingerprint_device_jit(
    x: jax.Array, slices: Optional[Tuple[Tuple[int, int], ...]] = None
) -> jax.Array:
    if slices is not None:
        x = x[tuple(slice(a, b) for a, b in slices)]
    w = _device_words(x)
    n = w.shape[0]
    # iota-derived weights fuse into the reduction — no O(n) weight
    # buffer is materialized.
    i = lax.iota(jnp.uint32, n)
    lanes = []
    for k in range(_N_LANES):
        salt = (int(_SALT) * k + 1) & 0xFFFFFFFF
        m = _mix_u32(i * jnp.uint32(_GOLD) + jnp.uint32(salt))
        lanes.append(jnp.sum(w * m, dtype=jnp.uint32))
    return jnp.stack(lanes)


def fingerprint_device_async(
    x: jax.Array, slices: Optional[Tuple[slice, ...]] = None
) -> jax.Array:
    """Dispatch the fingerprint computation on ``x``'s device; returns
    the (4,)-uint32 result array WITHOUT blocking. Call
    :func:`format_fingerprint` on it (or ``np.asarray`` it) to resolve.

    ``slices`` (static start/stop per dim) fingerprints a sub-box — the
    slice fuses into the jitted computation, so no chunk-sized buffer
    materializes for subdivided shards.
    """
    static = None
    if slices is not None:
        static = tuple(
            (
                0 if s.start is None else int(s.start),
                int(x.shape[d]) if s.stop is None else int(s.stop),
            )
            for d, s in enumerate(slices)
        )
    return _fingerprint_device_jit(x, static)


def resolve_fingerprints(results: list) -> list:
    """Resolve a batch of :func:`fingerprint_device_async` results with
    ONE device→host fetch per device: each individual 16-byte fetch
    pays a full link round trip (~90 ms measured over a congested
    TPU tunnel — the difference between a 0.9 s and a 0.2 s async-take
    stall at 10 leaves). Returns a list aligned with ``results`` whose
    elements are fingerprint strings, or the per-item ``Exception`` on
    failure (mixed placements fall back to per-item fetches)."""
    import jax.numpy as jnp

    out: list = [None] * len(results)
    by_device: dict = {}
    for i, r in enumerate(results):
        try:
            dev = next(iter(r.devices()))
        # Placement probe on a possibly-failed result; grouping is an
        # optimization and the per-item path re-surfaces real errors.
        except Exception:  # snapcheck: disable=swallowed-exception -- placement probe
            dev = None
        by_device.setdefault(dev, []).append(i)
    for idxs in by_device.values():
        rows = None
        if len(idxs) > 1:
            try:
                rows = np.asarray(jnp.stack([results[i] for i in idxs]))
            # Per-item fallback below re-runs each fetch and KEEPS its
            # exception in the output, so nothing is lost here.
            except Exception:  # snapcheck: disable=swallowed-exception -- retried per-item
                rows = None  # mixed placements etc.: per-item fallback
        if rows is not None:
            for i, row in zip(idxs, rows):
                out[i] = format_fingerprint(row)
            continue
        for i in idxs:
            try:
                out[i] = format_fingerprint(np.asarray(results[i]))
            except Exception as e:
                out[i] = e
    return out


# --------------------------------------------------------- chunked variants
#
# Per-chunk fingerprints for the content-addressed chunk store
# (chunkstore.py): the logical payload is split into fixed-size byte
# chunks and each chunk is fingerprinted INDEPENDENTLY, with weights
# indexed from the chunk's own start — so a chunk's fingerprint equals
# :func:`fingerprint_host` of exactly that byte slice, and the same
# bytes appearing at the same chunk-grid position in a later take hash
# to the same content key. One jitted pass computes every chunk's four
# lanes (a (n_chunks, 4) device array): HBM-bandwidth bound, resolved
# with ONE device→host fetch per leaf.


@partial(jax.jit, static_argnames=("chunk_words",))
def _fingerprint_device_chunked_jit(
    x: jax.Array, chunk_words: int
) -> jax.Array:
    w = _device_words(x)
    pad = (-w.shape[0]) % chunk_words
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad,), dtype=jnp.uint32)])
    rows = w.reshape(-1, chunk_words)
    # Within-chunk indices: zero-padding a short tail chunk adds 0*m
    # terms, so the result equals fingerprint_host of the unpadded
    # slice (which pads to a word boundary the same way).
    i = lax.iota(jnp.uint32, chunk_words)
    lanes = []
    for k in range(_N_LANES):
        salt = (int(_SALT) * k + 1) & 0xFFFFFFFF
        m = _mix_u32(i * jnp.uint32(_GOLD) + jnp.uint32(salt))
        lanes.append(jnp.sum(rows * m[None, :], axis=1, dtype=jnp.uint32))
    return jnp.stack(lanes, axis=1)


def fingerprint_device_chunked_async(
    x: jax.Array, chunk_bytes: int
) -> jax.Array:
    """Dispatch per-chunk fingerprints over ``x``'s stored-byte layout,
    ``chunk_bytes`` per chunk (must be a positive multiple of 4);
    returns the (n_chunks, 4)-uint32 result WITHOUT blocking. Resolve
    with :func:`resolve_chunk_fingerprints` (or ``np.asarray``)."""
    if chunk_bytes <= 0 or chunk_bytes % 4:
        raise ValueError(
            f"chunk_bytes must be a positive multiple of 4; got "
            f"{chunk_bytes}"
        )
    return _fingerprint_device_chunked_jit(x, chunk_bytes // 4)


def resolve_chunk_fingerprints(results: list) -> list:
    """Resolve a batch of :func:`fingerprint_device_chunked_async`
    results; each output element is a list of fingerprint strings (one
    per chunk) or the per-item ``Exception``."""
    out: list = []
    for r in results:
        try:
            rows = np.asarray(r)
            out.append([format_fingerprint(row) for row in rows])
        except Exception as e:
            out.append(e)
    return out


def fingerprint_host_chunked(data: Any, chunk_bytes: int) -> list:
    """Per-chunk fingerprints of host bytes / a numpy array, matching
    :func:`fingerprint_host` over each ``chunk_bytes`` slice of the
    C-order little-endian payload.

    Bounded memory like :func:`fingerprint_host`: rows are processed in
    ≤ ``_HOST_CHUNK_WORDS``-word batches with plain uint32 wraparound
    arithmetic (one batch-sized product transient, never a
    payload-sized one), and only the tail chunk is pad-copied — a
    multi-GiB host-staged leaf must not double its RSS to be
    fingerprinted."""
    if chunk_bytes <= 0 or chunk_bytes % 4:
        raise ValueError(
            f"chunk_bytes must be a positive multiple of 4; got "
            f"{chunk_bytes}"
        )
    if isinstance(data, np.ndarray):
        if data.dtype == np.bool_:
            data = data.astype(np.uint8)
        buf = np.ascontiguousarray(data).reshape(-1).view(np.uint8)
    else:
        buf = np.frombuffer(data, dtype=np.uint8)
    n = buf.shape[0]
    chunk_words = chunk_bytes // 4
    n_full = n // chunk_bytes
    n_chunks = -(-n // chunk_bytes) if n else 0
    i = np.arange(chunk_words, dtype=np.uint32)
    ms = []
    for k in range(_N_LANES):
        salt = np.uint32((int(_SALT) * k + 1) & 0xFFFFFFFF)
        ms.append(_mix_u32_np(i * _GOLD + salt))
    out = np.zeros((n_chunks, _N_LANES), dtype=np.uint32)
    body = buf[: n_full * chunk_bytes].view(np.uint32)
    batch_rows = max(1, _HOST_CHUNK_WORDS // chunk_words)
    for start in range(0, n_full, batch_rows):
        stop = min(n_full, start + batch_rows)
        rows = body[start * chunk_words : stop * chunk_words].reshape(
            stop - start, chunk_words
        )
        for k in range(_N_LANES):
            out[start:stop, k] = np.sum(
                rows * ms[k][None, :], axis=1, dtype=np.uint32
            )
    if n_chunks > n_full:
        tail = buf[n_full * chunk_bytes :]
        padded = np.zeros((chunk_bytes,), dtype=np.uint8)
        padded[: tail.shape[0]] = tail
        words = padded.view(np.uint32)
        for k in range(_N_LANES):
            out[n_full, k] = np.sum(words * ms[k], dtype=np.uint32)
    return [format_fingerprint(row) for row in out]


# ------------------------------------------------------------------- host

_HOST_CHUNK_WORDS = 1 << 22  # 16 MiB per pass


def _mix_u32_np(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = h * _M1
    h = h ^ (h >> np.uint32(13))
    h = h * _M2
    h = h ^ (h >> np.uint32(16))
    return h


def fingerprint_host(data: Any) -> str:
    """Fingerprint host bytes / a numpy array per the xs128 spec.

    Accepts ``bytes``/``memoryview``/``bytearray`` or an ``np.ndarray``
    (fingerprinted over its C-order little-endian bytes — the logical
    payload the snapshot would store).
    """
    if isinstance(data, np.ndarray):
        if data.dtype == np.bool_:
            data = data.astype(np.uint8)
        buf = np.ascontiguousarray(data).reshape(-1).view(np.uint8)
    else:
        buf = np.frombuffer(data, dtype=np.uint8)
    n_pad = (-buf.shape[0]) % 4
    if n_pad:
        buf = np.concatenate([buf, np.zeros((n_pad,), dtype=np.uint8)])
    words = buf.view(np.uint32)
    lanes = np.zeros((_N_LANES,), dtype=np.uint32)
    # Chunked so a multi-GiB payload never materializes a same-sized
    # weight array on host.
    for start in range(0, words.shape[0], _HOST_CHUNK_WORDS):
        w = words[start : start + _HOST_CHUNK_WORDS]
        i = np.arange(start, start + w.shape[0], dtype=np.uint32)
        for k in range(_N_LANES):
            salt = np.uint32((int(_SALT) * k + 1) & 0xFFFFFFFF)
            m = _mix_u32_np(i * _GOLD + salt)
            lanes[k] = lanes[k] + np.sum(w * m, dtype=np.uint32)
    return format_fingerprint(lanes)
