"""Memory-budgeted, pipelined execution of write/read requests.

TPU-native analog of reference torchsnapshot/scheduler.py:23-239. Two
two-stage asyncio pipelines overlap device→host staging / serialization
with storage IO under a per-process host-memory budget:

- write: ``stage_buffer`` (HBM→RAM copy + serialize, thread executor)
  → ``storage.write``;
- read: ``storage.read`` → ``consume_buffer`` (deserialize + RAM→HBM).

Budget accounting is symmetric and conservative (the reference *adds*
instead of subtracting the read budget at dispatch, scheduler.py:209,
making its read budget unbounded; and can leave finished staging tasks
un-reaped, scheduler.py:133-135 — both fixed here):

- write: charge ``staging_cost`` at dispatch; on stage completion re-credit
  ``staging_cost − len(buf)``; on write completion re-credit ``len(buf)``.
- read: charge ``consuming_cost`` at dispatch; re-credit it after consume —
  except a consumer's *deferred* portion (a split read's shared assembly
  buffer, which outlives the individual sub-read consumes; a streamed
  part's payload, which the H2D overlap engine holds until its transfer
  lands), which the consumer re-credits through a releaser callback when
  the allocation is actually freed. Pooled staging buffers
  (``staging_pool.py``) bind that releaser to their lease, which fires
  it exactly ONCE when the buffer returns to the pool — the pre-fastlane
  path assumed single-use allocations, and a pooled buffer re-crediting
  per sub-read would multiply-credit the budget. Releases may arrive
  from engine threads after this loop exited; ``_BudgetCell`` is locked
  for exactly that.

At least one request is always in flight regardless of budget so a single
over-budget buffer cannot deadlock the pipeline (reference
scheduler.py:104-117).
"""

import asyncio
import io
import logging
import os
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import psutil

from . import telemetry, tracing
from .io_types import IOReq, ReadReq, StoragePlugin, WriteReq, io_payload
from .telemetry import consume_profile as _cprof
from .telemetry import memwatch
from .telemetry import metrics as _metric_names

logger = logging.getLogger(__name__)

_MAX_PER_RANK_MEMORY_BUDGET_BYTES: int = 32 * 1024 * 1024 * 1024
_AVAILABLE_MEMORY_MULTIPLIER: float = 0.8
_MAX_STAGING_THREADS: int = 16

_MEMORY_BUDGET_ENV_VAR = "TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES"


def get_local_world_size(coord) -> int:
    """Number of snapshot processes on this host (hostname all-gather).

    Reference analog: scheduler.py:29-38.
    """
    hostnames = coord.all_gather_object(socket.gethostname())
    return max(1, hostnames.count(socket.gethostname()))


def get_process_memory_budget_bytes(coord) -> int:
    """min(0.8 × available RAM ÷ local procs, 32 GB), env-overridable.

    Reference analog: scheduler.py:41-61. Runs a collective (hostname
    all-gather) — only call from paths where every process participates.
    """
    env_val = os.environ.get(_MEMORY_BUDGET_ENV_VAR)
    if env_val is not None:
        budget = int(env_val)
        logger.info("Memory budget overridden by env var: %d bytes", budget)
        return budget
    local_world_size = get_local_world_size(coord)
    return _memory_budget_for_local_world(local_world_size)


def get_local_memory_budget_bytes() -> int:
    """Collective-free budget (assumes this is the host's only snapshot
    process) for single-process operations like ``Snapshot.read_object``."""
    env_val = os.environ.get(_MEMORY_BUDGET_ENV_VAR)
    if env_val is not None:
        return int(env_val)
    return _memory_budget_for_local_world(1)


def _memory_budget_for_local_world(local_world_size: int) -> int:
    available = psutil.virtual_memory().available
    budget = min(
        int(available * _AVAILABLE_MEMORY_MULTIPLIER) // local_world_size,
        _MAX_PER_RANK_MEMORY_BUDGET_BYTES,
    )
    logger.info("Per-process memory budget: %d MB", budget // 1024 // 1024)
    return budget


def _observe_op(
    ops: Dict[str, Dict[str, Any]],
    op: str,
    seconds: float,
    nbytes: int,
    progress: Optional[Any] = None,
    progress_bytes: int = 0,
) -> None:
    """Record one pipelined op in the always-on metrics AND the per-call
    aggregate (the flight recorder's exact per-operation numbers). Only
    ever called from the event-loop thread, so the plain dict is safe.
    ``progress`` (a telemetry ProgressPublisher) gets the same pulse —
    its heartbeat beats exactly as often as the pipeline completes
    work, which is what makes a stale heartbeat mean "stuck".
    ``progress_bytes`` is this op's credit against the announced
    bytes_total — in cost units, NOT stored-payload bytes (``nbytes``),
    which diverge under compression; ops that re-describe payloads a
    sibling op already credited pass 0."""
    telemetry.record_scheduler_op(op, seconds, nbytes)
    agg = ops.setdefault(op, {"count": 0, "seconds": 0.0, "bytes": 0})
    agg["count"] += 1
    agg["seconds"] += seconds
    agg["bytes"] += nbytes
    if progress is not None:
        progress.pipeline_update(op, progress_bytes)


def _merge_stats(
    stats: Optional[Dict[str, Any]],
    pipeline: str,
    nbytes: int,
    stall_s: float,
    high_water: int,
    ops: Dict[str, Dict[str, Any]],
) -> None:
    """Fold one pipeline run's aggregates into the always-on metrics and
    (when the caller wants per-operation attribution) the ``stats``
    accumulator dict."""
    telemetry.counter(
        _metric_names.SCHED_STALL_SECONDS, pipeline=pipeline
    ).inc(stall_s)
    telemetry.gauge(
        _metric_names.SCHED_BUDGET_HWM, pipeline=pipeline
    ).set_max(high_water)
    if stats is None:
        return
    stats["bytes"] = stats.get("bytes", 0) + nbytes
    stats["stall_s"] = stats.get("stall_s", 0.0) + stall_s
    stats["budget_high_water_bytes"] = max(
        stats.get("budget_high_water_bytes", 0), high_water
    )
    out = stats.setdefault("ops", {})
    for op, agg in ops.items():
        acc = out.setdefault(op, {"count": 0, "seconds": 0.0, "bytes": 0})
        acc["count"] += agg["count"]
        acc["seconds"] += agg["seconds"]
        acc["bytes"] += agg["bytes"]


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    stats: Optional[Dict[str, Any]] = None,
    progress: Optional[Any] = None,
) -> int:
    """Run the staged-write pipeline; returns total bytes written.

    ``stats`` (optional) accumulates this run's exact aggregates —
    bytes, per-op count/seconds/bytes, budget stall seconds, budget
    high-water — for the flight recorder; the same numbers also feed the
    always-on process metrics. ``progress`` (optional ProgressPublisher)
    is pulsed per op completion and cadence-published from this loop,
    so watchers see live bytes/phase while the pipeline runs.
    """
    begin_ts = time.monotonic()
    if progress is not None:
        # Pre-staged buffers charge a 0 budget cost but advertise their
        # real size via payload_nbytes — progress totals want bytes to
        # move, not budget to charge.
        progress.add_bytes_total(
            sum(
                getattr(wr.buffer_stager, "payload_nbytes", None)
                or wr.buffer_stager.get_staging_cost_bytes()
                for wr in write_reqs
            )
        )
        # Announce the totals immediately: a pipeline that then blocks
        # on its first storage op still leaves watchers a record with
        # bytes_total (0 done), not a blank.
        await progress.async_tick(force=True)
    pending = deque(write_reqs)
    staged: deque = deque()  # (WriteReq, buf)
    staging: Dict[asyncio.Task, Tuple[WriteReq, int]] = {}
    io_tasks: Dict[asyncio.Task, int] = {}
    budget = memory_budget_bytes
    min_budget = memory_budget_bytes
    stall_s = 0.0
    ops: Dict[str, Dict[str, Any]] = {}
    bytes_written = 0
    max_io = storage.max_write_concurrency
    executor = ThreadPoolExecutor(max_workers=_MAX_STAGING_THREADS)
    # Live budget gauges (snapscope): occupancy + stalled-right-now, so
    # the runtime sampler can see budget pressure while it happens
    # instead of post-hoc from the stall counter. Reset on exit.
    in_use_gauge = telemetry.gauge(
        _metric_names.SCHED_BUDGET_IN_USE, pipeline="write"
    )
    stalled_gauge = telemetry.gauge(
        _metric_names.SCHED_BUDGET_STALLED, pipeline="write"
    )
    # snapmem: the write budget is transient host RAM — staged buffers
    # live only between stage and write completion, so any residual
    # after the pipeline exits is a leak signal. Pre-storm forecast:
    # the allocation burst is bounded by min(total staging cost,
    # budget) since dispatch throttles at the budget line.
    mem_domain = memwatch.register(
        "scheduler.write",
        cap_bytes=memory_budget_bytes,
        transient=True,
        watch_residual="used",
    )
    memwatch.forecast(
        min(
            sum(
                wr.buffer_stager.get_staging_cost_bytes()
                for wr in write_reqs
            ),
            memory_budget_bytes,
        ),
        kind="take",
    )
    try:
        while pending or staged or staging or io_tasks:
            # Dispatch staging while the budget allows; always keep at
            # least one request moving.
            budget_blocked = False
            while pending:
                cost = pending[0].buffer_stager.get_staging_cost_bytes()
                nothing_in_flight = not (staging or staged or io_tasks)
                if budget >= cost or nothing_in_flight:
                    wr = pending.popleft()
                    budget -= cost
                    min_budget = min(min_budget, budget)

                    async def _stage(wr=wr, cost=cost):
                        t0 = time.monotonic()
                        with tracing.span("stage", path=wr.path, bytes=cost):
                            buf = await wr.buffer_stager.stage_buffer(executor)
                        _observe_op(
                            ops,
                            "stage",
                            time.monotonic() - t0,
                            len(buf),
                            progress,
                        )
                        # Codec stage (chunkstore.py ChunkStager): the
                        # encode ran inside the stage above; surface it
                        # as its own op so flight reports separate
                        # "device→host + serialize" from "compress/
                        # quantize" CPU time. Credits no progress bytes
                        # (the stage op already did).
                        enc = getattr(
                            wr.buffer_stager, "encode_stats", None
                        )
                        if enc is not None:
                            _observe_op(ops, "encode", enc[0], enc[1])
                            telemetry.counter(
                                _metric_names.CODEC_SECONDS, op="encode"
                            ).inc(enc[0])
                        return buf

                    task = asyncio.ensure_future(_stage())
                    staging[task] = (wr, cost)
                else:
                    budget_blocked = True
                    break
            # Dispatch storage writes up to the backend's concurrency cap.
            while staged and len(io_tasks) < max_io:
                wr, buf = staged.popleft()
                io_req = IOReq(path=wr.path, data=buf)
                # Progress credit in the SAME units bytes_total summed
                # (cost / payload_nbytes, pre-compression) — len(buf)
                # is post-compression and would stall the % short.
                share = (
                    getattr(wr.buffer_stager, "payload_nbytes", None)
                    or wr.buffer_stager.get_staging_cost_bytes()
                )

                async def _write(
                    io_req=io_req, path=wr.path, n=len(buf), share=share
                ):
                    t0 = time.monotonic()
                    with tracing.span("write", path=path, bytes=n):
                        await storage.write(io_req)
                    _observe_op(
                        ops,
                        "write",
                        time.monotonic() - t0,
                        n,
                        progress,
                        progress_bytes=share,
                    )

                task = asyncio.ensure_future(_write())
                io_tasks[task] = len(buf)

            in_use_gauge.set(memory_budget_bytes - budget)
            mem_domain.set_used(
                max(0, memory_budget_bytes - budget),
                pinned_bytes=max(0, memory_budget_bytes - budget),
            )
            stalled_gauge.set(1.0 if budget_blocked else 0.0)
            in_flight = set(staging) | set(io_tasks)
            if not in_flight:
                continue
            wait_t0 = time.monotonic()
            done, _ = await asyncio.wait(
                in_flight, return_when=asyncio.FIRST_COMPLETED
            )
            if budget_blocked:
                # Work was ready to dispatch but the budget said no: the
                # time until the next completion is budget-wait stall.
                stall_s += time.monotonic() - wait_t0
            for task in done:
                if task in staging:
                    wr, cost = staging.pop(task)
                    buf = task.result()
                    budget += cost - len(buf)
                    staged.append((wr, buf))
                else:
                    buf_len = io_tasks.pop(task)
                    task.result()  # propagate storage errors
                    budget += buf_len
                    bytes_written += buf_len
            if progress is not None:
                await progress.async_tick()
    finally:
        executor.shutdown(wait=False)
        in_use_gauge.set(0)
        stalled_gauge.set(0)
        mem_domain.set_used(max(0, memory_budget_bytes - budget))
        mem_domain.close()
    elapsed = time.monotonic() - begin_ts
    _merge_stats(
        stats,
        "write",
        bytes_written,
        stall_s,
        memory_budget_bytes - min_budget,
        ops,
    )
    mbps = bytes_written / 1024 / 1024 / elapsed if elapsed > 0 else 0.0
    logger.info(
        "Rank %d finished saving (%d bytes). Throughput: %.2f MB/s",
        rank,
        bytes_written,
        mbps,
    )
    return bytes_written


class _BudgetCell:
    """Mutable budget shared with consumers holding deferred reservations
    (split-read assembly buffers, streaming-split crc stashes): ``release``
    re-credits when the backing allocation is actually freed, not when a
    consume task completes. Locked: streaming splits release from executor
    threads as their in-order prefix drains, racing the event loop's
    charge/refund."""

    __slots__ = ("value", "_lock", "_charges", "_releases")

    def __init__(self, value: int) -> None:
        self.value = value
        self._lock = threading.Lock()
        self._charges = 0
        self._releases = 0

    def charge(self, nbytes: int) -> None:
        with self._lock:
            self.value -= nbytes
            self._charges += 1

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.value += nbytes
            self._releases += 1

    def charge_count(self) -> int:
        with self._lock:
            return self._charges

    def release_count(self) -> int:
        with self._lock:
            return self._releases


# Force-admission grace: when nothing is in flight on the event loop but
# the head still cannot be admitted under budget, a completed consume's
# deferred release may still be riding an engine/executor thread the OS
# hasn't scheduled (H2D done-callbacks resolve after the consume task
# does). Bound how long the pipeline waits for such a straggler before
# it force-admits and accepts the overrun.
_FORCE_ADMIT_GRACE_S = 0.5
_FORCE_ADMIT_POLL_S = 0.005


async def _straggler_release_landed(cell: _BudgetCell) -> bool:
    """Wait up to the grace window for ANY release on ``cell``; True
    means one landed and the caller should rescan under the refreshed
    budget instead of force-admitting."""
    if cell.charge_count() == 0:
        # Nothing was ever charged, so no release can possibly be in
        # flight — force-admit immediately (the solo over-budget head
        # at t=0 must not pay the grace).
        return False
    baseline = cell.release_count()
    deadline = time.monotonic() + _FORCE_ADMIT_GRACE_S
    while time.monotonic() < deadline:
        await asyncio.sleep(_FORCE_ADMIT_POLL_S)
        if cell.release_count() != baseline:
            return True
    return cell.release_count() != baseline


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    device_budget_bytes: Optional[int] = None,
    stats: Optional[Dict[str, Any]] = None,
    progress: Optional[Any] = None,
) -> int:
    """Run the read→consume pipeline; returns total bytes read.

    ``device_budget_bytes`` bounds the DEVICE (HBM) bytes deposited by
    in-flight streamed consumes awaiting assembly (SURVEY §7 hard-part
    5: restores must respect HBM headroom, not just host RAM). None =
    unbounded. At least one consume always dispatches so an over-budget
    region cannot deadlock the pipeline; releases arrive through the
    consumers' device releasers when assembly frees the chunks.

    ``stats`` (optional) accumulates exact per-run aggregates for the
    flight recorder, as in :func:`execute_write_reqs`.
    """
    begin_ts = time.monotonic()
    min_budget = memory_budget_bytes
    stall_s = 0.0
    ops: Dict[str, Dict[str, Any]] = {}
    if progress is not None:
        progress.add_bytes_total(
            sum(
                r.buffer_consumer.get_consuming_cost_bytes()
                - r.buffer_consumer.get_deferred_cost_bytes()
                for r in read_reqs
            )
        )
        await progress.async_tick(force=True)

    # Largest LOGICAL objects first: a big object issued last would gate
    # the restore's tail all alone after the small reads drain (VERDICT
    # r4 #2). The key is the whole-object size (sort_key_bytes), NOT the
    # consuming cost: a split object's first sub-read carries the
    # assembly surcharge in its cost, and sorting by cost would float
    # EVERY object's first sub-read ahead of ALL siblings — putting all
    # assembly buffers live concurrently through repeated forced
    # admission (r5 review finding). Same-object sub-reads share one
    # key, so the stable sort keeps each object's group contiguous and
    # in order.
    def _sort_bytes(r: ReadReq) -> int:
        key = getattr(r.buffer_consumer, "sort_key_bytes", None)
        return key if key is not None else r.buffer_consumer.get_consuming_cost_bytes()

    pending = deque(sorted(read_reqs, key=lambda r: -_sort_bytes(r)))
    reading: Dict[asyncio.Task, Tuple[ReadReq, int]] = {}
    consumable: deque = deque()  # (ReadReq, buf, host_refund, ready_t)
    # Consume micro-profile (snapxray): read_wait — a completed read's
    # payload queued behind budget/executor pressure before its consume
    # dispatched — is only measurable here, between the two pipeline
    # stages. The scope was opened by the restore root in this thread.
    profile = _cprof.current()
    consuming: Dict[asyncio.Task, int] = {}
    budget = _BudgetCell(memory_budget_bytes)
    device_budget = _BudgetCell(
        device_budget_bytes if device_budget_bytes is not None else (1 << 62)
    )
    bytes_read = 0
    max_io = storage.max_read_concurrency
    executor = ThreadPoolExecutor(max_workers=_MAX_STAGING_THREADS)
    in_use_gauge = telemetry.gauge(
        _metric_names.SCHED_BUDGET_IN_USE, pipeline="read"
    )
    stalled_gauge = telemetry.gauge(
        _metric_names.SCHED_BUDGET_STALLED, pipeline="read"
    )
    # snapmem: host-cell bytes are transient host RAM; the device cell
    # tracks HBM deposits — real bytes, but not host RAM, so it is
    # registered external (visible in the domain table, excluded from
    # the committed/headroom math). Forecast the host-side burst before
    # the read storm starts.
    mem_domain = memwatch.register(
        "scheduler.read.host",
        cap_bytes=memory_budget_bytes,
        transient=True,
        watch_residual="used",
    )
    mem_device_domain = memwatch.register(
        "scheduler.read.device",
        cap_bytes=device_budget_bytes,
        transient=True,
        external=True,
    )
    memwatch.forecast(
        min(
            sum(
                r.buffer_consumer.get_consuming_cost_bytes()
                for r in read_reqs
            ),
            memory_budget_bytes,
        ),
        kind="restore",
    )
    try:
        while pending or reading or consumable or consuming:
            budget_blocked = False
            while pending and len(reading) < max_io:
                consumer = pending[0].buffer_consumer
                cost = consumer.get_consuming_cost_bytes()
                nothing_in_flight = not (reading or consumable or consuming)
                if budget.value < cost and nothing_in_flight:
                    # Same straggler grace as the device scan below:
                    # split-assembly buffers release host budget from
                    # executor threads after their consume task resolves.
                    while (
                        budget.value < cost
                        and await _straggler_release_landed(budget)
                    ):
                        pass
                if budget.value >= cost or nothing_in_flight:
                    rr = pending.popleft()
                    # Invariant the flow analysis cannot see: every
                    # charge is re-credited when its read/consume task
                    # completes in a LATER loop iteration (the
                    # budget.release below / the consumer's deferred
                    # releaser), and the cell is per-pipeline-run — a
                    # failed run gang-cancels its tasks and drops the
                    # cell with the stack frame, so no charge outlives
                    # the budget it was charged against.
                    # snapcheck: disable=resource-lifecycle -- cross-iteration discharge: released at task completion (below) or via the consumer's deferred releaser; cell dies with the run
                    budget.charge(cost)
                    min_budget = min(min_budget, budget.value)
                    deferred = consumer.get_deferred_cost_bytes()
                    if deferred:
                        consumer.set_cost_releaser(budget.release)
                    io_req = IOReq(path=rr.path, byte_range=rr.byte_range)

                    async def _read(
                        io_req=io_req,
                        path=rr.path,
                        share=cost - deferred,
                    ) -> IOReq:
                        t0 = time.monotonic()
                        with tracing.span("read", path=path):
                            await storage.read(io_req)
                        _observe_op(
                            ops,
                            "read",
                            time.monotonic() - t0,
                            len(io_payload(io_req)),
                            progress,
                            # Credit the same cost units bytes_total
                            # summed (consuming cost minus deferred).
                            progress_bytes=share,
                        )
                        return io_req

                    task = asyncio.ensure_future(_read())
                    # The consume-completion refund excludes the deferred
                    # portion, which the consumer releases itself.
                    reading[task] = (rr, cost - deferred)
                else:
                    budget_blocked = True
                    break

            # Dispatch consumes under the device budget. The scan skips
            # past blocked entries (a region waiting for budget must not
            # head-of-line-block other regions' consumes, whose
            # completion is what releases budget). If NOTHING is in
            # flight, no future completion can release device bytes —
            # force-admit the head so progress is guaranteed; the
            # overrun is then bounded by that one region's in-assembly
            # bytes, which must fit HBM anyway as the restored array.
            while consumable:
                pick = None
                for i, (rr, _buf, _refund, _ready_t) in enumerate(
                    consumable
                ):
                    dcost = rr.buffer_consumer.get_device_cost_bytes()
                    if not dcost or device_budget.value >= dcost:
                        pick = i
                        break
                if pick is None:
                    if reading or consuming:
                        # Device-budget wait is stall too: consumable
                        # work exists but cannot dispatch until budget
                        # frees.
                        budget_blocked = True
                        break
                    if await _straggler_release_landed(device_budget):
                        # A deferred release from an engine thread beat
                        # the grace window — rescan before overrunning.
                        continue
                    pick = 0
                rr, buf, host_refund, ready_t = consumable[pick]
                del consumable[pick]
                if profile is not None:
                    profile.note(
                        "read_wait",
                        time.monotonic() - ready_t,
                        len(buf),
                    )
                consumer = rr.buffer_consumer
                dcost = consumer.get_device_cost_bytes()
                if dcost:
                    device_budget.charge(dcost)
                    consumer.set_device_cost_releaser(device_budget.release)

                async def _consume(rr=rr, buf=buf):
                    t0 = time.monotonic()
                    with tracing.span("consume", path=rr.path, bytes=len(buf)):
                        await rr.buffer_consumer.consume_buffer(buf, executor)
                    _observe_op(
                        ops,
                        "consume",
                        time.monotonic() - t0,
                        len(buf),
                        progress,
                    )

                consume_task = asyncio.ensure_future(_consume())
                consuming[consume_task] = host_refund

            in_use_gauge.set(memory_budget_bytes - budget.value)
            mem_domain.set_used(
                max(0, memory_budget_bytes - budget.value),
                pinned_bytes=max(0, memory_budget_bytes - budget.value),
            )
            if device_budget_bytes is not None:
                mem_device_domain.set_used(
                    max(0, device_budget_bytes - device_budget.value),
                    pinned_bytes=max(
                        0, device_budget_bytes - device_budget.value
                    ),
                )
            stalled_gauge.set(1.0 if budget_blocked else 0.0)
            in_flight = set(reading) | set(consuming)
            if not in_flight:
                continue
            wait_t0 = time.monotonic()
            done, _ = await asyncio.wait(
                in_flight, return_when=asyncio.FIRST_COMPLETED
            )
            if budget_blocked:
                stall_s += time.monotonic() - wait_t0
            for task in done:
                if task in reading:
                    rr, cost = reading.pop(task)
                    buf = io_payload(task.result())
                    bytes_read += len(buf)
                    consumable.append((rr, buf, cost, time.monotonic()))
                else:
                    cost = consuming.pop(task)
                    task.result()  # propagate consume errors
                    budget.release(cost)
            if progress is not None:
                await progress.async_tick()
    finally:
        executor.shutdown(wait=False)
        in_use_gauge.set(0)
        stalled_gauge.set(0)
        mem_domain.set_used(max(0, memory_budget_bytes - budget.value))
        mem_domain.close()
        mem_device_domain.close()
    elapsed = time.monotonic() - begin_ts
    _merge_stats(
        stats,
        "read",
        bytes_read,
        stall_s,
        memory_budget_bytes - min_budget,
        ops,
    )
    mbps = bytes_read / 1024 / 1024 / elapsed if elapsed > 0 else 0.0
    logger.info(
        "Rank %d finished loading (%d bytes). Throughput: %.2f MB/s",
        rank,
        bytes_read,
        mbps,
    )
    return bytes_read
