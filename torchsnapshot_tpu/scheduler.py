"""Memory-budgeted, pipelined execution of write/read requests.

TPU-native analog of reference torchsnapshot/scheduler.py:23-239. Two
two-stage asyncio pipelines overlap device→host staging / serialization
with storage IO under a per-process host-memory budget:

- write: ``stage_buffer`` (HBM→RAM copy + serialize, thread executor)
  → ``storage.write``;
- read: ``storage.read`` → ``consume_buffer`` (deserialize + RAM→HBM).

Budget accounting is symmetric and conservative (the reference *adds*
instead of subtracting the read budget at dispatch, scheduler.py:209,
making its read budget unbounded; and can leave finished staging tasks
un-reaped, scheduler.py:133-135 — both fixed here):

- write: charge ``staging_cost`` at dispatch; on stage completion re-credit
  ``staging_cost − len(buf)``; on write completion re-credit ``len(buf)``.
- read: charge ``consuming_cost`` at dispatch; re-credit it after consume —
  except a consumer's *deferred* portion (a split read's shared assembly
  buffer, which outlives the individual sub-read consumes), which the
  consumer re-credits through a releaser callback when the allocation is
  actually freed.

At least one request is always in flight regardless of budget so a single
over-budget buffer cannot deadlock the pipeline (reference
scheduler.py:104-117).
"""

import asyncio
import io
import logging
import os
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import psutil

from . import tracing
from .io_types import IOReq, ReadReq, StoragePlugin, WriteReq, io_payload

logger = logging.getLogger(__name__)

_MAX_PER_RANK_MEMORY_BUDGET_BYTES: int = 32 * 1024 * 1024 * 1024
_AVAILABLE_MEMORY_MULTIPLIER: float = 0.8
_MAX_STAGING_THREADS: int = 16

_MEMORY_BUDGET_ENV_VAR = "TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES"


def get_local_world_size(coord) -> int:
    """Number of snapshot processes on this host (hostname all-gather).

    Reference analog: scheduler.py:29-38.
    """
    hostnames = coord.all_gather_object(socket.gethostname())
    return max(1, hostnames.count(socket.gethostname()))


def get_process_memory_budget_bytes(coord) -> int:
    """min(0.8 × available RAM ÷ local procs, 32 GB), env-overridable.

    Reference analog: scheduler.py:41-61. Runs a collective (hostname
    all-gather) — only call from paths where every process participates.
    """
    env_val = os.environ.get(_MEMORY_BUDGET_ENV_VAR)
    if env_val is not None:
        budget = int(env_val)
        logger.info(f"Memory budget overridden by env var: {budget} bytes")
        return budget
    local_world_size = get_local_world_size(coord)
    return _memory_budget_for_local_world(local_world_size)


def get_local_memory_budget_bytes() -> int:
    """Collective-free budget (assumes this is the host's only snapshot
    process) for single-process operations like ``Snapshot.read_object``."""
    env_val = os.environ.get(_MEMORY_BUDGET_ENV_VAR)
    if env_val is not None:
        return int(env_val)
    return _memory_budget_for_local_world(1)


def _memory_budget_for_local_world(local_world_size: int) -> int:
    available = psutil.virtual_memory().available
    budget = min(
        int(available * _AVAILABLE_MEMORY_MULTIPLIER) // local_world_size,
        _MAX_PER_RANK_MEMORY_BUDGET_BYTES,
    )
    logger.info(f"Per-process memory budget: {budget // 1024 // 1024} MB")
    return budget


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
) -> int:
    """Run the staged-write pipeline; returns total bytes written."""
    begin_ts = time.monotonic()
    pending = deque(write_reqs)
    staged: deque = deque()  # (WriteReq, buf)
    staging: Dict[asyncio.Task, Tuple[WriteReq, int]] = {}
    io_tasks: Dict[asyncio.Task, int] = {}
    budget = memory_budget_bytes
    bytes_written = 0
    max_io = storage.max_write_concurrency
    executor = ThreadPoolExecutor(max_workers=_MAX_STAGING_THREADS)
    try:
        while pending or staged or staging or io_tasks:
            # Dispatch staging while the budget allows; always keep at
            # least one request moving.
            while pending:
                cost = pending[0].buffer_stager.get_staging_cost_bytes()
                nothing_in_flight = not (staging or staged or io_tasks)
                if budget >= cost or nothing_in_flight:
                    wr = pending.popleft()
                    budget -= cost

                    async def _stage(wr=wr, cost=cost):
                        with tracing.span("stage", path=wr.path, bytes=cost):
                            return await wr.buffer_stager.stage_buffer(executor)

                    task = asyncio.ensure_future(_stage())
                    staging[task] = (wr, cost)
                else:
                    break
            # Dispatch storage writes up to the backend's concurrency cap.
            while staged and len(io_tasks) < max_io:
                wr, buf = staged.popleft()
                io_req = IOReq(path=wr.path, data=buf)

                async def _write(io_req=io_req, path=wr.path, n=len(buf)):
                    with tracing.span("write", path=path, bytes=n):
                        await storage.write(io_req)

                task = asyncio.ensure_future(_write())
                io_tasks[task] = len(buf)

            in_flight = set(staging) | set(io_tasks)
            if not in_flight:
                continue
            done, _ = await asyncio.wait(
                in_flight, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in staging:
                    wr, cost = staging.pop(task)
                    buf = task.result()
                    budget += cost - len(buf)
                    staged.append((wr, buf))
                else:
                    buf_len = io_tasks.pop(task)
                    task.result()  # propagate storage errors
                    budget += buf_len
                    bytes_written += buf_len
    finally:
        executor.shutdown(wait=False)
    elapsed = time.monotonic() - begin_ts
    mbps = bytes_written / 1024 / 1024 / elapsed if elapsed > 0 else 0.0
    logger.info(
        f"Rank {rank} finished saving ({bytes_written} bytes). "
        f"Throughput: {mbps:.2f} MB/s"
    )
    return bytes_written


class _BudgetCell:
    """Mutable budget shared with consumers holding deferred reservations
    (split-read assembly buffers, streaming-split crc stashes): ``release``
    re-credits when the backing allocation is actually freed, not when a
    consume task completes. Locked: streaming splits release from executor
    threads as their in-order prefix drains, racing the event loop's
    charge/refund."""

    __slots__ = ("value", "_lock")

    def __init__(self, value: int) -> None:
        self.value = value
        self._lock = threading.Lock()

    def charge(self, nbytes: int) -> None:
        with self._lock:
            self.value -= nbytes

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.value += nbytes


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    device_budget_bytes: Optional[int] = None,
) -> int:
    """Run the read→consume pipeline; returns total bytes read.

    ``device_budget_bytes`` bounds the DEVICE (HBM) bytes deposited by
    in-flight streamed consumes awaiting assembly (SURVEY §7 hard-part
    5: restores must respect HBM headroom, not just host RAM). None =
    unbounded. At least one consume always dispatches so an over-budget
    region cannot deadlock the pipeline; releases arrive through the
    consumers' device releasers when assembly frees the chunks.
    """
    begin_ts = time.monotonic()

    # Largest LOGICAL objects first: a big object issued last would gate
    # the restore's tail all alone after the small reads drain (VERDICT
    # r4 #2). The key is the whole-object size (sort_key_bytes), NOT the
    # consuming cost: a split object's first sub-read carries the
    # assembly surcharge in its cost, and sorting by cost would float
    # EVERY object's first sub-read ahead of ALL siblings — putting all
    # assembly buffers live concurrently through repeated forced
    # admission (r5 review finding). Same-object sub-reads share one
    # key, so the stable sort keeps each object's group contiguous and
    # in order.
    def _sort_bytes(r: ReadReq) -> int:
        key = getattr(r.buffer_consumer, "sort_key_bytes", None)
        return key if key is not None else r.buffer_consumer.get_consuming_cost_bytes()

    pending = deque(sorted(read_reqs, key=lambda r: -_sort_bytes(r)))
    reading: Dict[asyncio.Task, Tuple[ReadReq, int]] = {}
    consumable: deque = deque()  # (ReadReq, buf, host_refund)
    consuming: Dict[asyncio.Task, int] = {}
    budget = _BudgetCell(memory_budget_bytes)
    device_budget = _BudgetCell(
        device_budget_bytes if device_budget_bytes is not None else (1 << 62)
    )
    bytes_read = 0
    max_io = storage.max_read_concurrency
    executor = ThreadPoolExecutor(max_workers=_MAX_STAGING_THREADS)
    try:
        while pending or reading or consumable or consuming:
            while pending and len(reading) < max_io:
                consumer = pending[0].buffer_consumer
                cost = consumer.get_consuming_cost_bytes()
                nothing_in_flight = not (reading or consumable or consuming)
                if budget.value >= cost or nothing_in_flight:
                    rr = pending.popleft()
                    budget.charge(cost)
                    deferred = consumer.get_deferred_cost_bytes()
                    if deferred:
                        consumer.set_cost_releaser(budget.release)
                    io_req = IOReq(path=rr.path, byte_range=rr.byte_range)

                    async def _read(io_req=io_req, path=rr.path) -> IOReq:
                        with tracing.span("read", path=path):
                            await storage.read(io_req)
                        return io_req

                    task = asyncio.ensure_future(_read())
                    # The consume-completion refund excludes the deferred
                    # portion, which the consumer releases itself.
                    reading[task] = (rr, cost - deferred)
                else:
                    break

            # Dispatch consumes under the device budget. The scan skips
            # past blocked entries (a region waiting for budget must not
            # head-of-line-block other regions' consumes, whose
            # completion is what releases budget). If NOTHING is in
            # flight, no future completion can release device bytes —
            # force-admit the head so progress is guaranteed; the
            # overrun is then bounded by that one region's in-assembly
            # bytes, which must fit HBM anyway as the restored array.
            while consumable:
                pick = None
                for i, (rr, _buf, _refund) in enumerate(consumable):
                    dcost = rr.buffer_consumer.get_device_cost_bytes()
                    if not dcost or device_budget.value >= dcost:
                        pick = i
                        break
                if pick is None:
                    if reading or consuming:
                        break
                    pick = 0
                rr, buf, host_refund = consumable[pick]
                del consumable[pick]
                consumer = rr.buffer_consumer
                dcost = consumer.get_device_cost_bytes()
                if dcost:
                    device_budget.charge(dcost)
                    consumer.set_device_cost_releaser(device_budget.release)

                async def _consume(rr=rr, buf=buf):
                    with tracing.span("consume", path=rr.path, bytes=len(buf)):
                        await rr.buffer_consumer.consume_buffer(buf, executor)

                consume_task = asyncio.ensure_future(_consume())
                consuming[consume_task] = host_refund

            in_flight = set(reading) | set(consuming)
            if not in_flight:
                continue
            done, _ = await asyncio.wait(
                in_flight, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in reading:
                    rr, cost = reading.pop(task)
                    buf = io_payload(task.result())
                    bytes_read += len(buf)
                    consumable.append((rr, buf, cost))
                else:
                    cost = consuming.pop(task)
                    task.result()  # propagate consume errors
                    budget.release(cost)
    finally:
        executor.shutdown(wait=False)
    elapsed = time.monotonic() - begin_ts
    mbps = bytes_read / 1024 / 1024 / elapsed if elapsed > 0 else 0.0
    logger.info(
        f"Rank {rank} finished loading ({bytes_read} bytes). "
        f"Throughput: {mbps:.2f} MB/s"
    )
    return bytes_read
