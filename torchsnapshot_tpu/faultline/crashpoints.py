"""Crash-point enumeration and the restore-or-detect invariant.

The harness at the heart of faultline: run a pipeline once under a pure
op counter to learn how many storage-op boundaries it crosses, then
replay it N times, crashing at every boundary (op 1, op 2, … op N —
including backend sub-steps like fs.py's write → fsync → rename →
dir-fsync), and after each crash assert the **restore-or-detect
invariant** over the surviving storage state:

  (a) every step a ``.steps/<N>`` marker names is FULLY restorable —
      ``Snapshot.verify()`` clean and a caller-supplied restore probe
      satisfied (the marker is the commit point; a marker naming a
      broken snapshot is a durability-ordering violation); and
  (b) everything else is detectably incomplete — invisible to
      ``latest_step()``/``restore()`` — and reclaimable:
      ``CheckpointManager.reconcile()`` either adopts it (committed
      metadata, missing marker: the work is finished, make it count) or
      sweeps it (no commit point: reclaim the bytes), after which a
      fresh save→prune cycle re-drives any interrupted prune and leaves
      no leaked objects.

Deterministic by construction: the schedule is a fixed op index, and a
run whose op stream comes up short of the crash point simply completes —
the invariant is checked either way.
"""

import asyncio
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..manager import _STEP_PREFIX, CheckpointManager, _step_dir
from ..snapshot import Snapshot
from ..storage_plugin import url_to_storage_plugin
from .plugin import inject
from .schedule import FaultSchedule, SimulatedCrash


def count_storage_ops(scenario: Callable[[], None]) -> int:
    """Run ``scenario`` under a fault-free op counter; return how many
    storage-op boundaries it crossed (the crash-point enumeration's N)."""
    with inject(FaultSchedule()) as ctl:
        scenario()
    return ctl.op_index


@dataclass
class CrashOutcome:
    crash_op: int
    crashed: bool  # False: the op stream came up short; scenario finished
    marked_steps: List[int] = field(default_factory=list)
    adopted_steps: List[int] = field(default_factory=list)


@dataclass
class CrashMatrixReport:
    total_ops: int
    outcomes: Dict[int, CrashOutcome] = field(default_factory=dict)


def check_recovery_invariant(
    base_url: str,
    restore_probe: Callable[[int], None],
    reconcile: bool = True,
) -> CrashOutcome:
    """Assert restore-or-detect over ``base_url``'s current state.

    ``restore_probe(step)`` must restore that step and raise on any
    value mismatch. Returns which steps were marker-visible and which
    ``reconcile()`` adopted (both sets verified restorable)."""
    mgr = CheckpointManager(base_url)
    marked = mgr.all_steps()
    for step in marked:
        problems = Snapshot(_step_dir(base_url, step)).verify()
        assert not problems, (
            f"restore-or-detect violated: marker .steps/{step} names a "
            f"corrupt snapshot: {problems}"
        )
        restore_probe(step)
    adopted: List[int] = []
    if reconcile:
        mgr.reconcile(adopt=True)
        after = mgr.all_steps()
        adopted = sorted(set(after) - set(marked))
        for step in adopted:
            problems = Snapshot(_step_dir(base_url, step)).verify()
            assert not problems, (
                f"reconcile adopted step {step} but its snapshot is "
                f"corrupt: {problems}"
            )
            restore_probe(step)
    return CrashOutcome(
        crash_op=-1, crashed=False, marked_steps=marked, adopted_steps=adopted
    )


def assert_reclaimed(base_url: str, live_steps: Sequence[int]) -> None:
    """Assert storage under ``base_url`` holds ONLY the live steps'
    objects: their payload prefixes and step markers — no tombstones, no
    stray markers, no payloads of pruned or crashed takes. The leak
    check run after recovery re-drove every interrupted operation.

    The telemetry ledger (``.telemetry/``, telemetry/ledger.py) is
    durable metadata by contract — its records describe the run, not
    any one step, and survive prune/reconcile by design — so it is
    never a leak (torn ``*.tmp<pid>`` debris under it still is).

    The content-addressed chunk store (``.chunkstore/``, chunkstore.py)
    is leak-checked BY REFERENCE: chunk objects some live step's
    committed manifest names are allowed (they are that step's
    payload), as is each live step's ref doc; everything else under the
    store — unreferenced chunks, stale refs, intents — is a leak the
    recovery should have reclaimed."""
    from ..chunkstore import (
        STORE_DIRNAME,
        REFS_PREFIX,
        chunk_keys_of,
        chunk_object_path,
        ref_doc_name,
    )
    from ..snapshot import Snapshot
    from ..telemetry.ledger import LEDGER_DIR

    import re

    live = set(live_steps)
    allowed_markers = {f"{_STEP_PREFIX}{s}" for s in live}
    allowed_prefixes = tuple(f"step-{s}/" for s in live)
    store_prefix = f"{STORE_DIRNAME}/"
    allowed_store: set = set()
    for s in sorted(live):
        step_url = _step_dir(base_url, s)
        try:
            manifest = Snapshot(step_url).get_manifest()
        # A live step whose metadata cannot be read fails the recovery
        # invariant itself; here it only shrinks the allow-set, which
        # can't hide a leak.
        except Exception:  # snapcheck: disable=swallowed-exception -- allow-set probe
            continue
        keys = chunk_keys_of(manifest)
        if keys:
            allowed_store.add(
                f"{store_prefix}{REFS_PREFIX}{ref_doc_name(step_url)}"
            )
            allowed_store.update(
                f"{store_prefix}{chunk_object_path(k)}" for k in keys
            )
    storage = url_to_storage_plugin(base_url)
    try:
        objs = asyncio.run(storage.list_prefix("")) or []
    finally:
        storage.close()

    def _is_ledger(o: str) -> bool:
        return o.startswith(f"{LEDGER_DIR}/") and not re.search(
            r"\.tmp\d+$", o
        )

    leaked = [
        o
        for o in objs
        if o not in allowed_markers
        and not o.startswith(allowed_prefixes)
        and not (o.startswith(store_prefix) and o in allowed_store)
        and not _is_ledger(o)
    ]
    assert not leaked, (
        f"leaked objects after recovery (live steps {sorted(live)}): "
        f"{sorted(leaked)}"
    )


def enumerate_crash_points(
    prepare: Callable[[], object],
    faulted: Callable[[object], None],
    check: Callable[[object, CrashOutcome], None],
    crash_points: Optional[Sequence[int]] = None,
    total_ops: Optional[int] = None,
) -> CrashMatrixReport:
    """Replay ``faulted`` crashing at every storage-op boundary.

    ``prepare()`` builds a FRESH context (new storage root, unfaulted
    history) per crash point and returns it; ``faulted(ctx)`` runs the
    pipeline under test (one save→commit→prune cycle); ``check(ctx,
    outcome)`` asserts the recovery invariant afterwards, with faults
    uninstalled. ``crash_points`` defaults to every op ``1..N`` where N
    is counted from a dry run; pass a subsample for a fast tier — the
    dry run is then SKIPPED (callers who sampled already counted; a
    whole extra pipeline run just to label the report is waste) and
    ``total_ops`` may supply the count for the report (else the largest
    sampled point stands in).
    """
    if crash_points is None:
        ctx = prepare()
        total = count_storage_ops(lambda: faulted(ctx))
        points = list(range(1, total + 1))
    else:
        points = list(crash_points)
        total = (
            total_ops
            if total_ops is not None
            else (max(points) if points else 0)
        )
    report = CrashMatrixReport(total_ops=total)
    for k in points:
        ctx = prepare()
        sched = FaultSchedule().crash_at(k)
        with inject(sched) as ctl:
            try:
                faulted(ctx)
                crashed = False
            except SimulatedCrash:
                crashed = True
        outcome = CrashOutcome(crash_op=k, crashed=crashed)
        check(ctx, outcome)
        report.outcomes[k] = outcome
    return report
