"""faultline: deterministic fault injection + crash-consistency checking.

Dynamic proof for what snapcheck (``torchsnapshot_tpu.analysis``) proves
statically: the snapshot pipeline's durability ordering, retry layer,
commit markers, and two-phase prune uphold their invariants when storage
fails mid-flight. See ``docs/FAULTS.md``.

Three layers:

- :class:`FaultPlugin` / :func:`inject` — a ``StoragePlugin`` wrapper
  driven by a scriptable :class:`FaultSchedule`: transient cloud errors
  (429/503), permanent failures, torn writes, latency, and a hard crash
  point (op N onward raises :class:`SimulatedCrash`).
- :func:`enumerate_crash_points` / :func:`check_recovery_invariant` — run
  a save→commit→prune cycle once to count storage ops, replay it crashing
  at every op boundary (including fs.py's write→fsync→rename→dir-fsync
  sub-steps), and assert the restore-or-detect invariant after each.
- :class:`MuteRankStore` — rank-fault injection for coordinator
  collectives: a rank that never publishes must be NAMED in the healthy
  ranks' shared-deadline ``TimeoutError``, not hang them.
"""

from .crashpoints import (
    CrashMatrixReport,
    CrashOutcome,
    assert_reclaimed,
    check_recovery_invariant,
    count_storage_ops,
    enumerate_crash_points,
)
from .plugin import FaultPlugin, inject
from .rankfaults import MuteRankStore, mute_patterns_for_rank
from .schedule import (
    FaultController,
    FaultRecord,
    FaultRule,
    FaultSchedule,
    InjectedPermanentError,
    InjectedTransientError,
    SimulatedCrash,
    TornWrite,
)

__all__ = [
    "CrashMatrixReport",
    "CrashOutcome",
    "FaultController",
    "FaultPlugin",
    "FaultRecord",
    "FaultRule",
    "FaultSchedule",
    "InjectedPermanentError",
    "InjectedTransientError",
    "MuteRankStore",
    "SimulatedCrash",
    "TornWrite",
    "assert_reclaimed",
    "check_recovery_invariant",
    "count_storage_ops",
    "enumerate_crash_points",
    "inject",
    "mute_patterns_for_rank",
]
