"""Fault model and scriptable schedules for deterministic injection.

A :class:`FaultSchedule` is an ordered list of :class:`FaultRule`\\ s plus
an optional global crash point. Every storage-op boundary — plugin-level
ops emitted by :class:`~torchsnapshot_tpu.faultline.plugin.FaultPlugin`
("write", "read", "delete", "list", "age", "size", "durable", "close")
and backend sub-steps emitted through
:func:`torchsnapshot_tpu.io_types.emit_storage_op` ("fs.write.tmp",
"fs.write.fsync", "fs.write.rename", "fs.write.dirsync") — consults the
schedule through a shared :class:`FaultController`, which also assigns
each boundary a monotonically increasing **op index**. The crash point is
expressed against that index: op N *onward* raises
:class:`SimulatedCrash`, modeling a process that stops executing.

Fault kinds:

- **transient** — a cloud-shaped retryable error (429/503 with a
  structured ``.code``), fired a bounded number of times; the real retry
  layer must absorb it.
- **permanent** — an error that fires on every match; retries exhaust and
  the failure propagates.
- **torn write** — the payload is truncated at byte ``keep_bytes`` and
  written through before the error raises: the backend now holds a
  partial object, exactly what an interrupted upload leaves.
- **latency** — a sleep before the op proceeds.
- **crash** — :class:`SimulatedCrash` from this boundary onward, forever
  (a dead process never comes back).
- **host loss** — a hot-tier peer host is preempted at a deterministic
  op boundary (its RAM replicas vanish; ``hottier.kill_host``); the op
  stream continues and the loss surfaces wherever the tier next touches
  the dead host. For a host backed by a REAL snapwire peer process,
  ``kill_host`` SIGKILLs the process and aborts its in-flight transport
  connections, so a blocked socket read observes the loss within the
  RPC deadline instead of hanging until timeout.
- **host flap** (``flap_host``) — deterministic lose-then-rejoin churn:
  the host is lost exactly like ``lose_host`` at the matched boundary,
  then revived ``revive_after_ops`` boundaries later — a wire-backed
  peer as a FRESH subprocess one membership generation up
  (``hottier.repair.respawn_host``; its empty store is never trusted
  with the predecessor's replicas), an in-process host alive-and-empty.
  The building block of the snapmend host-churn repair tests.
- **wire faults** (``drop_conn`` / ``torn_frame`` / ``slow_wire``) —
  the snapwire replication transport's failure modes, armed at a
  deterministic ``hottier.replicate`` boundary and consumed by the next
  matching RPC: a *dropped connection* aborts the socket before the
  request leaves, a *torn frame* sends a truncated frame then aborts
  (the receiver never acks — ack-at-k is backed by verified bytes or
  not given), and a *slow wire* sleeps the RPC into its
  ``TPUSNAPSHOT_REPLICATION_DEADLINE_S`` deadline. All three surface
  as transport failures and exercise the retry → spare-host →
  write-through degradation ladder.
- **server kill** — every in-process snapserve read-service dies at a
  deterministic ``snapserve.request`` boundary
  (``snapserve.kill_local_servers``): sockets abort, the listening
  port closes, and the client under test must degrade to direct
  backend reads (counted, bit-exact — the read plane's contract).
- **fleet member faults** (``kill_fleet_member`` / ``slow_fleet_member``)
  — the surgical snapfleet variants: ONE named in-process member (from
  ``snapserve.fleet.start_local_fleet``) dies or turns slow at a
  deterministic ``snapserve.request`` boundary. A kill must surface as
  client-side ring-replica failover (never an error, never a direct
  fallback while replicas live); a slow member as hung-not-dead to the
  fleet supervisor.

The schedule is deterministic by construction: rules fire on the *n*-th
match of their (op-glob, path-glob) pattern, and the crash point on a
fixed op index — replaying the same pipeline replays the same faults.
"""

import fnmatch
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from .. import telemetry, tracing
from ..telemetry import metrics as _metric_names


class SimulatedCrash(BaseException):
    """Process death at a storage-op boundary.

    Deliberately a ``BaseException``: a crash must rip through the retry
    layer, schedulers, and ``except Exception`` recovery paths the way a
    real ``SIGKILL`` would — nothing inside the pipeline may absorb it.
    """


class InjectedTransientError(Exception):
    """Cloud-shaped retryable failure (429/503).

    Carries a structured ``.code`` plus an ``errors`` attribute so the
    structural classifiers in ``io_types`` read it exactly like a
    google-api-core exception: NOT not-found, NOT range-not-satisfiable —
    hence retryable.
    """

    errors: Tuple = ()

    def __init__(self, status: int, op: str, path: str) -> None:
        super().__init__(f"injected {status} on {op}({path})")
        self.code = status


class InjectedPermanentError(Exception):
    """A failure that never goes away; retries must exhaust and surface it."""

    def __init__(self, op: str, path: str) -> None:
        super().__init__(f"injected permanent failure on {op}({path})")


# Actions a matched rule hands back to the plugin. Raising faults raise
# inside FaultController.on_op; the torn-write action must be APPLIED by
# the write path (only it holds the payload), so it travels back as data.
@dataclass
class TornWrite:
    keep_bytes: int
    # What strikes after the partial payload landed: "transient" (the
    # retry layer gets a chance to rewrite the object whole), "permanent",
    # or "crash" (a power-cut mid-upload).
    then: str = "transient"
    status: int = 503


@dataclass
class FaultRule:
    """One scheduled fault: fires on the ``nth`` .. ``nth+times-1``-th ops
    matching ``(op, path)`` globs (1-based; ``times=None`` = forever)."""

    kind: str  # "transient" | "permanent" | "torn" | "latency" | "crash"
    #          | "hostloss" | "killserver"
    #          | "killmember" | "slowmember"  (snapfleet: one NAMED member)
    #          | "drop_conn" | "torn_frame" | "slow_wire"  (snapwire)
    #          | "flap"  (snapmend: lose-then-revive churn)
    #          | "mem_pressure"  (snapmem: shrink a memory domain's cap)
    op: str = "*"
    path: str = "*"
    nth: int = 1
    times: Optional[int] = 1
    status: int = 503
    seconds: float = 0.0
    torn: Optional[TornWrite] = None
    error_factory: Optional[Callable[[str, str], Exception]] = None
    host: Optional[int] = None  # hostloss: which peer host dies
    member: Optional[str] = None  # killmember/slowmember: fleet member name
    # flap: how many further op boundaries after the loss until the
    # host comes back (a wire-backed peer as a FRESH subprocess one
    # membership generation up; an in-process host empty).
    revive_after_ops: Optional[int] = None
    # mem_pressure: which memwatch domain shrinks, and to what cap.
    domain: Optional[str] = None
    cap_bytes: Optional[int] = None
    _hits: int = field(default=0, repr=False)
    _fired: int = field(default=0, repr=False)

    def matches(self, op: str, path: str) -> bool:
        return fnmatch.fnmatchcase(op, self.op) and fnmatch.fnmatchcase(
            path, self.path
        )

    def should_fire(self) -> bool:
        """Advance the match counter; report whether the rule fires now."""
        self._hits += 1
        if self._hits < self.nth:
            return False
        if self.times is not None and self._fired >= self.times:
            return False
        self._fired += 1
        return True


class FaultSchedule:
    """Builder for a deterministic fault script.

    ::

        sched = (
            FaultSchedule()
            .transient(op="write", path=".steps/*", times=2)
            .torn_write(path="0/model/*", keep_bytes=7)
            .latency(op="read", seconds=0.01)
            .crash_at(17)                 # op 17 onward: SimulatedCrash
        )
    """

    def __init__(self) -> None:
        self.rules: List[FaultRule] = []
        self.crash_at_op: Optional[int] = None

    # ------------------------------------------------------------ builders

    def transient(
        self,
        op: str = "*",
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = 1,
        status: int = 503,
    ) -> "FaultSchedule":
        self.rules.append(
            FaultRule(
                kind="transient", op=op, path=path, nth=nth, times=times,
                status=status,
            )
        )
        return self

    def permanent(
        self, op: str = "*", path: str = "*", nth: int = 1
    ) -> "FaultSchedule":
        self.rules.append(
            FaultRule(kind="permanent", op=op, path=path, nth=nth, times=None)
        )
        return self

    def error(
        self,
        factory: Callable[[str, str], Exception],
        op: str = "*",
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = 1,
    ) -> "FaultSchedule":
        """Inject an arbitrary exception built by ``factory(op, path)`` —
        for backend-specific shapes the named kinds do not cover."""
        self.rules.append(
            FaultRule(
                kind="error", op=op, path=path, nth=nth, times=times,
                error_factory=factory,
            )
        )
        return self

    def torn_write(
        self,
        path: str = "*",
        keep_bytes: int = 0,
        nth: int = 1,
        times: Optional[int] = 1,
        then: str = "transient",
    ) -> "FaultSchedule":
        self.rules.append(
            FaultRule(
                kind="torn", op="write", path=path, nth=nth, times=times,
                torn=TornWrite(keep_bytes=keep_bytes, then=then),
            )
        )
        return self

    def latency(
        self,
        op: str = "*",
        path: str = "*",
        seconds: float = 0.01,
        nth: int = 1,
        times: Optional[int] = None,
    ) -> "FaultSchedule":
        self.rules.append(
            FaultRule(
                kind="latency", op=op, path=path, nth=nth, times=times,
                seconds=seconds,
            )
        )
        return self

    def slow_drain(
        self,
        seconds: float = 0.2,
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = None,
    ) -> "FaultSchedule":
        """Latency targeting the hot tier's ``hottier.drain`` op
        boundaries: every matched tier-down write pays ``seconds``
        before it proceeds — the deterministic way to stretch the
        ack→``.tierdown`` exposure window past a durability-lag budget
        and prove the ``durability-lag-above-budget`` doctor rule and
        the SLO engine's nonzero exit actually fire (docs/FAULTS.md)."""
        return self.latency(
            op="hottier.drain",
            path=path,
            seconds=seconds,
            nth=nth,
            times=times,
        )

    def mem_pressure(
        self,
        domain: str,
        cap_bytes: int,
        op: str = "*",
        path: str = "*",
        nth: int = 1,
    ) -> "FaultSchedule":
        """snapmem: at the ``nth`` matching op boundary, shrink the
        REPORTED cap of the named memwatch domain (``"staging_pool"``,
        ``"snapserve.cache"``, ...) to ``cap_bytes`` via
        :func:`~torchsnapshot_tpu.telemetry.memwatch.force_cap`. The
        subsystem's real budget is untouched — occupancy simply lands
        above the shrunk cap, so the doctor's
        ``host-memory-overcommit`` rule (and the slo live memory rule)
        trip deterministically in tests, exactly as they would on a
        host whose real limit came down under the workload
        (docs/FAULTS.md). Cleared by ``memwatch.reset()`` /
        ``clear_cap_overrides()``."""
        self.rules.append(
            FaultRule(
                kind="mem_pressure", op=op, path=path, nth=nth, times=1,
                domain=domain, cap_bytes=int(cap_bytes),
            )
        )
        return self

    def kill_server(
        self, op: str = "snapserve.request", path: str = "*", nth: int = 1
    ) -> "FaultSchedule":
        """Kill every in-process snapserve server at the ``nth`` op
        matching the globs (default: the ``nth`` client RPC attempt).
        The boundary fires BEFORE the RPC touches the network, so the
        matched request itself already finds the server dead — the
        deterministic mid-restore server-death scenario
        (docs/FAULTS.md). The op stream continues; the client's
        degraded direct-read fallback is the behavior under test."""
        self.rules.append(
            FaultRule(
                kind="killserver", op=op, path=path, nth=nth, times=1
            )
        )
        return self

    def kill_fleet_member(
        self,
        member: str,
        op: str = "snapserve.request",
        path: str = "*",
        nth: int = 1,
    ) -> "FaultSchedule":
        """Snapfleet: kill ONE named in-process fleet member (e.g.
        ``"m1"`` from :func:`~torchsnapshot_tpu.snapserve.fleet.
        start_local_fleet`) at the ``nth`` matching op boundary —
        ``kill_server`` made surgical. The boundary fires BEFORE the
        RPC dials, so the matched read already finds the member dead;
        the client's ring-replica failover (never an error, never a
        direct fallback while replicas live) is the behavior under
        test."""
        self.rules.append(
            FaultRule(
                kind="killmember",
                op=op,
                path=path,
                nth=nth,
                times=1,
                member=member,
            )
        )
        return self

    def slow_fleet_member(
        self,
        member: str,
        seconds: float = 0.05,
        op: str = "snapserve.request",
        path: str = "*",
        nth: int = 1,
    ) -> "FaultSchedule":
        """Snapfleet: inject ``seconds`` of per-request latency into ONE
        named fleet member's server loop (every request it answers from
        then on pays it) — the slow-but-alive member scenario. The
        supervisor must classify it hung-not-dead (strikes, no
        immediate down), and clients keep getting correct bytes,
        slower."""
        self.rules.append(
            FaultRule(
                kind="slowmember",
                op=op,
                path=path,
                nth=nth,
                times=1,
                member=member,
                seconds=seconds,
            )
        )
        return self

    def slow_server(
        self,
        seconds: float = 0.05,
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = None,
    ) -> "FaultSchedule":
        """Latency targeting the snapserve client's
        ``snapserve.request`` boundaries: every matched RPC pays
        ``seconds`` before dialing — a slow/overloaded read service,
        without killing it. The deterministic way to stretch a
        service-routed restore for straggler/SLO assertions."""
        return self.latency(
            op="snapserve.request",
            path=path,
            seconds=seconds,
            nth=nth,
            times=times,
        )

    def drop_conn(
        self,
        host: Optional[int] = None,
        op: str = "hottier.replicate",
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = 1,
    ) -> "FaultSchedule":
        """Snapwire: the connection to peer ``host`` (None = any peer)
        dies at the ``nth`` matching op boundary — the next RPC to that
        host aborts its socket before the request leaves and fails as a
        transport error. The retry layer (jitter under
        ``TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S``) absorbs it by
        re-dialing; the schedule is deterministic because the fault is
        armed at the op boundary, not on a timer."""
        self.rules.append(
            FaultRule(
                kind="drop_conn", op=op, path=path, nth=nth, times=times,
                host=host,
            )
        )
        return self

    def torn_frame(
        self,
        host: Optional[int] = None,
        op: str = "hottier.replicate",
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = 1,
    ) -> "FaultSchedule":
        """Snapwire: the next matching RPC to peer ``host`` sends only
        HALF its frame and then aborts the connection — the receiver's
        ``readexactly`` observes the tear and never acks (a torn frame
        can only produce a NACK; the ack-at-k contract is backed by
        verified bytes or not given). The client sees a transport
        failure and retries/degrades."""
        self.rules.append(
            FaultRule(
                kind="torn_frame", op=op, path=path, nth=nth, times=times,
                host=host,
            )
        )
        return self

    def slow_wire(
        self,
        seconds: float = 0.05,
        host: Optional[int] = None,
        op: str = "hottier.replicate",
        path: str = "*",
        nth: int = 1,
        times: Optional[int] = 1,
    ) -> "FaultSchedule":
        """Snapwire: the next matching RPC to peer ``host`` pays
        ``seconds`` on the wire before the request is sent — with
        ``seconds`` above ``TPUSNAPSHOT_REPLICATION_DEADLINE_S`` the
        RPC deterministically misses its deadline (counted in
        ``tpusnapshot_hot_tier_replication_deadline_misses_total``) and
        enters the retry → spare-host → write-through ladder."""
        self.rules.append(
            FaultRule(
                kind="slow_wire", op=op, path=path, nth=nth, times=times,
                seconds=seconds, host=host,
            )
        )
        return self

    def crash_at(self, op_index: int) -> "FaultSchedule":
        """Crash at global op index ``op_index`` (1-based) and every
        boundary after it — the crash-point enumerator's lever."""
        self.crash_at_op = op_index
        return self

    def crash_on(
        self, op: str = "*", path: str = "*", nth: int = 1
    ) -> "FaultSchedule":
        """Crash at the ``nth`` op matching the globs (and stay crashed)."""
        self.rules.append(
            FaultRule(kind="crash", op=op, path=path, nth=nth, times=None)
        )
        return self

    def lose_host(
        self, host: int, op: str = "*", path: str = "*", nth: int = 1
    ) -> "FaultSchedule":
        """Preempt hot-tier peer ``host`` at the ``nth`` op matching the
        globs: its RAM store is dropped wholesale and it goes dead
        (``hottier.kill_host``), at a deterministic boundary of the op
        stream — the host-loss half of the tier-down fault matrix. The
        op itself then proceeds; the loss is observed by whichever
        replica read/drain touches the dead host next."""
        self.rules.append(
            FaultRule(
                kind="hostloss", op=op, path=path, nth=nth, times=1,
                host=host,
            )
        )
        return self

    def flap_host(
        self,
        host: int,
        revive_after_ops: int = 1,
        op: str = "*",
        path: str = "*",
        nth: int = 1,
    ) -> "FaultSchedule":
        """snapmend: deterministic lose-then-REJOIN churn. Peer host
        ``host`` is lost exactly like :meth:`lose_host` at the ``nth``
        op matching the globs (a wire-backed peer's subprocess is
        really SIGKILLed), then revived ``revive_after_ops`` op
        boundaries later: a wire-backed host comes back as a FRESH
        subprocess one membership generation up (``repair.respawn_host``
        — empty store, never trusted with its predecessor's replicas),
        an in-process host via ``tier.revive_host`` (alive, empty).
        Both the loss and the rejoin ride the op stream, so replaying
        the same pipeline replays the same churn — the building block
        of the host-churn repair tests (docs/FAULTS.md)."""
        self.rules.append(
            FaultRule(
                kind="flap", op=op, path=path, nth=nth, times=1,
                host=host, revive_after_ops=max(1, int(revive_after_ops)),
            )
        )
        return self


@dataclass
class FaultRecord:
    op_index: int
    op: str
    path: str
    kind: str


class FaultController:
    """Shared state of one injection session: the op counter, the
    schedule, the crash latch, and the injection log.

    One controller observes EVERY plugin the pipeline resolves (take,
    finalize, prune each open their own) plus backend sub-step hooks, so
    op indices form a single global sequence. Thread-safe: fs sub-steps
    fire from executor threads while plugin ops fire on the event loop.
    """

    def __init__(self, schedule: Optional[FaultSchedule] = None) -> None:
        self.schedule = schedule if schedule is not None else FaultSchedule()
        self.op_index = 0
        self.crashed = False
        self.records: List[FaultRecord] = []
        self._lock = threading.Lock()
        # flap_host revivals due at a future op index: (revive_at, host).
        # Popped at boundary entry and performed OUTSIDE the lock (a
        # wire-backed revival spawns a real subprocess).
        self._pending_revivals: List[Tuple[int, int]] = []

    # ---------------------------------------------------------- internals

    def _record(self, idx: int, op: str, path: str, kind: str) -> None:
        # Lock held by caller. The trace event satisfies "traces show
        # recovery behavior": every injected fault is visible next to the
        # storage_retry instants the retry layer emits. The matching
        # always-on counter rides beside it — one increment per instant,
        # so trace instant-count == counter-count by construction
        # (tests/test_telemetry.py pins the equality).
        self.records.append(FaultRecord(idx, op, path, kind))
        telemetry.counter(_metric_names.FAULTS_INJECTED, kind=kind).inc()
        tracing.instant(
            "fault_injected", op=op, path=path, kind=kind, op_index=idx
        )

    def _revive_flapped_host(self, host: int, op: str, path: str) -> None:
        """Bring a flapped host back (lock NOT held — a wire-backed
        revival spawns a real subprocess): remote peers return as a
        FRESH process one membership generation up, in-process hosts
        simply come back alive and empty. Either way the revived host
        holds none of its predecessor's replicas — re-replication is
        the repair plane's job, which is the point of the rule."""
        from ..hottier import repair as ht_repair
        from ..hottier import tier as ht_tier

        try:
            if ht_tier.remote_host(host) is not None:
                ht_repair.respawn_host(host)
            else:
                ht_tier.revive_host(host)
        except Exception as e:
            # A failed rejoin is a host that stayed lost — the repair
            # plane keeps re-replicating around it; the schedule streams
            # on deterministically either way.
            import logging

            logging.getLogger(__name__).warning(
                f"flap_host revival of host {host} failed: {e!r}"
            )
            return
        with self._lock:
            # The revival is in place when THIS boundary's op runs, and
            # on_op has not incremented yet — stamp the index that op
            # is about to get, not the previous boundary's.
            self._record(self.op_index + 1, op, path, "revive")

    def on_op(self, op: str, path: str) -> Optional[TornWrite]:
        """Announce one op boundary. Raises the scheduled fault, if any;
        returns a :class:`TornWrite` the caller must apply, or None."""
        due_revivals: List[int] = []
        with self._lock:
            if self._pending_revivals and not self.crashed:
                upcoming = self.op_index + 1
                still_pending: List[Tuple[int, int]] = []
                for at, host in self._pending_revivals:
                    if at <= upcoming:
                        due_revivals.append(host)
                    else:
                        still_pending.append((at, host))
                self._pending_revivals = still_pending
        for host in due_revivals:
            # Before this boundary's own faults: a revival scheduled N
            # ops after the loss is in place when the Nth op runs.
            self._revive_flapped_host(host, op, path)
        sleep_s = 0.0
        torn: Optional[TornWrite] = None
        with self._lock:
            if self.crashed:
                raise SimulatedCrash(f"(post-crash) {op}({path})")
            self.op_index += 1
            idx = self.op_index
            crash_at = self.schedule.crash_at_op
            if crash_at is not None and idx >= crash_at:
                self.crashed = True
                self._record(idx, op, path, "crash")
                raise SimulatedCrash(f"op {idx}: {op}({path})")
            for rule in self.schedule.rules:
                if not rule.matches(op, path):
                    continue
                if not rule.should_fire():
                    continue
                if rule.kind == "latency":
                    self._record(idx, op, path, "latency")
                    sleep_s += rule.seconds
                    continue
                if rule.kind == "hostloss":
                    self._record(idx, op, path, "hostloss")
                    from ..hottier import kill_host

                    kill_host(rule.host)
                    continue
                if rule.kind == "flap":
                    # Lose now (exactly lose_host: a wire peer is really
                    # SIGKILLed), rejoin revive_after_ops boundaries on.
                    self._record(idx, op, path, "flap")
                    from ..hottier import kill_host

                    kill_host(rule.host)
                    self._pending_revivals.append(
                        (idx + (rule.revive_after_ops or 1), rule.host)
                    )
                    continue
                if rule.kind in ("drop_conn", "torn_frame", "slow_wire"):
                    self._record(idx, op, path, rule.kind)
                    from ..hottier import transport

                    # Arm the wire fault; the next RPC to the matched
                    # host consumes it (for the canonical
                    # hottier.replicate boundary that IS the RPC this
                    # boundary guards — the emit fires just before the
                    # put dials).
                    transport.script_wire_fault(
                        rule.kind, host=rule.host, seconds=rule.seconds
                    )
                    continue
                if rule.kind == "mem_pressure":
                    self._record(idx, op, path, "mem_pressure")
                    from ..telemetry import memwatch

                    # Shrink the reported cap; never raises into the
                    # guarded op — the fault is the observability
                    # plane's problem to NOTICE, not the pipeline's to
                    # trip over.
                    memwatch.force_cap(
                        rule.domain or "", int(rule.cap_bytes or 0)
                    )
                    continue
                if rule.kind == "killserver":
                    self._record(idx, op, path, "killserver")
                    from ..snapserve.server import kill_local_servers

                    # kill() blocks until the server loop has aborted
                    # its sockets (never waiting on anything that takes
                    # this lock), so the very op this boundary guards
                    # already finds the server dead.
                    kill_local_servers()
                    continue
                if rule.kind == "killmember":
                    self._record(idx, op, path, "killmember")
                    from ..snapserve import fleet

                    fleet.kill_local_member(rule.member or "")
                    continue
                if rule.kind == "slowmember":
                    self._record(idx, op, path, "slowmember")
                    from ..snapserve import fleet

                    fleet.slow_local_member(
                        rule.member or "", rule.seconds
                    )
                    continue
                if rule.kind == "crash":
                    self.crashed = True
                    self._record(idx, op, path, "crash")
                    raise SimulatedCrash(f"op {idx}: {op}({path})")
                if rule.kind == "torn":
                    self._record(idx, op, path, "torn")
                    torn = rule.torn
                    break
                if rule.kind == "transient":
                    self._record(idx, op, path, "transient")
                    raise InjectedTransientError(rule.status, op, path)
                if rule.kind == "permanent":
                    self._record(idx, op, path, "permanent")
                    raise InjectedPermanentError(op, path)
                if rule.kind == "error":
                    self._record(idx, op, path, "error")
                    raise rule.error_factory(op, path)
        if sleep_s > 0.0:
            # Outside the lock. time.sleep (not asyncio): this runs both
            # on the event loop and inside executor threads; briefly
            # blocking the loop is the injected latency, by design.
            import time

            time.sleep(sleep_s)
        return torn

    def torn_followup(self, torn: TornWrite, op: str, path: str) -> None:
        """Raise the failure that struck after a torn payload landed."""
        if torn.then == "crash":
            with self._lock:
                self.crashed = True
            raise SimulatedCrash(f"torn write crash: {op}({path})")
        if torn.then == "permanent":
            raise InjectedPermanentError(op, path)
        raise InjectedTransientError(torn.status, op, path)

    # Sub-step hook (registered via io_types.add_storage_op_hook). Torn
    # actions make no sense at sub-step granularity; raising faults do.
    def on_subop(self, op: str, path: str) -> None:
        self.on_op(op, path)

    def fault_counts(self) -> dict:
        with self._lock:
            out: dict = {}
            for r in self.records:
                out[r.kind] = out.get(r.kind, 0) + 1
            return out
