"""FaultPlugin: a StoragePlugin wrapper that injects scheduled faults.

Composable over any backend (memory, fs, cloud) and installed UNDER the
retry layer by :func:`inject`, so injected transient errors exercise the
real retry policy while a :class:`~.schedule.SimulatedCrash`
(``BaseException``) rips through it the way process death would.

Layering when active::

    RetryingStoragePlugin( FaultPlugin( FSStoragePlugin | Memory... ) )

``inject`` also registers the controller as a storage-op hook
(:func:`torchsnapshot_tpu.io_types.add_storage_op_hook`), so backend
sub-step boundaries (fs.py's write → fsync → rename → dir-fsync) count
as op boundaries and can crash too.
"""

import logging
from contextlib import contextmanager
from typing import Iterator, Optional

from ..io_types import (
    IOReq,
    StoragePlugin,
    add_storage_op_hook,
    remove_storage_op_hook,
)
from .schedule import FaultController, FaultSchedule, TornWrite


class FaultPlugin(StoragePlugin):
    """Wrap ``inner``, consulting ``controller`` before every op."""

    def __init__(self, inner: StoragePlugin, controller: FaultController) -> None:
        self._inner = inner
        self._controller = controller
        self.max_write_concurrency = inner.max_write_concurrency
        self.max_read_concurrency = inner.max_read_concurrency

    async def write(self, io_req: IOReq) -> None:
        torn = self._controller.on_op("write", io_req.path)
        if torn is not None:
            await self._write_torn(io_req, torn)
            return
        await self._inner.write(io_req)

    async def _write_torn(self, io_req: IOReq, torn: TornWrite) -> None:
        # The partial payload LANDS (that is the point: the backend now
        # holds a torn object), then the scheduled failure strikes. On
        # the fs backend the inner write is still atomic tmp+rename, so
        # this models a torn OBJECT (truncated payload, complete
        # visibility protocol); to tear the protocol itself, crash
        # between fs.write.* sub-steps instead.
        payload = (
            io_req.data if io_req.data is not None else io_req.buf.getbuffer()
        )
        keep = max(0, min(torn.keep_bytes, len(payload)))
        await self._inner.write(
            IOReq(path=io_req.path, data=bytes(payload[:keep]))
        )
        self._controller.torn_followup(torn, "write", io_req.path)

    async def read(self, io_req: IOReq) -> None:
        self._controller.on_op("read", io_req.path)
        await self._inner.read(io_req)

    async def delete(self, path: str) -> None:
        self._controller.on_op("delete", path)
        await self._inner.delete(path)

    async def list_prefix(self, prefix: str):
        self._controller.on_op("list", prefix)
        return await self._inner.list_prefix(prefix)

    async def object_age_s(self, path: str) -> Optional[float]:
        self._controller.on_op("age", path)
        return await self._inner.object_age_s(path)

    async def object_size_bytes(self, path: str) -> Optional[int]:
        self._controller.on_op("size", path)
        return await self._inner.object_size_bytes(path)

    def ensure_durable(self) -> None:
        self._controller.on_op("durable", "")
        self._inner.ensure_durable()

    def close(self) -> None:
        # A dead process never closes cleanly: after a crash, close() is
        # a silent no-op — the inner plugin must NOT get a chance to
        # settle deferred durability work (fs dirent fsyncs) the real
        # crashed process would have lost. Raising here instead would
        # shadow the original SimulatedCrash inside ``finally:`` blocks.
        if self._controller.crashed:
            return
        # close IS an op boundary: a crash scheduled here dies before
        # the inner close settles deferred fsyncs (the latch above then
        # suppresses the inner call on every later close).
        self._controller.on_op("close", "")
        self._inner.close()


@contextmanager
def inject(
    schedule: Optional[FaultSchedule] = None,
    controller: Optional[FaultController] = None,
) -> Iterator[FaultController]:
    """Install fault injection process-wide for the duration of the block.

    Every storage plugin resolved while active (take, marker finalize,
    prune, reconcile each resolve their own) is wrapped in a
    :class:`FaultPlugin` sharing ONE controller — op indices form a
    single global stream — and backend sub-step hooks route to the same
    controller. With an empty schedule this is a pure op counter: the
    crash-point enumerator's dry run.

    Not reentrant, and the caller must not leak pipelines past the block
    (an async_take still draining when the block exits would keep faulting
    through the captured wrapper on its already-open plugin, but new
    plugin resolutions go back to the real backends).
    """
    from .. import storage_plugin as _sp

    ctl = controller if controller is not None else FaultController(schedule)
    prev = None

    def _wrap(plugin: StoragePlugin, url: str) -> StoragePlugin:
        # Chain over any previously installed wrap hook (the hot tier's
        # TieredPlugin in particular) instead of shadowing it: faults
        # then strike the composed stack — Fault(Tiered(backend)) when
        # the tier was enabled first — so tier-down writes and hot-tier
        # op boundaries are inside the injection domain too.
        base = plugin if prev is None else prev(plugin, url)
        return FaultPlugin(base, ctl)

    prev = _sp.set_plugin_wrap_hook(_wrap)
    add_storage_op_hook(ctl.on_subop)
    try:
        yield ctl
    finally:
        remove_storage_op_hook(ctl.on_subop)
        _sp.set_plugin_wrap_hook(prev)
        # A wire fault a drop_conn/torn_frame/slow_wire rule armed but
        # no RPC consumed (e.g. the matched host was substituted out
        # before its next dial) must not leak past the injection block
        # into an unrelated later RPC.
        try:
            from ..hottier import transport as _wire_transport

            _wire_transport.clear_wire_faults()
        except Exception:
            logging.getLogger(__name__).warning(
                "faultline: wire-fault cleanup failed", exc_info=True
            )
