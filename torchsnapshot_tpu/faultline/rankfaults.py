"""Rank-fault injection for coordinator collectives.

Models the multi-process failure the storage-side harness cannot: a rank
that dies (or wedges) BEFORE publishing its collective key. The healthy
ranks must fail fast with the shared-deadline ``TimeoutError`` that NAMES
the stalled rank(s) — never hang for world × timeout, never blame a
healthy peer.

:class:`MuteRankStore` wraps any :class:`~torchsnapshot_tpu.coord.Store`
and silently drops ``set()`` calls for the muted rank's publish keys
(barrier arrivals, all-gather values and their chunk parts, broadcast
acks) — the rank executes the collective but its writes never become
visible, exactly what process death after the local call looks like to
everyone else.
"""

import fnmatch
from typing import List, Optional

from ..coord import Store


def mute_patterns_for_rank(rank: int) -> List[str]:
    """The key globs a rank publishes through (see StoreCoordinator)."""
    return [
        f"b/*/{rank}",           # barrier arrival
        f"ag/*/{rank}",          # all-gather value (chunk head)
        f"ag/*/{rank}/part*",    # all-gather chunk parts
        f"bcack/*/{rank}",       # broadcast ack
    ]


class MuteRankStore(Store):
    """Drop publishes matching the muted rank's key patterns.

    ``mute_after`` optionally lets the first N matching publishes
    through — the rank "dies" partway into a chunked publish, leaving a
    torn value (head without parts, or some parts missing) that readers
    must treat as "never finished publishing", not garbage.
    """

    def __init__(
        self,
        inner: Store,
        rank: int,
        mute_after: int = 0,
        patterns: Optional[List[str]] = None,
    ) -> None:
        self._inner = inner
        self._patterns = (
            patterns if patterns is not None else mute_patterns_for_rank(rank)
        )
        self._let_through = mute_after
        self.dropped: List[str] = []

    def _muted(self, key: str) -> bool:
        if not any(fnmatch.fnmatchcase(key, p) for p in self._patterns):
            return False
        if self._let_through > 0:
            self._let_through -= 1
            return False
        return True

    def set(self, key: str, value: bytes) -> None:
        if self._muted(key):
            self.dropped.append(key)
            return
        self._inner.set(key, value)

    def get(self, key: str, timeout_s: float = 300.0) -> bytes:
        return self._inner.get(key, timeout_s)

    def delete(self, key: str) -> None:
        self._inner.delete(key)

    def try_get(self, key: str):
        return self._inner.try_get(key)
