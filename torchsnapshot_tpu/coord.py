"""Coordination shim: small object collectives over a KV store.

TPU-native analog of reference torchsnapshot/pg_wrapper.py:13-57. The
snapshot protocol needs only *tiny* object collectives — key lists, glob
matches, manifests (kilobytes) — plus barriers; bulk tensor data goes
process→storage, never process→process (SURVEY §5). So instead of a
NCCL/gloo process group, the backend is a key-value store:

- ``NoOpCoordinator`` — single-process; every collective degrades to the
  identity (reference pg_wrapper.py:26-29).
- ``StoreCoordinator`` — generic collectives over an abstract blocking KV
  store, with three stores:

  - ``DictStore`` — in-process shared dict (threaded multi-"rank" tests);
  - ``FileStore`` — a directory on a shared filesystem (multi-process
    tests, single-node launches);
  - ``JaxStore`` — the ``jax.distributed`` coordination service (DCN),
    the production path on multi-host TPU pods.

``get_coordinator()`` picks ``JaxStore`` automatically when
``jax.distributed`` is initialized, else ``NoOpCoordinator`` — mirroring
the reference's "degrade gracefully when dist is uninitialized" contract.

Large blobs (> ~1 MB) are chunked through the store transparently, since
coordination-service values have size limits (SURVEY §7 hard part #3).
"""

import abc
import base64
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from . import telemetry, tracing

logger = logging.getLogger(__name__)

_DEFAULT_TIMEOUT_S = 300.0
_CHUNK = 512 * 1024  # chunk size for large values through the KV store
# Max broadcast generations a source lets go unacked before it blocks on
# the oldest one's acks. A free-running source outpaces its receivers
# indefinitely (it never blocks), so purely lazy ack collection would
# never fire in a broadcast-only loop; the window bounds live keys at
# O(window x world) and doubles as backpressure.
_BC_WINDOW = 8


class Store(abc.ABC):
    """A blocking KV store: set once, get blocks until the key exists."""

    @abc.abstractmethod
    def set(self, key: str, value: bytes) -> None:
        ...

    @abc.abstractmethod
    def get(self, key: str, timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes:
        ...

    def delete(self, key: str) -> None:
        """Best-effort removal of a key (used by collective-key GC).

        Deleting an absent key is a no-op. The default is a no-op for
        stores that cannot delete — GC then degrades to unbounded keys,
        which is what every store did before GC existed.
        """

    def try_get(self, key: str) -> Optional[bytes]:
        """Non-blocking best-effort read: the value if the key exists
        *now*, else ``None``. Used by lazy broadcast-ack collection; a
        false ``None`` (e.g. a slow round-trip on a remote store) only
        defers GC to a later proof of progress, never affects
        correctness. Default: poll :meth:`get` with a tiny timeout."""
        try:
            return self.get(key, timeout_s=0.05)
        # A short-poll miss IS the expected "absent now" answer, and this
        # probe runs per pending ack — logging would flood steady state.
        except Exception:  # snapcheck: disable=swallowed-exception -- absent-now probe
            return None


class DictStore(Store):
    """In-process store shared between threads simulating ranks."""

    def __init__(self) -> None:
        self._data: Dict[str, bytes] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: bytes) -> None:
        with self._cond:
            self._data[key] = value
            self._cond.notify_all()

    def get(self, key: str, timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes:
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while key not in self._data:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"Timed out waiting for key: {key}")
                self._cond.wait(timeout=remaining)
            return self._data[key]

    def delete(self, key: str) -> None:
        with self._cond:
            self._data.pop(key, None)

    def try_get(self, key: str) -> Optional[bytes]:
        with self._cond:
            return self._data.get(key)

    def key_count(self) -> int:
        with self._cond:
            return len(self._data)


class FileStore(Store):
    """Directory-backed store for multi-process coordination on one node
    (or any shared filesystem). Writes are atomic via rename."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _file(self, key: str) -> str:
        safe = key.replace("/", "__")
        return os.path.join(self.path, safe)

    def set(self, key: str, value: bytes) -> None:
        target = self._file(key)
        fd, tmp = tempfile.mkstemp(dir=self.path)
        with os.fdopen(fd, "wb") as f:
            f.write(value)
        # No fsync: coordination keys are ephemeral per-generation values.
        # close() above precedes the rename, so live readers — including
        # NFS close-to-open peers — always see full data, and a host
        # crash kills the whole generation; durability buys nothing and
        # would cost an fsync per 512KB chunk on the collective hot path.
        # snapcheck: disable=durability-order -- ephemeral coordination keys
        os.replace(tmp, target)

    def get(self, key: str, timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes:
        target = self._file(key)
        deadline = time.monotonic() + timeout_s
        delay = 0.001
        while True:
            try:
                with open(target, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"Timed out waiting for key: {key}")
                time.sleep(delay)
                delay = min(delay * 2, 0.05)

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._file(key))
        except OSError:
            # Best-effort (Store.delete contract): a stale-handle/perms
            # hiccup on a shared filesystem must never fail the snapshot
            # whose collective triggered the GC.
            pass

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._file(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def key_count(self) -> int:
        return len(os.listdir(self.path))


class JaxStore(Store):
    """The jax.distributed coordination-service KV store (DCN).

    Values are base64-encoded because the service stores strings —
    1.33x the raw bytes vs hex's 2x (r2), which matters for the
    chunked large-value path (every byte is DCN traffic through one
    service). KV values live only within one collective generation, and
    all ranks of a job must run the same library version (the standard
    contract for any collective library), so no cross-encoding
    compatibility is attempted.
    """

    def __init__(self) -> None:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; call "
                "jax.distributed.initialize() first."
            )
        self._client = client

    def set(self, key: str, value: bytes) -> None:
        self._client.key_value_set(
            key, base64.b64encode(value).decode("ascii")
        )

    def get(self, key: str, timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes:
        try:
            val = self._client.blocking_key_value_get(
                key, int(timeout_s * 1000)
            )
        except Exception as e:
            # The coordination service surfaces expiry as a backend
            # RuntimeError (DEADLINE_EXCEEDED), not TimeoutError.
            # Normalize so the collectives' rank-naming timeout handling
            # works identically on every Store backend.
            # Match only the structured status token — a broader match
            # (any message mentioning "deadline") would rewrite
            # connection/retry errors into TimeoutError and make the
            # collectives blame a healthy peer rank.
            if "DEADLINE_EXCEEDED" in str(e):
                raise TimeoutError(
                    f"Timed out waiting for key: {key}"
                ) from e
            raise
        return base64.b64decode(val.encode("ascii"), validate=True)

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(key)
        except Exception:
            # Best-effort: a delete that races service restart or an older
            # jaxlib without key_value_delete must never fail a snapshot —
            # but the failure is still visible at debug level so a GC that
            # silently stops collecting is diagnosable.
            logger.debug(
                f"coordination-service delete of {key} failed", exc_info=True
            )

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            val = self._client.key_value_try_get(key)
        except AttributeError:
            # Older jaxlib: fall back to the short blocking poll.
            return super().try_get(key)
        except Exception as e:
            # Non-blocking probe: absence and transient failure both mean
            # "not observable now"; GC just defers (see Store.try_get).
            # Absence (NOT_FOUND) is the steady-state answer for pending
            # broadcast acks — logging it would flood DEBUG output — so
            # only genuinely unexpected failures leave a trace.
            if "NOT_FOUND" not in str(e):
                logger.debug(
                    f"coordination-service try_get of {key} failed",
                    exc_info=True,
                )
            return None
        return base64.b64decode(val.encode("ascii"), validate=True)


def format_rank_list(ranks: List[int], noun: str = "rank") -> str:
    """``[17]`` → "rank 17"; ``[1,2,3,7]`` → "ranks 1-3, 7". Runs
    compress to ranges so a pod-scale stall (thousands of absent ranks)
    reads as a handful of spans, not a 10 KB comma list. ``noun``
    re-labels the members (the hot tier names "peer host 3" /
    "peer hosts 0-2" with the same compression). Input must be sorted
    ascending; empty input reads as "no <noun>s"."""
    if not ranks:
        return f"no {noun}s"
    if len(ranks) == 1:
        return f"{noun} {ranks[0]}"
    spans = []
    start = prev = ranks[0]
    for r in ranks[1:]:
        if r == prev + 1:
            prev = r
            continue
        spans.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = r
    spans.append(f"{start}-{prev}" if prev > start else str(start))
    return f"{noun}s " + ", ".join(spans)


class Coordinator(abc.ABC):
    """Collective interface used by Snapshot (reference PGWrapper)."""

    @abc.abstractmethod
    def get_rank(self) -> int:
        ...

    @abc.abstractmethod
    def get_world_size(self) -> int:
        ...

    @abc.abstractmethod
    def barrier(self, timeout_s: Optional[float] = None) -> None:
        """Block until every rank arrives.

        ``timeout_s`` overrides the coordinator's default wait for this
        one barrier. Callers that barrier behind a long-latency rank-0
        operation (storage-marker commit, metadata write over a cloud
        backend) must pass the operation's own timeout here — otherwise
        waiting ranks raise a spurious TimeoutError at the store default
        while the operation is still legitimately in flight (ADVICE r3).
        """

    @abc.abstractmethod
    def all_gather_object(self, obj: Any) -> List[Any]:
        ...

    @abc.abstractmethod
    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        ...


class NoOpCoordinator(Coordinator):
    def get_rank(self) -> int:
        return 0

    def get_world_size(self) -> int:
        return 1

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        pass

    def all_gather_object(self, obj: Any) -> List[Any]:
        return [obj]

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        return obj


class StoreCoordinator(Coordinator):
    """Object collectives over a :class:`Store`.

    Every collective consumes one *generation* so keys never collide across
    successive operations; all processes must issue the same sequence of
    collectives (same discipline as any process group).

    **Key garbage collection.** A job taking snapshots every N steps for
    weeks must not grow the coordination service without bound (VERDICT r2
    weak #3), so each rank deletes its *own* keys once global progress
    proves no rank can still read them. The proof: ranks issue collectives
    sequentially, and in a barrier or all-gather at generation ``g`` every
    rank sets its own ``…/g/<rank>`` key only *after* finishing every
    operation of generations ``< g`` (including all reads). So the moment
    this rank has observed all world-size keys of generation ``g``, every
    key this rank wrote at generations ``< g`` has been read by everyone
    who ever will — it deletes them. Broadcast completion proves nothing
    by itself about non-source ranks, so receivers additionally *ack*
    each broadcast with a tiny per-generation key; the source collects
    acks lazily (non-blocking) at its next broadcast and deletes both its
    payload keys and the acks (VERDICT r3 weak #6 — a broadcast-only
    steady state, e.g. a restore(step=None) serving loop, must not grow
    the store). Whichever proof lands first wins: ack collection and
    barrier/gather progress both delete the same keys, and double-delete
    is a no-op. Steady state: O(keys-per-collective) live keys per rank —
    O(world) total — instead of O(operations x world).
    """

    def __init__(self, store: Store, rank: int, world_size: int,
                 timeout_s: float = _DEFAULT_TIMEOUT_S) -> None:
        self._store = store
        self._rank = rank
        self._world = world_size
        # Stamp the trace identity the moment a rank is known, so every
        # trace this process flushes is mergeable (telemetry/merge.py).
        tracing.set_identity(rank=rank)
        self._gen = 0
        self._timeout_s = timeout_s
        # (generation, key) for every key this rank wrote and has not yet
        # proven globally consumed.
        self._own_keys: List[tuple] = []
        # Generations at which this rank was a broadcast *source* and has
        # not yet observed every receiver's ack (oldest first).
        self._pending_bc: List[int] = []

    def _gc_through(self, proven_gen: int) -> None:
        """Delete own keys of generations < ``proven_gen`` (all ranks are
        proven past them); keep the rest pending."""
        keep = []
        for gen, key in self._own_keys:
            if gen < proven_gen:
                self._store.delete(key)
            else:
                keep.append((gen, key))
        self._own_keys = keep
        self._pending_bc = [g for g in self._pending_bc if g >= proven_gen]

    def get_rank(self) -> int:
        return self._rank

    def get_world_size(self) -> int:
        return self._world

    def _next_gen(self) -> int:
        self._gen += 1
        return self._gen

    def _set_chunked(self, key: str, payload: bytes, gen: int) -> None:
        if len(payload) <= _CHUNK:
            self._store.set(key, b"\x00" + payload)
            self._own_keys.append((gen, key))
        else:
            n = -(-len(payload) // _CHUNK)
            for i in range(n):
                part = f"{key}/part{i}"
                self._store.set(part, payload[i * _CHUNK:(i + 1) * _CHUNK])
                self._own_keys.append((gen, part))
            self._store.set(key, b"\x01" + str(n).encode())
            self._own_keys.append((gen, key))

    def _remaining(self, deadline: Optional[float]) -> float:
        if deadline is None:
            return self._timeout_s
        # Floor, don't clamp to zero: a zero budget would make backends
        # that check the deadline before the key (JaxStore's
        # blocking_key_value_get at 0 ms) time out even on a key that is
        # already published — and the caller would then blame a healthy
        # rank. The floor keeps "present key always wins" and bounds the
        # deadline overshoot at ~50 ms per remaining key.
        return max(0.05, deadline - time.monotonic())

    def _get_chunked(
        self, key: str, deadline: Optional[float] = None
    ) -> bytes:
        head = self._store.get(key, self._remaining(deadline))
        if head[:1] == b"\x00":
            return head[1:]
        n = int(head[1:].decode())
        return b"".join(
            self._store.get(f"{key}/part{i}", self._remaining(deadline))
            for i in range(n)
        )

    def _absent_ranks(self, key_fmt: str, first: int) -> List[int]:
        """``first`` plus every later rank whose key is absent *now* — a
        non-blocking sweep so a timeout error names EVERY straggler (at
        pod scale "ranks 17, 40-63" localizes the failure; "rank 17"
        alone does not). A false absent from a remote-store hiccup only
        over-names the report; the operation already failed."""
        missing = [first]
        for r in range(first + 1, self._world):
            if self._store.try_get(key_fmt.format(rank=r)) is None:
                missing.append(r)
        return missing

    @staticmethod
    def _fmt_ranks(ranks: List[int]) -> str:
        return format_rank_list(ranks)

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        wait = self._timeout_s if timeout_s is None else timeout_s
        gen = self._next_gen()
        key = f"b/{gen}/{self._rank}"
        self._store.set(key, b"1")
        self._own_keys.append((gen, key))
        # One shared deadline for the whole barrier, not a fresh timeout
        # per rank: the caller's timeout bounds the OPERATION (a per-rank
        # budget would let the total wait grow to world x timeout), and
        # every rank that never arrives is named in the error instead of
        # surfacing as an opaque store-key timeout.
        deadline = time.monotonic() + wait
        wait_t0 = time.monotonic()
        try:
            for r in range(self._world):
                try:
                    self._store.get(f"b/{gen}/{r}", self._remaining(deadline))
                except TimeoutError:
                    missing = self._absent_ranks(f"b/{gen}/{{rank}}", r)
                    raise TimeoutError(
                        f"barrier (generation {gen}) timed out after "
                        f"{wait:g}s: {self._fmt_ranks(missing)} never arrived "
                        f"(observed by rank {self._rank} of {self._world}); "
                        f"likely crashed or stuck in storage IO."
                    ) from None
        finally:
            # Timed-out barriers observe too: a stall that ends in an
            # error is exactly the wait a dashboard must show.
            telemetry.record_coord_wait(
                "barrier", time.monotonic() - wait_t0
            )
        # Barrier-exit instant: every rank passes this point only after
        # the LAST rank arrived, so across ranks the same generation's
        # instants mark (approximately) one global wall-clock moment —
        # the clock-skew anchors telemetry/merge.py aligns traces with.
        tracing.instant("barrier_exit", gen=gen)
        self._gc_through(gen)

    def all_gather_object(self, obj: Any) -> List[Any]:
        gen = self._next_gen()
        self._set_chunked(
            f"ag/{gen}/{self._rank}", pickle.dumps(obj, protocol=4), gen
        )
        # Same shared-deadline discipline as barrier: self._timeout_s
        # bounds the whole gather — a fresh budget per rank key (or per
        # chunk part) would let the worst-case wait grow to world x
        # timeout.
        deadline = time.monotonic() + self._timeout_s
        out = []
        wait_t0 = time.monotonic()
        try:
            for r in range(self._world):
                try:
                    out.append(
                        pickle.loads(
                            self._get_chunked(f"ag/{gen}/{r}", deadline)
                        )
                    )
                except TimeoutError:
                    missing = self._absent_ranks(f"ag/{gen}/{{rank}}", r)
                    raise TimeoutError(
                        f"all_gather (generation {gen}) timed out after "
                        f"{self._timeout_s:g}s total: "
                        f"{self._fmt_ranks(missing)} never "
                        f"finished publishing (observed by rank "
                        f"{self._rank} of {self._world})."
                    ) from None
        finally:
            telemetry.record_coord_wait(
                "all_gather", time.monotonic() - wait_t0
            )
        self._gc_through(gen)
        return out

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        gen = self._next_gen()
        if self._rank == src:
            self._collect_broadcast_acks()
            self._set_chunked(f"bc/{gen}", pickle.dumps(obj, protocol=4), gen)
            self._pending_bc.append(gen)
            # Bounded in-flight window: block on the oldest generation's
            # acks once too many are outstanding. Safe — receivers are
            # sequential and the pending payloads all exist, so every
            # receiver reaches (and acks) the oldest one without needing
            # anything further from this rank.
            while len(self._pending_bc) > _BC_WINDOW:
                self._collect_broadcast_acks(block_oldest=True)
            return obj
        self._prune_consumed_acks()
        deadline = time.monotonic() + self._timeout_s
        wait_t0 = time.monotonic()
        try:
            out = pickle.loads(self._get_chunked(f"bc/{gen}", deadline))
        except TimeoutError:
            raise TimeoutError(
                f"broadcast (generation {gen}) timed out after "
                f"{self._timeout_s:g}s total: source rank {src} never "
                f"finished publishing (receiving rank {self._rank} of "
                f"{self._world})."
            ) from None
        finally:
            telemetry.record_coord_wait(
                "broadcast", time.monotonic() - wait_t0
            )
        # Ack after the read completes: the source may delete the payload
        # keys the moment all acks exist. The ack is also tracked in
        # _own_keys so barrier/gather progress collects it if the source
        # never broadcasts again.
        ack = f"bcack/{gen}/{self._rank}"
        self._store.set(ack, b"1")
        self._own_keys.append((gen, ack))
        return out

    def _prune_consumed_acks(self) -> None:
        """Receiver-side bookkeeping GC: drop own ack entries whose store
        keys the source already deleted. Without this, a broadcast-only
        receiver loop grows ``_own_keys`` by one tuple per broadcast
        forever, then floods the store with an O(history) burst of no-op
        deletes at the next barrier/gather. Oldest first, stop at the
        first still-present ack — the source consumes acks in generation
        order, so later acks cannot be gone either. A false absent probe
        (remote-store hiccup) merely skips the later self-delete of a key
        the source deletes anyway."""
        while True:
            idx = next(
                (
                    i
                    for i, (_, k) in enumerate(self._own_keys)
                    if k.startswith("bcack/")
                ),
                None,
            )
            if idx is None or self._store.try_get(
                self._own_keys[idx][1]
            ) is not None:
                return
            self._own_keys.pop(idx)

    def _collect_broadcast_acks(self, block_oldest: bool = False) -> None:
        """Source-side GC of broadcast payload keys.

        Oldest pending generation first; stop at the first generation not
        fully acked — ranks issue collectives sequentially, so a receiver
        that has not acked generation ``g`` cannot have acked any later
        one, and checking further would waste non-blocking probes. With
        ``block_oldest`` the first generation is waited on (window
        overflow) rather than probed."""
        first = True
        while self._pending_bc:
            gen = self._pending_bc[0]
            acks = [
                f"bcack/{gen}/{r}"
                for r in range(self._world)
                if r != self._rank
            ]
            if block_oldest and first:
                deadline = time.monotonic() + self._timeout_s
                for a in acks:
                    try:
                        self._store.get(a, self._remaining(deadline))
                    except TimeoutError:
                        raise TimeoutError(
                            f"broadcast ack (generation {gen}) timed out "
                            f"after {self._timeout_s:g}s total: rank "
                            f"{a.rsplit('/', 1)[1]} never acknowledged "
                            f"(source rank {self._rank} of "
                            f"{self._world})."
                        ) from None
                first = False
            elif any(self._store.try_get(a) is None for a in acks):
                return
            for a in acks:
                self._store.delete(a)
            keep = []
            for g, key in self._own_keys:
                if g == gen:
                    self._store.delete(key)
                else:
                    keep.append((g, key))
            self._own_keys = keep
            self._pending_bc.pop(0)


def barrier_compat(coordinator: "Coordinator", timeout_s: float) -> None:
    """``coordinator.barrier(timeout_s=...)``, tolerating out-of-tree
    Coordinator implementations written against the pre-r4 ABC whose
    ``barrier(self)`` takes no timeout — they must degrade to their own
    default wait, not raise TypeError at the commit barrier after all
    the expensive storage work already succeeded."""
    import inspect

    try:
        params = inspect.signature(coordinator.barrier).parameters
        accepts = "timeout_s" in params or any(
            p.kind is p.VAR_KEYWORD for p in params.values()
        )
    except (ValueError, TypeError):
        accepts = False
    if accepts:
        coordinator.barrier(timeout_s=timeout_s)
    else:
        coordinator.barrier()


# Process-wide singleton: collective key generations must advance
# monotonically across *all* snapshot operations in a process — a fresh
# StoreCoordinator per take() would restart at generation 1 and collide
# with keys already present in the persistent coordination service.
_default_coordinator: Optional[Coordinator] = None
_default_coordinator_lock = threading.Lock()


def get_coordinator(coord: Optional[Coordinator] = None) -> Coordinator:
    """Resolve the coordinator: explicit > jax.distributed > single-process.

    Reference analog: PGWrapper's fallback to WORLD / no-op
    (pg_wrapper.py:24-29). The auto-resolved jax.distributed coordinator is
    a process-wide singleton so successive snapshot operations never reuse
    KV keys. Explicitly-passed coordinators are likewise expected to be
    long-lived (one per process, like a process group).
    """
    global _default_coordinator
    if coord is not None:
        return coord
    with _default_coordinator_lock:
        if _default_coordinator is not None:
            return _default_coordinator
        try:
            import jax
            from jax._src import distributed

            client = distributed.global_state.client
        except (ImportError, AttributeError):
            # jax absent or its internals moved: single-process semantics.
            client = None
        if client is None:
            # jax.distributed not initialized — single-process. Not cached,
            # so a later jax.distributed.initialize() is still honored
            # (initialize() must precede the first *multi-process* snapshot
            # op, as with any process group).
            return NoOpCoordinator()
        # jax.distributed IS initialized: failures past this point must
        # raise, not silently degrade to a world-size-1 coordinator that
        # would corrupt multi-host snapshots.
        _default_coordinator = StoreCoordinator(
            store=JaxStore(),
            rank=jax.process_index(),
            world_size=jax.process_count(),
        )
        return _default_coordinator
