"""Flagship workload: a decoder-only transformer with dp/sp/tp shardings.

The reference has no model code — its flagship workload is a torchrec DLRM
whose row-wise-sharded embedding tables drive the sharded-checkpoint path
(reference examples/torchrec_example.py:85-128). The TPU build's flagship
is a pjit transformer: it exercises every state category the snapshot
layer supports (tp-sharded matrices, dp-replicated scales, optimizer
moments mirroring the params, PRNG keys, host-side progress), and it is
the model the driver compile-checks (`__graft_entry__.py`) and the
benchmark trains.

TPU-first design notes:
- all matmuls are einsums over [B, S, D] x [D, ...] — large, batched,
  MXU-shaped; params bf16-able (kept f32 here for optimizer exactness,
  cast at use via `cast_dtype`);
- sharding: weights tp-sharded on their hidden dims, activations
  constrained to P(dp, sp, None) so sequence parallelism rides the mesh's
  "sp" axis; XLA inserts the all-gathers/reduce-scatters over ICI;
- static shapes, no data-dependent control flow: the whole train step is
  one jit program.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import flash_attention, resolve_flash_block
from ..parallel.mesh import shard_pytree
from ..parallel.ring_attention import (
    ring_attention,
    ring_attention_zigzag,
    zigzag_indices,
)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 4
    # Key/value heads (grouped-query attention): n_heads % n_kv_heads
    # == 0; q-head h attends kv-head h // group. None = n_heads (dense
    # MHA). Under ring attention the K/V slices that rotate over ICI
    # shrink by the group factor — GQA is a long-context communication
    # optimization, not just a KV-cache one.
    n_kv_heads: Any = None
    n_layers: int = 2
    d_ff: int = 512
    max_seq_len: int = 128
    dtype: Any = jnp.float32
    # Use the fused Pallas attention kernel (ops/attention.py) instead of
    # materializing the S×S score matrix. Off by default: the einsum path
    # is the numerical reference (the kernel's online softmax reassociates
    # reductions, so outputs match to float tolerance, not bitwise).
    flash_attention: bool = False
    # Ring attention (parallel/ring_attention.py) over the mesh's "sp"
    # axis: exact attention with K/V slices rotating over ICI, so no
    # device gathers the full sequence — the long-context path. Requires
    # a mesh with an "sp" axis; mutually exclusive with flash_attention.
    #   False          — off (dense einsum attention)
    #   True｜"contiguous" — contiguous layout: device j holds tokens
    #                    [j·S/n, (j+1)·S/n); causal wall-clock tracks the
    #                    busiest (last) device
    #   "zigzag"       — balanced layout: device j holds sub-chunks j and
    #                    2n−1−j, making causal work per device constant.
    #                    The whole train step runs in zigzag token order
    #                    (loss_fn permutes tokens/targets once at the
    #                    input); forward() then expects tokens ALREADY in
    #                    zigzag order and returns logits in that order.
    #   "ulysses"      — all-to-all sequence parallelism: one all_to_all
    #                    re-partitions [B,H,S/n,D] -> [B,H/n,S,D], each
    #                    device runs FULL-sequence (flash) attention on
    #                    its head subset, and a second all_to_all
    #                    restores the layout. Needs per-device heads
    #                    divisible by the sp axis; tokens stay in
    #                    original order.
    ring_attention: Any = False
    # Local attention implementation for every sequence-parallel mode:
    # "einsum" or "flash" (the fused Pallas kernel via its custom VJP —
    # differentiable, O(rows·D) on-device memory). For ring modes this
    # is the per-chunk attention and resolve_flash_block applies to the
    # RING CHUNK length (S / sp, halved again under zigzag); for
    # "ulysses" it is the full-sequence local attention and the
    # constraint applies to the GLOBAL sequence length S.
    ring_chunk_impl: str = "einsum"


def _n_kv_heads(config: "TransformerConfig") -> int:
    """Normalized kv-head count: None = dense MHA; 0 or a non-divisor of
    n_heads is a configuration error, not a silent fallback."""
    n_kv = config.n_kv_heads
    if n_kv is None:
        return config.n_heads
    if n_kv <= 0 or config.n_heads % n_kv:
        raise ValueError(
            f"n_heads ({config.n_heads}) must be a positive multiple of "
            f"n_kv_heads ({n_kv})"
        )
    return n_kv


def _ring_mode(config: "TransformerConfig") -> Optional[str]:
    """Normalize config.ring_attention to
    None | "contiguous" | "zigzag" | "ulysses"."""
    r = config.ring_attention
    if r is False or r is None:
        return None
    if r is True or r == "contiguous":
        return "contiguous"
    if r in ("zigzag", "ulysses"):
        return r
    raise ValueError(f"unknown ring_attention mode: {r!r}")


def init_params(config: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Plain-container pytree of parameters (snapshot-friendly)."""
    keys = jax.random.split(key, config.n_layers + 2)
    scale = 1.0 / np.sqrt(config.d_model)

    def dense(k, shape):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(
            config.dtype
        )

    n_kv = _n_kv_heads(config)
    kv_dim = (config.d_model // config.n_heads) * n_kv
    layers = []
    for i in range(config.n_layers):
        lk = jax.random.split(keys[i], 6)
        layers.append(
            {
                "attn": {
                    "wq": dense(lk[0], (config.d_model, config.d_model)),
                    "wk": dense(lk[1], (config.d_model, kv_dim)),
                    "wv": dense(lk[2], (config.d_model, kv_dim)),
                    "wo": dense(lk[3], (config.d_model, config.d_model)),
                },
                "mlp": {
                    "w1": dense(lk[4], (config.d_model, config.d_ff)),
                    "w2": dense(lk[5], (config.d_ff, config.d_model)),
                },
                "ln1": jnp.ones((config.d_model,), dtype=jnp.float32),
                "ln2": jnp.ones((config.d_model,), dtype=jnp.float32),
            }
        )
    return {
        "embed": dense(keys[-2], (config.vocab_size, config.d_model)),
        "pos_embed": dense(keys[-1], (config.max_seq_len, config.d_model)),
        "final_ln": jnp.ones((config.d_model,), dtype=jnp.float32),
        "layers": layers,
    }


def param_sharding_rules(keys: Tuple[str, ...], leaf: Any) -> Optional[P]:
    """tp-shard the big matrices; replicate norms and positions.

    Column-parallel (wq/wk/wv/w1) shard the output dim; row-parallel
    (wo/w2) shard the input dim — the Megatron layout, expressed as
    shardings for XLA to lower onto ICI collectives.
    """
    name = keys[-1]
    if name in ("wq", "wk", "wv", "w1"):
        return P(None, "tp")
    if name in ("wo", "w2"):
        return P("tp", None)
    if name == "embed":
        return P("tp", None)  # vocab-sharded
    return P()


def _layer_norm(x, scale):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + 1e-6) * scale


def _activation_spec(mesh: Optional[Mesh]) -> Optional[P]:
    if mesh is None:
        return None
    names = mesh.axis_names
    return P(
        "dp" if "dp" in names else None,
        "sp" if "sp" in names else None,
        None,
    )


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, S] int32
    config: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Logits [B, S, V]. Pure function; jit/pjit-able."""
    act_spec = _activation_spec(mesh)

    def constrain(x):
        if act_spec is not None:
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, act_spec)
            )
        return x

    def constrain4(x, spec):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    _, seq_len = tokens.shape
    ring_mode = _ring_mode(config)
    if ring_mode is not None:
        if config.flash_attention:
            raise ValueError(
                "flash_attention and ring_attention are mutually exclusive"
            )
        if mesh is None or "sp" not in mesh.axis_names:
            raise ValueError(
                'ring_attention requires a mesh with an "sp" axis'
            )
    if ring_mode == "zigzag":
        # Tokens arrive in zigzag order; index the positional table by
        # each slot's ORIGINAL position (a static permutation of rows of
        # a replicated parameter — free under XLA). Everything else in
        # the block stack is position-independent, and the zigzag ring
        # enforces causality w.r.t. original order itself.
        zz = zigzag_indices(seq_len, mesh.shape["sp"])
        pos_rows = jnp.take(params["pos_embed"][:seq_len], zz, axis=0)
    else:
        pos_rows = params["pos_embed"][:seq_len]
    h = params["embed"][tokens] + pos_rows
    h = constrain(h.astype(config.dtype))

    if config.flash_attention and mesh is not None:
        # pallas_call has no SPMD partitioning rule: under a mesh with
        # sp-sharded activations it would fail to lower (or silently
        # replicate), defeating the sequence parallelism this model
        # advertises. Sharded attention needs a ring/all-to-all kernel —
        # use the einsum path on meshes until then.
        raise ValueError(
            "flash_attention currently supports single-device (per-host) "
            "execution only; drop the mesh or use the einsum path."
        )
    mask = (
        None
        if (config.flash_attention or ring_mode is not None)
        else jnp.tril(jnp.ones((seq_len, seq_len), dtype=bool))
    )
    head_dim = config.d_model // config.n_heads
    n_kv_heads = _n_kv_heads(config)

    for layer in params["layers"]:
        x = _layer_norm(h, layer["ln1"])
        q = jnp.einsum("bsd,dh->bsh", x, layer["attn"]["wq"])
        k = jnp.einsum("bsd,dh->bsh", x, layer["attn"]["wk"])
        v = jnp.einsum("bsd,dh->bsh", x, layer["attn"]["wv"])
        q = q.reshape(*q.shape[:2], config.n_heads, head_dim)
        k = k.reshape(*k.shape[:2], n_kv_heads, head_dim)
        v = v.reshape(*v.shape[:2], n_kv_heads, head_dim)
        if config.flash_attention:
            block = resolve_flash_block(seq_len)
            attn = flash_attention(
                q.transpose(0, 2, 1, 3),
                k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3),
                causal=True,
                block_q=block,
                block_k=block,
            ).transpose(0, 2, 1, 3)
        elif ring_mode is not None:
            # [B, S, H, Dh] -> [B, H, S, Dh]: sequence rides "sp", batch
            # rides "dp", and heads ride "tp" (q/k/v are tp-column-
            # sharded already — replicating heads here would all-gather
            # them and redo attention tp-fold); shard_map inside the jit
            # trace needs the spec passed explicitly.
            names = mesh.axis_names
            head_axis = (
                "tp"
                if "tp" in names
                and config.n_heads % mesh.shape["tp"] == 0
                and n_kv_heads % mesh.shape["tp"] == 0
                else None
            )
            ring_spec = P(
                "dp" if "dp" in names else None, head_axis, "sp", None
            )
            qr = constrain4(q.transpose(0, 2, 1, 3), ring_spec)
            kr = constrain4(k.transpose(0, 2, 1, 3), ring_spec)
            vr = constrain4(v.transpose(0, 2, 1, 3), ring_spec)
            if ring_mode == "zigzag":
                attn = ring_attention_zigzag(
                    qr, kr, vr, mesh, axis="sp", spec=ring_spec,
                    chunk_impl=config.ring_chunk_impl,
                ).transpose(0, 2, 1, 3)
            elif ring_mode == "ulysses":
                from ..parallel.ulysses import ulysses_attention

                attn = ulysses_attention(
                    qr, kr, vr, mesh, axis="sp", causal=True,
                    spec=ring_spec, attn_impl=config.ring_chunk_impl,
                ).transpose(0, 2, 1, 3)
            else:
                attn = ring_attention(
                    qr, kr, vr, mesh, axis="sp", causal=True,
                    spec=ring_spec, chunk_impl=config.ring_chunk_impl,
                ).transpose(0, 2, 1, 3)
        else:
            if n_kv_heads != config.n_heads:
                # Dense einsum is the numerical reference path; repeating
                # kv heads is the textbook GQA semantics (the kernels
                # avoid the materialization; this path keeps it simple).
                group = config.n_heads // n_kv_heads
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            scores = jnp.einsum("bqnd,bknd->bnqk", q, k) / np.sqrt(head_dim)
            scores = jnp.where(mask[None, None, :, :], scores, -1e30)
            probs = jax.nn.softmax(
                scores.astype(jnp.float32), axis=-1
            ).astype(config.dtype)
            attn = jnp.einsum("bnqk,bknd->bqnd", probs, v)
        attn = attn.reshape(*attn.shape[:2], config.d_model)
        h = h + constrain(jnp.einsum("bsh,hd->bsd", attn, layer["attn"]["wo"]))

        x = _layer_norm(h, layer["ln2"])
        ff = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, layer["mlp"]["w1"]))
        h = h + constrain(jnp.einsum("bsf,fd->bsd", ff, layer["mlp"]["w2"]))

    h = _layer_norm(h, params["final_ln"])
    logits = jnp.einsum("bsd,vd->bsv", h, params["embed"])  # tied head
    return logits.astype(jnp.float32)


def loss_fn(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Next-token cross entropy. ``tokens`` are in original order.

    Under ``ring_attention="zigzag"`` the permutation to zigzag order
    happens HERE, once per step, on int32 token ids (4 bytes/token over
    the interconnect — the activations never leave zigzag order): tokens,
    their next-token targets, and the validity mask are permuted
    together, the forward runs entirely in zigzag order, and the loss —
    a masked mean, permutation-invariant — matches the dense loss to
    float tolerance.
    """
    if _ring_mode(config) == "zigzag":
        s = tokens.shape[1]
        idx = zigzag_indices(s, mesh.shape["sp"])
        # Next-token targets in original order; the final position has
        # no target (the rolled-in first token is masked out).
        targets = jnp.roll(tokens, -1, axis=1)
        valid = (jnp.arange(s) < s - 1).astype(jnp.float32)
        ztok = jnp.take(tokens, idx, axis=1)
        ztgt = jnp.take(targets, idx, axis=1)
        zval = jnp.take(valid, idx)
        logits = forward(params, ztok, config, mesh)  # zigzag order
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, ztgt[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * zval[None]) / (tokens.shape[0] * (s - 1))
    logits = forward(params, tokens, config, mesh)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def sgd_train_step(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: TransformerConfig,
    mesh: Optional[Mesh] = None,
    lr: float = 1e-2,
) -> Tuple[Dict[str, Any], jax.Array]:
    """One SGD step — self-contained (no optax) so __graft_entry__ can jit
    the *full* training step without external state plumbing."""
    loss, grads = jax.value_and_grad(partial(loss_fn, config=config, mesh=mesh))(
        params, tokens
    )
    new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype), params, grads)
    return new_params, loss


def shard_params(params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    return shard_pytree(params, mesh, param_sharding_rules)
