"""URL → StoragePlugin dispatch.

TPU-native analog of reference torchsnapshot/storage_plugin.py:16-60.
Protocols: ``fs`` (default when no ``://`` present), ``memory``, ``gs``,
``s3``, ``snapserve`` (the read-plane client,
``snapserve://host:port/<backend-url>``); unknown protocols resolve
through the ``storage_plugins`` Python
entry-point group so third-party backends can register themselves
(reference storage_plugin.py:43-58).

Also home to :class:`RefRouterPlugin`, the storage-side half of
incremental snapshots: manifest entries whose payload lives in a BASE
snapshot (unchanged since that take — never rewritten) resolve through
``@base<N>/<location>`` paths that the router forwards to the base
snapshot's own storage root.
"""

import logging
from importlib import metadata as importlib_metadata
from typing import Callable, Dict, List, Optional, Tuple

from .io_types import IOReq, RetryingStoragePlugin, StoragePlugin
from .storage_plugins.fs import FSStoragePlugin
from .storage_plugins.memory import MemoryStoragePlugin

logger = logging.getLogger(__name__)

# Shared in-memory "buckets" keyed by root so that memory://foo resolves to
# the same store across plugin instances within a process (tests, async
# staging targets).
_MEMORY_STORES: Dict[str, Dict[str, bytes]] = {}

# Fault-injection seam (torchsnapshot_tpu.faultline): when set, every
# resolved backend is passed through this wrapper BEFORE the retry layer,
# so injected transient failures exercise the real retry policy while an
# injected crash (a BaseException) rips straight through it — the same
# layering a real backend failure or process death would see. Process-
# global on purpose: take/finalize/prune each resolve their own plugin
# instance, and one controller must observe them all as one op stream.
_PLUGIN_WRAP_HOOK: Optional[Callable[[StoragePlugin, str], StoragePlugin]] = None


def set_plugin_wrap_hook(hook):
    """Install (or, with None, clear) the plugin wrapper applied to every
    backend ``url_to_storage_plugin`` resolves; returns the previous hook
    so callers can restore it."""
    global _PLUGIN_WRAP_HOOK
    prev = _PLUGIN_WRAP_HOOK
    _PLUGIN_WRAP_HOOK = hook
    return prev


def url_to_storage_plugin(url_path: str) -> StoragePlugin:
    """Resolve a URL to its backend, wrapped with the retry policy (every
    storage op — payloads, metadata commit, markers, deletes — retries
    transient failures; see io_types.retry_storage_op)."""
    plugin = _resolve_plugin(url_path)
    if _PLUGIN_WRAP_HOOK is not None:
        plugin = _PLUGIN_WRAP_HOOK(plugin, url_path)
    return RetryingStoragePlugin(plugin)


def _resolve_plugin(url_path: str) -> StoragePlugin:
    if "://" in url_path:
        protocol, path = url_path.split("://", 1)
        if protocol == "":
            protocol = "fs"
    else:
        protocol, path = "fs", url_path

    if protocol == "fs":
        return FSStoragePlugin(root=path)
    if protocol == "memory":
        # Hierarchical, like a real object store: the first path segment
        # names the bucket, the rest is a key prefix within it — so
        # memory://run and memory://run/step-0 share one bucket and the
        # base root can enumerate the step's objects.
        bucket, _, prefix = path.partition("/")
        store = _MEMORY_STORES.setdefault(bucket, {})
        return MemoryStoragePlugin(store=store, prefix=prefix)
    if protocol == "snapserve":
        # Disaggregated read plane (snapserve/): reads go through the
        # caching read service at host:port, everything else straight
        # to the embedded backend URL; unreachable servers degrade to
        # direct backend reads (counted, never an error).
        from .snapserve.client import SnapServePlugin

        return SnapServePlugin(path)
    if protocol == "gs":
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path)
    if protocol == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path)

    # Third-party plugins via entry points.
    try:
        eps = importlib_metadata.entry_points()
        if hasattr(eps, "select"):
            group = list(eps.select(group="storage_plugins"))
        else:  # pragma: no cover
            group = list(eps.get("storage_plugins", []))
    except Exception:
        # Broken entry-point metadata in some unrelated package must not
        # mask the actionable "unsupported protocol" error below — but it
        # must be visible, or a mispackaged environment looks identical
        # to a missing plugin.
        logger.warning(
            f"Enumerating storage_plugins entry points for protocol "
            f"{protocol!r} failed",
            exc_info=True,
        )
        group = []
    for ep in group:
        if ep.name == protocol:
            # The plugin IS installed: a load()/constructor failure is
            # the real, actionable error — propagate it instead of
            # demoting it to "unsupported protocol".
            return ep.load()(path)
    raise RuntimeError(f"Unsupported protocol: {protocol}")


# --------------------------------------------------------- incremental refs
#
# Location namespace: a payload location beginning with "@base<N>/" lives
# under the snapshot root named by SnapshotMetadata.base_paths[N] instead
# of the snapshot's own root. Real storage locations never begin with "@"
# (they begin with "<rank>/", "replicated/", "chunked/", or ".completed/"),
# so the marker cannot collide.

_REF_MARKER = "@base"


def make_ref_location(base_idx: int, location: str) -> str:
    return f"{_REF_MARKER}{base_idx}/{location}"


def parse_ref_location(path: str) -> Optional[Tuple[int, str]]:
    """``"@base<N>/<rest>"`` → ``(N, rest)``; None for ordinary paths.
    ``N`` must be exactly what :func:`make_ref_location` emits — plain
    digits. ``int()`` alone would accept "-1"/"+1"/whitespace, and a
    negative index would wrap through Python list indexing into the
    WRONG base root instead of tripping the corrupt-metadata guard."""
    if not path.startswith(_REF_MARKER):
        return None
    head, sep, rest = path.partition("/")
    if not sep:
        return None
    digits = head[len(_REF_MARKER):]
    # ASCII digits only: isdigit() alone admits Unicode digit-likes
    # (e.g. "²") that int() then rejects with an uncaught ValueError —
    # in exactly the corrupt-input case this parse exists to neutralize.
    if not (digits.isascii() and digits.isdigit()):
        return None
    return int(digits), rest


def is_ref_location(path: str) -> bool:
    return parse_ref_location(path) is not None


def _parent_url(url: str) -> Optional[str]:
    """The parent "directory" of a snapshot URL, or None when there is
    none to speak of (e.g. ``memory://bucket`` with a rootless path)."""
    trimmed = url.rstrip("/")
    if "://" in trimmed:
        scheme, _, rest = trimmed.partition("://")
        if "/" not in rest:
            return None
        head, _, _ = rest.rpartition("/")
        return f"{scheme}://{head}"
    if "/" not in trimmed:
        return None
    return trimmed.rpartition("/")[0]


def encode_base_ref(base_path: str, own_path: str) -> str:
    """Record a base-snapshot reference portably.

    Siblings (same parent directory) are recorded relative
    (``"rel:<name>"``) so moving/renaming the whole snapshot family —
    the layout CheckpointManager produces — never breaks the chain;
    anything else is recorded absolute (``"abs:<url>"``).
    """
    bp, op = base_path.rstrip("/"), own_path.rstrip("/")
    b_parent, o_parent = _parent_url(bp), _parent_url(op)
    if b_parent is not None and b_parent == o_parent:
        return "rel:" + bp.rsplit("/", 1)[1]
    return "abs:" + bp


def resolve_base_ref(ref: str, own_path: str) -> str:
    """Resolve an encoded base reference against this snapshot's path."""
    if ref.startswith("rel:"):
        parent = _parent_url(own_path.rstrip("/"))
        if parent is None:
            raise ValueError(
                f"Cannot resolve relative base reference {ref!r}: snapshot "
                f"path {own_path!r} has no parent directory"
            )
        return f"{parent}/{ref[4:]}"
    if ref.startswith("abs:"):
        return ref[4:]
    raise ValueError(f"Malformed base reference: {ref!r}")


class RefRouterPlugin(StoragePlugin):
    """Routes ``@base<N>/…`` paths to base-snapshot storage roots.

    Wraps a snapshot's primary plugin; ordinary paths pass through
    untouched. Base plugins open lazily on first touch and close with
    the router. Writes and deletes against ``@base`` paths are refused —
    a snapshot never mutates objects another snapshot owns (the
    back-link markers written into a base during take go through an
    explicitly-opened plugin, not this router).
    """

    def __init__(self, inner: StoragePlugin) -> None:
        self._inner = inner
        self._base_urls: List[str] = []
        self._base_plugins: Dict[int, StoragePlugin] = {}
        self.max_write_concurrency = inner.max_write_concurrency
        self.max_read_concurrency = inner.max_read_concurrency

    def attach_bases(self, base_urls: List[str]) -> None:
        self._base_urls = list(base_urls)

    def _route(self, path: str) -> Tuple[StoragePlugin, str]:
        parsed = parse_ref_location(path)
        if parsed is None:
            return self._inner, path
        idx, rest = parsed
        if idx >= len(self._base_urls):
            raise RuntimeError(
                f"Manifest references base snapshot #{idx} but metadata "
                f"records only {len(self._base_urls)} base path(s) — "
                f"corrupt or truncated metadata"
            )
        plugin = self._base_plugins.get(idx)
        if plugin is None:
            plugin = url_to_storage_plugin(self._base_urls[idx])
            self._base_plugins[idx] = plugin
        return plugin, rest

    async def write(self, io_req: IOReq) -> None:
        if is_ref_location(io_req.path):
            raise RuntimeError(
                f"Refusing to write into a base snapshot: {io_req.path}"
            )
        await self._inner.write(io_req)

    async def read(self, io_req: IOReq) -> None:
        plugin, path = self._route(io_req.path)
        if plugin is self._inner:
            await plugin.read(io_req)
            return
        routed = IOReq(path=path, buf=io_req.buf, byte_range=io_req.byte_range)
        await plugin.read(routed)
        io_req.data = routed.data

    async def delete(self, path: str) -> None:
        if is_ref_location(path):
            raise RuntimeError(
                f"Refusing to delete an object owned by a base snapshot: "
                f"{path} (delete the base snapshot itself, after its "
                f"referencing snapshots are gone)"
            )
        await self._inner.delete(path)

    async def list_prefix(self, prefix: str):
        # Enumeration stays within the snapshot's OWN prefix: sweeps and
        # ref checks must never wander into a base root.
        return await self._inner.list_prefix(prefix)

    async def object_age_s(self, path: str) -> Optional[float]:
        plugin, p = self._route(path)
        return await plugin.object_age_s(p)

    async def object_size_bytes(self, path: str) -> Optional[int]:
        plugin, p = self._route(path)
        return await plugin.object_size_bytes(p)

    def close(self) -> None:
        for plugin in self._base_plugins.values():
            try:
                plugin.close()
            except Exception:  # pragma: no cover - best-effort teardown
                logger.warning("base plugin close failed", exc_info=True)
        self._base_plugins.clear()
        self._inner.close()
