"""Snapshot inspection CLI.

Usage::

    python -m torchsnapshot_tpu.inspect <snapshot-path> [--rank N] [--raw]

Prints the rank-local view of the manifest: one line per entry with type,
dtype/shape (arrays), chunk count (sharded arrays), byte size, and
location. ``--raw`` prints the full rank-prefixed global manifest instead.
"""

import argparse
import sys

from .manifest import (
    ArrayEntry,
    DictEntry,
    ListEntry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
    get_available_entries,
)
from .serialization import array_nbytes
from .snapshot import Snapshot


def _human(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _describe(path: str, entry) -> str:
    if isinstance(entry, ShardedArrayEntry):
        nbytes = array_nbytes(entry.dtype, entry.shape)
        return (
            f"{path:60s} ShardedArray {entry.dtype}{tuple(entry.shape)} "
            f"{_human(nbytes)} in {len(entry.shards)} chunks"
        )
    if isinstance(entry, ArrayEntry):
        nbytes = array_nbytes(entry.dtype, entry.shape)
        repl = " replicated" if entry.replicated else ""
        return (
            f"{path:60s} Array {entry.dtype}{tuple(entry.shape)} "
            f"{_human(nbytes)}{repl} @ {entry.location}"
        )
    if isinstance(entry, ObjectEntry):
        repl = " replicated" if entry.replicated else ""
        return f"{path:60s} object{repl} @ {entry.location}"
    if isinstance(entry, PrimitiveEntry):
        return f"{path:60s} {entry.ptype} = {entry.readable}"
    if isinstance(entry, (ListEntry, DictEntry)):
        return f"{path:60s} <{entry.type}>"
    return f"{path:60s} {entry.type}"


def _print_reports(path: str) -> int:
    """Render the snapshot's flight record(s): the committed take report
    plus any rank-local restore reports present."""
    import asyncio

    from .storage_plugin import url_to_storage_plugin
    from .telemetry import report as flight

    from .io_types import IOReq, is_not_found_error
    from .snapshot import SNAPSHOT_METADATA_FNAME

    storage = url_to_storage_plugin(path)
    try:
        # A typo'd path must read as "no snapshot here", not as "this
        # snapshot predates telemetry" — the two send an operator down
        # entirely different debugging paths.
        try:
            asyncio.run(storage.read(IOReq(path=SNAPSHOT_METADATA_FNAME)))
        except Exception as e:
            if is_not_found_error(e):
                print(f"no snapshot at {path}", file=sys.stderr)
                return 1
            raise
        take_report = asyncio.run(
            flight.aread_json(storage, flight.REPORT_FNAME)
        )
        restore_paths = sorted(
            p
            for p in (
                asyncio.run(storage.list_prefix(flight.REPORT_PREFIX)) or []
            )
            if p.startswith(".report.restore.")
        )
        printed = False
        if take_report is not None:
            print(flight.render_report(take_report))
            printed = True
        for rp in restore_paths:
            doc = asyncio.run(flight.aread_json(storage, rp))
            if doc is None:
                continue
            if printed:
                print()
            print(flight.render_report(doc))
            printed = True
        if not printed:
            print(
                f"no flight record at {path} (snapshot taken before "
                f"telemetry existed, or its report write failed)",
                file=sys.stderr,
            )
            return 1
        return 0
    finally:
        storage.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="torchsnapshot_tpu.inspect")
    parser.add_argument("path")
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--raw", action="store_true")
    parser.add_argument(
        "--delete",
        action="store_true",
        help="delete the snapshot (metadata first, then all payloads)",
    )
    parser.add_argument(
        "--sweep",
        action="store_true",
        help="with --delete: also enumerate the prefix and remove orphans "
        "from interrupted takes (works even without a metadata document)",
    )
    parser.add_argument(
        "--verify",
        action="store_true",
        help="scrub every payload against its manifest checksum/length; "
        "exit 1 if any object is bad",
    )
    parser.add_argument(
        "--convert-back",
        metavar="DEST",
        help="export this native snapshot to reference-torchsnapshot "
        "format at DEST (torch_save payloads + YAML metadata; sharded "
        "arrays assemble dense) — the reverse-migration path",
    )
    parser.add_argument(
        "--steps",
        action="store_true",
        help="treat PATH as a CheckpointManager base dir and list its "
        "committed steps",
    )
    parser.add_argument(
        "--reconcile",
        choices=["adopt", "sweep"],
        help="treat PATH as a CheckpointManager base dir and adopt "
        "(write the missing step marker) or sweep (age-guarded delete) "
        "async saves orphaned by a crash between commit and finalize",
    )
    parser.add_argument(
        "--copy-to",
        metavar="DEST",
        help="copy this snapshot to another storage backend (e.g. "
        "gs://bucket/path), verifying every payload checksum in "
        "transit; the destination commits metadata-last",
    )
    parser.add_argument(
        "--report",
        action="store_true",
        help="print the snapshot's embedded flight record (.report.json: "
        "per-rank phase timings, bytes, throughput, budget stall, "
        "retry/fault counts) plus any restore reports found; exit 1 "
        "when the snapshot has no report (taken before telemetry, or "
        "the report write failed)",
    )
    parser.add_argument(
        "--doctor",
        action="store_true",
        help="run the telemetry doctor over the snapshot's flight "
        "report(s): structured anomaly findings (consume-dominated "
        "restore, budget stall, retry storm, straggler rank, "
        "imbalanced stripe) with evidence and remediation hints; exit "
        "0 healthy, 1 findings, 2 no report to diagnose",
    )
    parser.add_argument(
        "--timeline",
        action="store_true",
        help="render the telemetry ledger's per-step trends (take "
        "seconds, GB/s, stall %%, retries, churn, goodput) for PATH "
        "(a CheckpointManager base or snapshot root) and run the "
        "regression sentinel; exit 0 healthy, 1 regression, 2 no "
        "ledger (see telemetry/timeline.py)",
    )
    parser.add_argument(
        "--diff",
        metavar="OLDER",
        help="content-diff PATH against the OLDER snapshot: which "
        "logical paths were added/removed/changed/unchanged (exact when "
        "both takes recorded fingerprints); metadata-only, no payload "
        "reads; exit 1 when anything changed, 3 when the comparison was "
        "inconclusive for some paths (unknown) with no definite change "
        "(2 is argparse's usage-error code)",
    )
    args = parser.parse_args(argv)

    exclusive = [
        bool(args.verify),
        bool(args.delete or args.sweep),
        bool(args.convert_back),
        bool(args.steps),
        bool(args.reconcile),
        bool(args.copy_to),
        bool(args.diff),
        bool(args.report),
        bool(args.doctor),
        bool(args.timeline),
    ]
    if sum(exclusive) > 1:
        parser.error(
            "--verify, --delete/--sweep, --convert-back, --steps, "
            "--reconcile, --copy-to, --diff, --report, --doctor, and "
            "--timeline are mutually exclusive; run them in separate "
            "invocations"
        )
    if args.timeline:
        from .telemetry import timeline as _timeline

        return _timeline.main([args.path])
    if args.report:
        return _print_reports(args.path)
    if args.doctor:
        from .telemetry import doctor as _doctor

        reports = _doctor._collect_snapshot_reports(args.path)
        if not reports:
            print(
                f"no flight report at {args.path} to diagnose",
                file=sys.stderr,
            )
            return 2
        findings = _doctor.diagnose(reports)
        print(_doctor.render_findings(findings))
        return 1 if findings else 0
    if args.diff:
        result = Snapshot(args.path).diff(args.diff, rank=args.rank)
        for kind in ("added", "removed", "changed", "unknown"):
            for p in result[kind]:
                print(f"{kind:>9}  {p}")
        print(
            f"{len(result['added'])} added, {len(result['removed'])} "
            f"removed, {len(result['changed'])} changed, "
            f"{len(result['unchanged'])} unchanged, "
            f"{len(result['unknown'])} unknown"
        )
        if result["added"] or result["removed"] or result["changed"]:
            return 1
        # Inconclusive is NOT "identical": a CI gate must be able to
        # tell "nothing changed" from "could not compare". 3, not 2 —
        # argparse exits 2 on usage errors, and a gate must also be
        # able to tell "inconclusive" from "bad invocation".
        return 3 if result["unknown"] else 0
    if args.copy_to:
        Snapshot(args.path).copy_to(args.copy_to)
        print(f"copied {args.path} -> {args.copy_to} (verified in transit)")
        return 0
    if args.reconcile:
        from .manager import CheckpointManager

        handled = CheckpointManager(args.path).reconcile(
            adopt=(args.reconcile == "adopt")
        )
        verb = "adopted" if args.reconcile == "adopt" else "swept"
        if not handled:
            print("no orphaned steps", file=sys.stderr)
            return 0
        for step in handled:
            print(step)
        print(f"{verb} {len(handled)} orphaned step(s)", file=sys.stderr)
        return 0
    if args.steps:
        from .manager import CheckpointManager

        steps = CheckpointManager(args.path).all_steps()
        if not steps:
            # stderr: stdout is the machine-readable step list here.
            print("no committed steps", file=sys.stderr)
            return 1
        for step in steps:
            print(step)
        return 0
    if args.convert_back:
        from .interop.reference_writer import convert_back

        convert_back(args.path, args.convert_back)
        print(f"exported {args.path} -> {args.convert_back} (reference format)")
        return 0
    if args.verify:
        problems = Snapshot(args.path).verify()
        if not problems:
            print("OK: all payloads match their manifest checksums")
            return 0
        for location, problem in sorted(problems.items()):
            print(f"BAD {location}: {problem}")
        return 1

    if args.delete:
        Snapshot(args.path).delete(sweep=args.sweep)
        print(f"deleted {args.path}" + (" (swept)" if args.sweep else ""))
        return 0
    if args.sweep:
        parser.error("--sweep requires --delete")

    manifest = Snapshot(args.path).get_manifest()
    view = manifest if args.raw else get_available_entries(manifest, args.rank)
    total = 0
    counted = set()
    for path in sorted(view):
        entry = view[path]
        print(_describe(path, entry))
        if isinstance(entry, (ArrayEntry, ShardedArrayEntry)):
            # In --raw mode sharded/replicated entries appear once per
            # rank; count each logical value once.
            logical = path.split("/", 1)[1] if args.raw and "/" in path else path
            if logical not in counted:
                counted.add(logical)
                total += array_nbytes(entry.dtype, entry.shape)
    print(f"\n{len(view)} entries, {_human(total)} of array data")
    return 0


if __name__ == "__main__":
    sys.exit(main())
