"""Incremental (deduplicated) snapshot takes.

Beyond reference parity: torchsnapshot rewrites every tensor on every
``Snapshot.take``. Here, ``Snapshot.take(path, app_state, base=prev)``
fingerprints each array **on device** (fingerprint.py) and, when a
leaf's content matches what ``prev`` recorded, skips BOTH the
device→host transfer and the storage write — the manifest entry instead
references the base snapshot's stored object (``@base<N>/…`` routing,
storage_plugin.RefRouterPlugin). Take cost becomes proportional to
*changed* bytes: checkpointing a LoRA fine-tune whose backbone is
frozen, or an embedding model where only touched rows train, stops
paying for the frozen majority.

Safety model:

- A fingerprint MISS (absent, algorithm drift, host↔device migration,
  shape/dtype change) always degrades to a full write — never corrupt,
  only less deduplication.
- A dedup hit requires the base entry to carry BOTH a fingerprint and a
  checksum, equal dtype/shape/prng_impl, and (for shards/chunks) equal
  region coordinates.
- Chains flatten: if the base entry itself references an older
  snapshot, the new entry points directly at that original object, so
  reference chains never deepen and every reference names the snapshot
  that physically wrote the bytes.
- Back-link markers (``refs/inc_<uuid>`` objects written into the base
  root before this take commits) let ``Snapshot.delete`` on the base
  discover referencing snapshots and refuse — see snapshot.py.
"""

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .io_types import IOReq, StoragePlugin, WriteReq, io_payload
from .manifest import (
    ArrayEntry,
    Entry,
    Manifest,
    ShardedArrayEntry,
    SnapshotMetadata,
    get_available_entries,
)
from .storage_plugin import (
    encode_base_ref,
    parse_ref_location,
    resolve_base_ref,
    url_to_storage_plugin,
)

logger = logging.getLogger(__name__)

REFS_PREFIX = "refs/"


@dataclass
class IncrementalStats:
    fingerprinted: int = 0
    dedup_hits: int = 0
    dedup_bytes: int = 0
    written: int = 0
    # Manifest-churn accounting (telemetry/ledger.py): bytes of array
    # leaves THIS RANK owned in the base manifest whose logical paths do
    # not exist in the new take — state that was dropped between
    # consecutive takes. 0 without a base.
    removed_bytes: int = 0

    def churn_note(self, has_base: bool) -> dict:
        """The per-rank churn block the flight recorder attaches to its
        summary; the ledger sums these across ranks at commit."""
        return {
            "unchanged_bytes": self.dedup_bytes,
            "removed_bytes": self.removed_bytes,
            "dedup_hits": self.dedup_hits,
            "fingerprinted": self.fingerprinted,
            "basis": "incremental" if has_base else "full",
        }


@dataclass
class _BaseContext:
    base_path: str
    metadata: SnapshotMetadata
    available: Manifest
    # Encoded refs for OUR metadata: [0] is the base itself, the rest are
    # the base's own (transitive) bases re-encoded relative to us.
    base_paths: List[str] = field(default_factory=list)
    # base's base index -> our base_paths index (chain flattening).
    idx_map: Dict[int, int] = field(default_factory=dict)


def _read_metadata(base_path: str) -> SnapshotMetadata:
    from .snapshot import _aread_metadata_at

    return asyncio.run(_aread_metadata_at(base_path))


def load_base_context(
    base_path: str,
    own_path: str,
    rank: int,
    metadata: Optional[SnapshotMetadata] = None,
) -> _BaseContext:
    """Read the base snapshot's metadata (or reuse a handle's cached
    copy) and precompute the reference namespace for the new take.
    Raises if the base is not a committed snapshot — an explicit
    ``base=`` argument that cannot be honored is a configuration error,
    not a soft miss."""
    if metadata is None:
        try:
            metadata = _read_metadata(base_path)
        except Exception as e:
            raise ValueError(
                f"base snapshot at {base_path!r} is unreadable ({e!r}); "
                f"pass a committed snapshot (or None for a full take)"
            ) from e
    ctx = _BaseContext(
        base_path=base_path,
        metadata=metadata,
        available=get_available_entries(metadata.manifest, rank),
        base_paths=[encode_base_ref(base_path, own_path)],
    )
    # Flatten the base's own reference roots into our namespace. The
    # list is a pure function of (base metadata, the two paths), so
    # every rank derives the identical namespace with no collective.
    for k, ref in enumerate(metadata.base_paths):
        resolved = resolve_base_ref(ref, base_path)
        ours = encode_base_ref(resolved, own_path)
        if ours in ctx.base_paths:
            ctx.idx_map[k] = ctx.base_paths.index(ours)
        else:
            ctx.idx_map[k] = len(ctx.base_paths)
            ctx.base_paths.append(ours)
    return ctx


def _is_jax_array(obj: Any) -> bool:
    import jax

    return isinstance(obj, jax.Array)


def _compute_fingerprints(
    write_reqs: List[WriteReq], stats: IncrementalStats
) -> Dict[int, str]:
    """Fingerprint every array write request's payload, device-side for
    device-resident data. Returns {id(entry): fingerprint}.

    Device computations are dispatched for ALL leaves first (jax's async
    dispatch pipelines them on device), then resolved — the blocking
    per-leaf cost is one 16-byte device→host fetch, not a serialized
    compute+fetch per leaf.
    """
    from .fingerprint import (
        fingerprint_device_async,
        fingerprint_host,
        resolve_fingerprints,
    )
    from .io_preparer import ArrayBufferStager

    pending: List[Tuple[ArrayEntry, Any]] = []
    fingerprints: Dict[int, str] = {}
    failed_dtypes: set = set()

    def _note_failure(dtype: Any, e: Exception) -> None:
        # Fingerprint failures DEGRADE (full write, no dedup) — the
        # safety model forbids them from aborting a checkpoint take.
        key = str(dtype)
        if key not in failed_dtypes:
            failed_dtypes.add(key)
            logger.warning(
                f"content fingerprint unavailable for dtype {key} "
                f"({e!r}); affected leaves are written in full"
            )

    for wr in write_reqs:
        stager = wr.buffer_stager
        if not isinstance(stager, ArrayBufferStager):
            continue
        entry = stager._entry
        data = stager._data
        if entry is None or data is None or not isinstance(entry, ArrayEntry):
            continue
        if _is_jax_array(data):
            try:
                pending.append(
                    (
                        entry,
                        fingerprint_device_async(data, stager._chunk_slices),
                    )
                )
            except Exception as e:
                _note_failure(data.dtype, e)
        else:
            try:
                host = np.asarray(data)
                if stager._chunk_slices is not None:
                    host = host[stager._chunk_slices]
                fingerprints[id(entry)] = fingerprint_host(
                    np.ascontiguousarray(host)
                )
                stats.fingerprinted += 1
            except Exception as e:
                _note_failure(getattr(data, "dtype", type(data)), e)
    resolved = resolve_fingerprints([r for _, r in pending])
    for (entry, _), res in zip(pending, resolved):
        if isinstance(res, str):
            fingerprints[id(entry)] = res
            stats.fingerprinted += 1
        else:
            _note_failure(entry.dtype, res)
    return fingerprints


def _entry_nbytes(entry: ArrayEntry) -> int:
    from .serialization import array_nbytes

    try:
        return array_nbytes(entry.dtype, entry.shape)
    # Size ESTIMATE for retention accounting; an exotic dtype degrades
    # to 0 (counted as "cheap to keep"), never blocks a snapshot.
    except Exception:  # snapcheck: disable=swallowed-exception -- size estimate
        return 0


def _region_nbytes(dtype: str, sizes: Any) -> int:
    from .serialization import array_nbytes

    try:
        return array_nbytes(dtype, list(sizes))
    except Exception:  # snapcheck: disable=swallowed-exception -- size estimate
        return 0


def _rewrite_to_ref(
    entry: ArrayEntry,
    base_entry: ArrayEntry,
    ctx: _BaseContext,
    fingerprint: Optional[str],
    used_idxs: set,
) -> None:
    """Point ``entry`` at the base snapshot's stored object."""
    if base_entry.base is not None:
        # The base itself borrowed this object from an older snapshot:
        # reference the ORIGINAL directly (chains never deepen).
        our_idx = ctx.idx_map[base_entry.base]
    else:
        our_idx = 0
    # The base metadata may come from a handle whose cache was DECORATED
    # for restore ("@base<k>/<loc>"); the bare location is canonical.
    location = base_entry.location
    parsed = parse_ref_location(location)
    if parsed is not None:
        location = parsed[1]
    entry.location = location
    entry.base = our_idx
    entry.serializer = base_entry.serializer
    entry.checksum = base_entry.checksum
    entry.compression = base_entry.compression
    entry.fingerprint = fingerprint
    used_idxs.add(our_idx)


def _dense_match(
    entry: ArrayEntry, base_entry: Entry, fp: Optional[str]
) -> bool:
    return (
        fp is not None
        and isinstance(base_entry, ArrayEntry)
        and base_entry.fingerprint == fp
        and base_entry.checksum is not None
        # Chunk-stored base entries (chunkstore.py) have no single
        # borrowable object — the chunk pass dedups them per chunk
        # against the shared store instead.
        and not base_entry.chunks
        and base_entry.dtype == entry.dtype
        and list(base_entry.shape) == list(entry.shape)
        and base_entry.prng_impl == entry.prng_impl
    )


def apply_incremental(
    manifest: Manifest,
    write_reqs: List[WriteReq],
    *,
    rank: int,
    own_path: str,
    base_path: Optional[str],
    record_fingerprints: bool,
    base_metadata: Optional[SnapshotMetadata] = None,
    coordinator: Optional[Any] = None,
) -> Tuple[List[str], IncrementalStats]:
    """Fingerprint array payloads and (when ``base_path`` is given)
    dedup unchanged ones against the base snapshot.

    Mutates ``manifest`` entries in place (entries are shared with the
    stagers' back-patch references) and drops deduplicated requests from
    ``write_reqs``. Returns the ``base_paths`` list for this take's
    metadata (empty when no base) and the dedup stats. Runs BEFORE
    staging/cloning, so a dedup hit skips the device→host transfer, the
    storage write, and (async takes) the device clone. Per-rank
    divergence in hit counts is fine; the reference namespace itself is
    rank-deterministic. With a base, ONE collective runs (a kilobyte
    gather of used base indices) so rank 0 alone writes the union's
    back-link markers — N ranks PUTting the same idempotent object
    concurrently would trip same-object rate limits on cloud backends.
    """
    stats = IncrementalStats()
    if base_path is None and not record_fingerprints:
        return [], stats

    fingerprints = _compute_fingerprints(write_reqs, stats)
    if record_fingerprints:
        # Record fingerprints on the entries (the manifest aliases
        # them). With fingerprint=False + base, they are computed only
        # to COMPARE — the user opted out of growing the manifest /
        # making this snapshot a future base.
        for wr in write_reqs:
            entry = getattr(wr.buffer_stager, "_entry", None)
            if isinstance(entry, ArrayEntry) and id(entry) in fingerprints:
                entry.fingerprint = fingerprints[id(entry)]

    if base_path is None:
        stats.written = len(write_reqs)
        return [], stats

    ctx = load_base_context(
        base_path, own_path, rank, metadata=base_metadata
    )
    dropped: set = set()
    used_idxs: set = set()

    for logical_path, entry in manifest.items():
        base_entry = ctx.available.get(logical_path)
        if base_entry is None:
            continue
        if isinstance(entry, ArrayEntry):
            fp = fingerprints.get(id(entry))
            if id(entry) in dropped or not _dense_match(entry, base_entry, fp):
                continue
            _rewrite_to_ref(
                entry,
                base_entry,
                ctx,
                fp if record_fingerprints else None,
                used_idxs,
            )
            dropped.add(id(entry))
            stats.dedup_hits += 1
            stats.dedup_bytes += _entry_nbytes(entry)
        elif isinstance(entry, ShardedArrayEntry) and isinstance(
            base_entry, ShardedArrayEntry
        ):
            if (
                entry.dtype != base_entry.dtype
                or list(entry.shape) != list(base_entry.shape)
                or entry.prng_impl != base_entry.prng_impl
            ):
                continue
            by_region = {
                (tuple(s.offsets), tuple(s.sizes)): s.array
                for s in base_entry.shards
            }
            for shard in entry.shards:
                chunk = shard.array
                fp = fingerprints.get(id(chunk))
                if fp is None or id(chunk) in dropped:
                    continue
                candidate = by_region.get(
                    (tuple(shard.offsets), tuple(shard.sizes))
                )
                if (
                    candidate is None
                    or candidate.fingerprint != fp
                    or candidate.checksum is None
                    or candidate.chunks
                    or candidate.dtype != chunk.dtype
                ):
                    continue
                _rewrite_to_ref(
                    chunk,
                    candidate,
                    ctx,
                    fp if record_fingerprints else None,
                    used_idxs,
                )
                dropped.add(id(chunk))
                stats.dedup_hits += 1
                stats.dedup_bytes += _entry_nbytes(chunk)

    # Churn: array state this rank owned in the base but dropped from
    # the new take (a deleted optimizer slot, a removed parameter).
    # Ownership diff only ("<rank>/<logical>" keys), so per-rank values
    # count exactly once across ranks. Replicated leaves are mirrored
    # under EVERY rank's prefix in the merged base manifest, so a
    # removed one would be counted world_size times when the ledger
    # sums the per-rank notes — rank 0 counts those alone.
    own_prefix = f"{rank}/"
    for full_path, base_entry in ctx.metadata.manifest.items():
        if not full_path.startswith(own_prefix):
            continue
        logical = full_path[len(own_prefix):]
        if logical in manifest:
            continue
        if getattr(base_entry, "replicated", False) and rank != 0:
            continue
        if isinstance(base_entry, ShardedArrayEntry):
            # This rank's shards only; the full logical shape repeats
            # under every owning rank's prefix and must not multiply.
            for shard in base_entry.shards:
                stats.removed_bytes += _region_nbytes(
                    shard.array.dtype, shard.sizes
                )
        elif isinstance(base_entry, ArrayEntry):
            stats.removed_bytes += _entry_nbytes(base_entry)

    if dropped:
        write_reqs[:] = [
            wr
            for wr in write_reqs
            if id(getattr(wr.buffer_stager, "_entry", None)) not in dropped
        ]
    # The marker gather is UNCONDITIONAL under a base (hit counts may
    # diverge across ranks, collective participation must not).
    if coordinator is not None and coordinator.get_world_size() > 1:
        gathered = coordinator.all_gather_object(sorted(used_idxs))
        union = set()
        for idxs in gathered:
            union.update(idxs)
        if rank == 0 and union:
            _write_back_link(ctx, own_path, rank, union)
    elif used_idxs:
        _write_back_link(ctx, own_path, rank, used_idxs)
    stats.written = len(write_reqs)
    if stats.dedup_hits:
        logger.info(
            f"incremental take: rank {rank} deduplicated {stats.dedup_hits} "
            f"object(s) (~{stats.dedup_bytes / (1 << 20):.1f} MiB) against "
            f"{base_path}"
        )
    return ctx.base_paths, stats


def _write_back_link(
    ctx: _BaseContext, own_path: str, rank: int, used_idxs: set
) -> None:
    """Durably mark each referenced base snapshot BEFORE this take can
    commit. The marker records the referencing snapshot (relative when a
    sibling, mirroring metadata base_paths), so ``delete`` on the base
    can discover live referencers; a marker whose referencing snapshot
    never committed (crashed take) is stale and swept by delete.

    The marker name is a DETERMINISTIC function of the referencing
    snapshot, so the write is idempotent: N ranks over M takes leave one
    marker per (base, referencing snapshot) pair — concurrent PUTs carry
    identical bytes — instead of N×M accumulating objects that every
    future ``delete`` on a long-lived base would have to read."""
    import hashlib

    for idx in sorted(used_idxs):
        root = resolve_base_ref(ctx.base_paths[idx], own_path)
        storage = url_to_storage_plugin(root)
        try:
            child_ref = encode_base_ref(own_path, root)
            name = hashlib.sha1(child_ref.encode()).hexdigest()[:16]
            marker = IOReq(path=f"{REFS_PREFIX}inc_{name}")
            marker.buf.write(json.dumps({"path": child_ref}).encode())
            asyncio.run(storage.write(marker))
        finally:
            storage.close()


async def referencing_snapshots(
    storage: StoragePlugin, own_path: str
) -> List[Tuple[str, str]]:
    """Back-link markers in THIS snapshot's prefix: [(marker_path,
    resolved_referencing_snapshot_url)]. Malformed markers resolve to
    an empty URL (caller treats as stale)."""
    paths = await storage.list_prefix(REFS_PREFIX)
    if paths is None:
        return []
    out: List[Tuple[str, str]] = []
    for p in paths:
        try:
            io_req = IOReq(path=p)
            await storage.read(io_req)
            doc = json.loads(bytes(io_payload(io_req)).decode())
            out.append((p, resolve_base_ref(doc["path"], own_path)))
        except Exception as e:
            logger.warning(f"unreadable back-link marker {p}: {e!r}")
            out.append((p, ""))
    return out
