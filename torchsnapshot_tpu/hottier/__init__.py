"""snaptier: preemption-tolerant hot checkpoint tier.

``async_take`` acknowledges once each rank's objects are k-replicated
in peer hosts' RAM; a background drain tiers them down to the durable
plugin and records a ``.tierdown`` watermark beside the manifest;
``restore`` prefers the (fingerprint-verified) hot tier and falls back
per-object to the durable tier when peers are dead, stale, or corrupt —
so a preempted job restores at RAM speed instead of storage speed, and
any k-1 simultaneous host losses still restore bit-exact.

Quickstart::

    from torchsnapshot_tpu import hottier

    hottier.enable_hot_tier()          # k from TPUSNAPSHOT_HOT_TIER_K,
                                       # per-host RAM cap from
                                       # TPUSNAPSHOT_HOT_TIER_BYTES
    pending = Snapshot.async_take(path, app_state)   # acks at RAM speed
    ...
    snapshot.restore(app_state)        # served from peer RAM when hot

Layering and the failure model are documented in runtime.py/tier.py;
docs/FAULTS.md covers the host-loss schedules and the tier-down crash
matrix, docs/OBSERVABILITY.md the tier metrics, the flight report's
``tier`` block, the ledger field, and the ``hot-tier-degraded`` doctor
rule.
"""

from .plugin import TieredPlugin
from .runtime import (
    BYTES_ENV_VAR,
    K_ENV_VAR,
    TIERDOWN_FNAME,
    HotTierRuntime,
    disable_hot_tier,
    drain_now,
    durability_lag_s,
    enable_hot_tier,
    forget_root,
    hot_tier,
    introspect,
    is_enabled,
    is_payload_path,
    reconcile_hot_tier,
    repair_plane,
    repair_tick,
    replication_stats_begin,
    replication_stats_collect,
    reset_pending,
    restore_stats_begin,
    restore_stats_collect,
    runtime,
    wait_drained,
)
from .tier import (
    HostLostError,
    buffered_roots,
    condemn_host,
    host_generation,
    kill_host,
    live_hosts,
    live_replicas,
    register_remote_host,
    remote_host,
    remote_hosts,
    reset_hot_tier,
    revive_host,
    total_buffered_bytes,
    unregister_remote_host,
)
from . import peer, repair, transport  # noqa: F401  (snapwire/snapmend)

__all__ = [
    "BYTES_ENV_VAR",
    "HostLostError",
    "HotTierRuntime",
    "K_ENV_VAR",
    "TIERDOWN_FNAME",
    "TieredPlugin",
    "buffered_roots",
    "condemn_host",
    "disable_hot_tier",
    "drain_now",
    "durability_lag_s",
    "enable_hot_tier",
    "forget_root",
    "host_generation",
    "hot_tier",
    "introspect",
    "is_enabled",
    "is_payload_path",
    "kill_host",
    "live_hosts",
    "live_replicas",
    "peer",
    "reconcile_hot_tier",
    "register_remote_host",
    "remote_host",
    "repair",
    "repair_plane",
    "repair_tick",
    "replication_stats_begin",
    "replication_stats_collect",
    "remote_hosts",
    "reset_hot_tier",
    "reset_pending",
    "restore_stats_begin",
    "restore_stats_collect",
    "revive_host",
    "runtime",
    "total_buffered_bytes",
    "transport",
    "unregister_remote_host",
    "wait_drained",
]
