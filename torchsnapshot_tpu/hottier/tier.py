"""Peer-host RAM stores: the storage substrate of the hot tier.

A *host* here is a failure domain that can be preempted as a unit — in
production one TPU worker host, in tests a virtual host id. Each host
exposes one :class:`HostRamStore`: a byte-capped in-RAM object store
holding hot replicas of recently taken snapshot objects. The rendezvous
index (``key → replica hosts``) records where each object's k replicas
landed so a reader probes exactly the hosts that hold it.

This module is deliberately transport-agnostic: in-process, the
"stores" are plain dicts (each virtual host a separate failure domain
the tests can kill independently); on a multi-host pod the same
interface is what a coord-layer (DCN KV / RDMA) transport implements —
the runtime only ever speaks ``put/get/drop`` plus the index. The
failure model the harness exercises — :func:`kill_host` drops a host's
RAM wholesale, exactly what preemption does — is identical either way.

Integrity: every object carries an xs128 content fingerprint
(fingerprint.py — the same algorithm that gates incremental dedup)
computed at put time over the exact payload bytes; ``get`` recomputes
and compares, so a corrupt replica is detected at the tier boundary and
the reader falls over to the next replica (or the durable tier) instead
of handing garbage to the consume path.

Eviction: only *drained* objects (already persisted to the durable
tier) are evictable, LRU per host. An undrained object is the only copy
of committed bytes outside its k-replica set — evicting it could leave
a manifest referencing bytes that exist in no tier, the exact invariant
the crash matrix proves we never violate. A put that cannot fit even
after evicting drained objects is *refused*; the caller degrades to a
synchronous durable write-through.

One module-wide lock guards hosts + objects + index: the structures are
tiny (metadata, not payload copies beyond the stored bytes) and a
single lock makes the cross-structure invariants (index entries always
name live replicas) trivially atomic.
"""

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import telemetry
from ..fingerprint import fingerprint_host
from ..telemetry import memwatch
from ..telemetry import metrics as _metric_names

import logging

logger = logging.getLogger(__name__)


class HostLostError(RuntimeError):
    """The addressed peer host is dead (preempted / unreachable)."""


def payload_tag(data) -> str:
    """Content fingerprint of raw payload bytes (xs128, fingerprint.py)."""
    return fingerprint_host(bytes(data))


@dataclass
class HotObject:
    data: bytes
    tag: str  # xs128 fingerprint of ``data`` at put time
    root: str  # snapshot root this object belongs to (reconcile grouping)
    put_t: float  # epoch seconds
    drained: bool = False  # persisted to the durable tier


class HostRamStore:
    """One host's RAM store. All mutation happens under ``_TIER_LOCK``
    (module-wide); the class only encapsulates per-host state."""

    def __init__(self, host_id: int, capacity_bytes: int) -> None:
        self.host_id = host_id
        self.capacity_bytes = capacity_bytes
        self.alive = True
        self.objects: "OrderedDict[str, HotObject]" = OrderedDict()
        self.used_bytes = 0


_TIER_LOCK = threading.RLock()
_HOSTS: Dict[int, HostRamStore] = {}
# Rendezvous index: key -> hosts holding a replica (in placement order).
# Remote placements are indexed here too, so every key lookup answers
# from one structure regardless of where the replica physically lives.
_KEY_HOSTS: Dict[str, List[int]] = {}

# ----------------------------------------------------------- remote hosts
#
# snapwire (transport.py / peer.py): a host id registered here is backed
# by a REAL peer process over TCP instead of an in-process dict. The
# registry lives in this module so every tier function can route without
# an import cycle; the registered object is duck-typed (RemotePeer).
# _REMOTE_SHADOW is the client-side ledger of what we placed on each
# remote host — (host_id, key) -> {root, nbytes, tag (the logical
# content tag hot_put computed), stored_tag (what the peer actually
# holds; differs only for lossy int8 pushes), put_t, drained} — feeding
# the same accounting (buffered_roots / occupancy / ages / key_tag) the
# local stores answer from their own dicts, without a per-query RPC.

_REMOTE: Dict[int, object] = {}
_REMOTE_SHADOW: Dict[tuple, Dict[str, object]] = {}

# Generation-stamped membership (snapmend, repair.py): each host id
# carries a monotonically increasing generation, bumped every time a
# NEW peer process takes the id over (register after a loss/respawn).
# A respawned peer starts with an empty store — trusting it to hold its
# predecessor's replicas would turn one SIGKILL into silent
# under-replication — so the client-side shadow for the host is
# invalidated at every generation change and every view answers only
# from entries of the CURRENT generation's peer.
_HOST_GEN: Dict[int, int] = {}

# Peer-SERVER scope (peer.py): when an in-process peer serves a host id
# this same process also has registered as remote, the server half must
# address the LOCAL store — otherwise its tier calls would route back
# through the RemotePeer into itself. Thread-local because the server
# handles requests on its own event-loop thread.
_LOCAL_ONLY = threading.local()


class serve_local:
    """``with tier.serve_local():`` — tier calls on this thread address
    local stores even for remotely-registered host ids (the peer-server
    side of an in-process wire)."""

    def __enter__(self) -> None:
        _LOCAL_ONLY.active = True

    def __exit__(self, *exc) -> None:
        _LOCAL_ONLY.active = False


def _route_peer(host_id: int):
    """The remote peer to route ``host_id`` through, or None for the
    local store (unregistered host, or inside a :class:`serve_local`
    scope)."""
    if getattr(_LOCAL_ONLY, "active", False):
        return None
    return remote_host(host_id)


def register_remote_host(host_id: int, peer) -> None:
    """Back virtual host ``host_id`` with a remote peer client
    (transport.RemotePeer): every tier operation addressing it crosses
    the wire from here on. Re-registering an id whose previous peer is
    gone (condemned/killed/closed) is a GENERATION CHANGE: the shadow
    entries of the predecessor are invalidated — the new process holds
    none of its replicas and must never be credited with them."""
    with _TIER_LOCK:
        if host_id in _HOSTS and _HOSTS[host_id].objects:
            raise RuntimeError(
                f"host {host_id} already holds in-process replicas; "
                f"cannot re-register it as remote"
            )
        _HOSTS.pop(host_id, None)
        prev = _REMOTE.get(host_id)
        if prev is not None and prev is not peer:
            for hk in [k for k in _REMOTE_SHADOW if k[0] == host_id]:
                del _REMOTE_SHADOW[hk]
        _REMOTE[host_id] = peer
        gen = getattr(peer, "generation", None)
        if gen is None:
            gen = _HOST_GEN.get(host_id, 0) + (0 if prev is None else 1)
        _HOST_GEN[host_id] = max(int(gen), _HOST_GEN.get(host_id, 0))
        _update_buffered_gauge()


def host_generation(host_id: int) -> int:
    """The membership generation of ``host_id``'s current peer (0 for a
    host never lost/re-registered)."""
    with _TIER_LOCK:
        return _HOST_GEN.get(host_id, 0)


def note_host_generation(host_id: int, generation: int) -> None:
    """Raise the membership view of ``host_id`` to ``generation``
    (monotonic; lower observations are ignored). Called by a transport
    probe that learned the server's true generation — a client rebuilt
    from the generation-less address book starts at 0 and adopts the
    respawned server's generation on first contact."""
    with _TIER_LOCK:
        _HOST_GEN[host_id] = max(int(generation), _HOST_GEN.get(host_id, 0))


def unregister_remote_host(host_id: int, kill_spawned: bool = True) -> None:
    with _TIER_LOCK:
        peer = _REMOTE.pop(host_id, None)
        for hk in [k for k in _REMOTE_SHADOW if k[0] == host_id]:
            del _REMOTE_SHADOW[hk]
    if peer is not None:
        try:
            peer.close(kill_spawned=kill_spawned)
        except Exception as e:
            logger.warning(f"remote peer close failed: {e!r}")


def remote_host(host_id: int):
    """The registered remote peer for ``host_id`` (None = in-process)."""
    with _TIER_LOCK:
        return _REMOTE.get(host_id)


def remote_hosts() -> Dict[int, object]:
    with _TIER_LOCK:
        return dict(_REMOTE)


def host_store(host_id: int, capacity_bytes: Optional[int] = None) -> HostRamStore:
    with _TIER_LOCK:
        store = _HOSTS.get(host_id)
        if store is None:
            store = HostRamStore(
                host_id,
                capacity_bytes if capacity_bytes is not None else (1 << 30),
            )
            _HOSTS[host_id] = store
        elif capacity_bytes is not None:
            store.capacity_bytes = capacity_bytes
        return store


def kill_host(host_id: int) -> None:
    """Simulate preemption: the host's RAM is gone and the host is dead.

    Index entries are NOT cleaned — a reader discovers the death on
    access (the ``dead`` fallback reason), exactly like a real
    unreachable peer.

    For a host backed by a REAL remote peer (snapwire), this is real:
    a spawned peer subprocess is SIGKILLed, and the host's in-flight
    transport connections are aborted so a blocked socket read observes
    the loss within the RPC deadline instead of hanging until timeout
    (the ``lose_host`` contract)."""
    peer = remote_host(host_id)
    if peer is not None:
        peer.kill()
        with _TIER_LOCK:
            # The dead process's RAM is gone: clear the client-side
            # shadow so buffered_roots/occupancy stop counting vanished
            # replicas (the local branch's objects.clear() analog).
            # Index entries stay, exactly like the local branch —
            # readers discover the death on access.
            for hk in [k for k in _REMOTE_SHADOW if k[0] == host_id]:
                del _REMOTE_SHADOW[hk]
            _update_buffered_gauge()
        return
    with _TIER_LOCK:
        store = host_store(host_id)
        store.alive = False
        store.objects.clear()
        store.used_bytes = 0
        _update_buffered_gauge()


def condemn_host(host_id: int, only_if: Optional[object] = None) -> None:
    """Classify a wire-backed host LOST without signalling its process
    (snapmend: a hung-not-dead peer — SIGSTOP, network partition —
    cannot be killed from here, but must stop being trusted). The
    RemotePeer is latched dead and its connections aborted, so every
    later op raises :class:`HostLostError`; the client-side shadow is
    cleared so occupancy/replica counting stops crediting the lost
    process. The peer stays REGISTERED (routing to a condemned host
    must fail loudly, never silently fall back to a fresh in-process
    store) until a replacement generation registers over it. For an
    in-process host this is exactly :func:`kill_host`.

    ``only_if`` pins the verdict to the peer OBJECT the caller judged:
    when a replacement has been registered over the id since (a
    respawn, an external supervisor's re-registration), the call is a
    no-op — a healthy fresh peer must never be condemned on a stale
    view of its predecessor."""
    with _TIER_LOCK:
        peer = _REMOTE.get(host_id)
        if (
            peer is not None
            and only_if is not None
            and peer is not only_if
        ):
            return
    if peer is None:
        if only_if is not None:
            return  # the judged remote peer is no longer registered
        kill_host(host_id)
        return
    condemn = getattr(peer, "condemn", None)
    if condemn is not None:
        condemn()
    else:  # duck-typed peer without the latch: a kill is the best we have
        peer.kill()
    with _TIER_LOCK:
        if _REMOTE.get(host_id) is not peer:
            # A replacement registered over the id while the judged
            # peer was being condemned outside the lock. Its
            # registration already invalidated the predecessor's
            # shadow, so every entry present now belongs to the
            # REPLACEMENT (it may already hold fresh replicas) and
            # must survive.
            return
        for hk in [k for k in _REMOTE_SHADOW if k[0] == host_id]:
            del _REMOTE_SHADOW[hk]
        _update_buffered_gauge()


def live_replicas(key: str, tag: Optional[str] = None) -> List[int]:
    """Hosts whose CURRENT store verifiably holds a replica of ``key``
    (with ``tag``, only replicas of exactly those bytes) — the repair
    plane's replica count. Unlike :func:`replica_hosts_for` (the
    rendezvous CLAIM, deliberately left stale so readers discover death
    on access), this answers from live state only: an in-process host
    must be alive and hold the object; a remote host must have a
    current-generation shadow entry (condemned/killed hosts had theirs
    invalidated)."""
    with _TIER_LOCK:
        out: List[int] = []
        for h in _KEY_HOSTS.get(key, []):
            if h in _REMOTE:
                peer = _REMOTE[h]
                if not getattr(peer, "alive", False):
                    continue
                shadow = _REMOTE_SHADOW.get((h, key))
                if shadow is not None and (
                    tag is None or shadow["tag"] == tag
                ):
                    out.append(h)
                continue
            store = _HOSTS.get(h)
            if store is None or not store.alive:
                continue
            obj = store.objects.get(key)
            if obj is not None and (tag is None or obj.tag == tag):
                out.append(h)
        return out


def replica_is_drained(key: str, host_id: int) -> Optional[bool]:
    """The drained flag of ``key``'s replica on ``host_id`` (None when
    no live replica there) — repaired replicas inherit it."""
    with _TIER_LOCK:
        shadow = _REMOTE_SHADOW.get((host_id, key))
        if shadow is not None:
            return bool(shadow["drained"])
        store = _HOSTS.get(host_id)
        obj = store.objects.get(key) if store is not None else None
        return None if obj is None else bool(obj.drained)


def revive_host(host_id: int) -> None:
    """Bring a host back (empty — preemption lost its RAM). Remote
    peers do not revive: a preempted host comes back as a NEW process
    (spawn + register again)."""
    if remote_host(host_id) is not None:
        logger.warning(
            f"revive_host({host_id}): remote peers do not revive; spawn "
            f"and register a new peer process instead"
        )
        return
    with _TIER_LOCK:
        host_store(host_id).alive = True


def live_hosts() -> List[int]:
    with _TIER_LOCK:
        hosts = {h for h, s in _HOSTS.items() if s.alive}
        hosts.update(h for h, p in _REMOTE.items() if p.alive)
        return sorted(hosts)


def reset_hot_tier() -> None:
    """Drop every host, object, index entry, and remote peer
    registration (tests). Spawned peer subprocesses are killed so no
    test leaks a process."""
    with _TIER_LOCK:
        peers = list(_REMOTE.values())
        _REMOTE.clear()
        _REMOTE_SHADOW.clear()
        _HOST_GEN.clear()
        _HOSTS.clear()
        _KEY_HOSTS.clear()
        _update_buffered_gauge()
    for peer in peers:
        try:
            peer.close(kill_spawned=True)
        except Exception as e:
            logger.warning(f"remote peer close failed: {e!r}")


def _update_buffered_gauge() -> None:
    # Lock held by caller. The client view: local stores of in-process
    # hosts plus the shadow of remote placements (a remote host's local
    # store — the in-process peer-server half — would double-count).
    telemetry.gauge(_metric_names.HOT_TIER_BUFFERED_BYTES).set(
        float(
            sum(
                s.used_bytes
                for h, s in _HOSTS.items()
                if h not in _REMOTE
            )
            + sum(int(s["nbytes"]) for s in _REMOTE_SHADOW.values())
        )
    )


def _evict_for(store: HostRamStore, need: int) -> None:
    """Free >= ``need`` bytes by evicting drained objects, oldest-touch
    first. Undrained objects are never evicted (see module docstring);
    the caller refuses the put if this cannot make room."""
    if store.used_bytes + need <= store.capacity_bytes:
        return
    for key in list(store.objects):
        if store.used_bytes + need <= store.capacity_bytes:
            return
        obj = store.objects[key]
        if not obj.drained:
            continue
        del store.objects[key]
        store.used_bytes -= len(obj.data)
        _index_remove(key, store.host_id)
        telemetry.counter(_metric_names.HOT_TIER_EVICTIONS).inc()


def _index_remove(key: str, host_id: int) -> None:
    hosts = _KEY_HOSTS.get(key)
    if hosts is not None:
        try:
            hosts.remove(host_id)
        except ValueError:
            pass
        if not hosts:
            del _KEY_HOSTS[key]


def put_replica(
    key: str, host_id: int, data: bytes, tag: str, root: str,
    capacity_bytes: Optional[int] = None,
) -> bool:
    """Place one replica on ``host_id``; returns False when refused for
    capacity. Raises :class:`HostLostError` on a dead host. Replaces any
    existing replica of ``key`` (a re-written object invalidates the old
    bytes — stale replicas cannot survive a successful re-put)."""
    peer = _route_peer(host_id)
    if peer is not None:
        # Over the wire (no tier lock held during the RPC): the peer
        # reconstructs the delta push, fingerprint-verifies, stores, and
        # only then acks — `stored` False is a capacity refusal. A dead
        # or down peer raises HostLostError from inside put (counted as
        # a push failure in the wire stats).
        stored, stored_tag = peer.put(
            key, bytes(data), tag, root, capacity_bytes=capacity_bytes
        )
        with _TIER_LOCK:
            if _REMOTE.get(host_id) is not peer:
                # The membership moved on mid-RPC (the peer was
                # condemned/replaced while our push was in flight): the
                # bytes may sit in a process nothing routes to anymore.
                # Do NOT credit the shadow — report the placement
                # failed so the caller places elsewhere (and the repair
                # plane's count stays honest).
                return False
            if stored:
                _REMOTE_SHADOW[(host_id, key)] = {
                    "root": root.rstrip("/"),
                    "nbytes": len(data),
                    "tag": tag,
                    "stored_tag": stored_tag,
                    "put_t": time.time(),
                    "drained": False,
                }
                hosts = _KEY_HOSTS.setdefault(key, [])
                if host_id not in hosts:
                    hosts.append(host_id)
                telemetry.counter(_metric_names.HOT_TIER_REPLICAS).inc()
                _update_buffered_gauge()
        return stored
    with _TIER_LOCK:
        store = host_store(host_id, capacity_bytes)
        if not store.alive:
            raise HostLostError(f"host {host_id} is dead")
        old = store.objects.pop(key, None)
        if old is not None:
            store.used_bytes -= len(old.data)
            _index_remove(key, host_id)
        _evict_for(store, len(data))
        if store.used_bytes + len(data) > store.capacity_bytes:
            _update_buffered_gauge()
            return False
        store.objects[key] = HotObject(
            data=bytes(data), tag=tag, root=root, put_t=time.time()
        )
        store.used_bytes += len(data)
        hosts = _KEY_HOSTS.setdefault(key, [])
        if host_id not in hosts:
            hosts.append(host_id)
        _update_buffered_gauge()
        telemetry.counter(_metric_names.HOT_TIER_REPLICAS).inc()
        return True


def get_replica(key: str, host_id: int) -> HotObject:
    """The replica on ``host_id`` — raises :class:`HostLostError` (dead
    host) or ``KeyError`` (missing). Verifying the content tag is the
    CALLER's job (the runtime counts corruption as a fallback reason)."""
    peer = _route_peer(host_id)
    if peer is not None:
        if not peer.alive:
            raise HostLostError(f"host {host_id} is dead")
        return peer.get(key)  # KeyError / HostLostError propagate
    with _TIER_LOCK:
        store = _HOSTS.get(host_id)
        if store is None or not store.alive:
            raise HostLostError(f"host {host_id} is dead")
        obj = store.objects[key]  # KeyError propagates: replica missing
        store.objects.move_to_end(key)  # LRU touch
        return obj


def replica_hosts_for(key: str) -> Optional[List[int]]:
    """The rendezvous answer: hosts that (claimed to) hold ``key``, in
    placement order — or None for a key the hot tier never saw."""
    with _TIER_LOCK:
        hosts = _KEY_HOSTS.get(key)
        return list(hosts) if hosts is not None else None


def _remote_quiet(peer, op: str, *args) -> None:
    """Best-effort remote side-effect: a dead/unreachable peer already
    IS the state we wanted (its replicas are gone with it)."""
    try:
        getattr(peer, op)(*args)
    except (HostLostError, KeyError):
        pass
    except Exception as e:
        logger.warning(f"remote {op} failed: {e!r}")


def drop_replica(key: str, host_id: int) -> None:
    """Remove one (e.g. corrupt) replica."""
    peer = _route_peer(host_id)
    if peer is not None:
        _remote_quiet(peer, "drop", key)
        with _TIER_LOCK:
            _REMOTE_SHADOW.pop((host_id, key), None)
            _index_remove(key, host_id)
            _update_buffered_gauge()
        return
    with _TIER_LOCK:
        store = _HOSTS.get(host_id)
        if store is not None:
            obj = store.objects.pop(key, None)
            if obj is not None:
                store.used_bytes -= len(obj.data)
        _index_remove(key, host_id)
        _update_buffered_gauge()


def forget_key(key: str) -> bool:
    """Drop every replica of ``key``; True if any existed."""
    remote_peers = []
    with _TIER_LOCK:
        hosts = _KEY_HOSTS.pop(key, None)
        existed = False
        for h in hosts or []:
            peer = _route_peer(h)
            if peer is not None:
                if _REMOTE_SHADOW.pop((h, key), None) is not None:
                    existed = True
                remote_peers.append(peer)
                continue
            store = _HOSTS.get(h)
            if store is None:
                continue
            obj = store.objects.pop(key, None)
            if obj is not None:
                store.used_bytes -= len(obj.data)
                existed = True
        _update_buffered_gauge()
    for peer in remote_peers:  # RPCs outside the tier lock
        _remote_quiet(peer, "drop", key)
    return existed


def mark_drained(key: str, tag: Optional[str] = None) -> None:
    """Flag replicas of ``key`` as persisted (hence evictable). With
    ``tag``, only replicas holding exactly those bytes are flagged — a
    replica of a NEWER re-write of the object is not durable just
    because an older version of it reached storage. A remote replica is
    flagged by its STORED tag (a lossy push's stored bytes differ from
    the logical object, but the logical object they derive from is
    durable — they are equally evictable)."""
    remote_ops = []
    with _TIER_LOCK:
        for h in _KEY_HOSTS.get(key, []):
            peer = _route_peer(h)
            if peer is not None:
                shadow = _REMOTE_SHADOW.get((h, key))
                if shadow is not None and (
                    tag is None or shadow["tag"] == tag
                ):
                    shadow["drained"] = True
                    remote_ops.append((peer, shadow["stored_tag"]))
                continue
            store = _HOSTS.get(h)
            if store is not None:
                obj = store.objects.get(key)
                if obj is not None and (tag is None or obj.tag == tag):
                    obj.drained = True
    for peer, stored_tag in remote_ops:  # RPCs outside the tier lock
        _remote_quiet(peer, "mark_drained", key, stored_tag)


def drop_stale_replicas(key: str, tag: str) -> None:
    """Drop replicas of ``key`` whose content tag differs from ``tag``
    — superseded bytes left on hosts outside the newest placement when
    the replica set changed between writes. They must not linger: a
    self-consistent stale replica would serve old bytes to readers,
    and being undrained it would pin host RAM forever. Remote staleness
    is judged against the client-side shadow's LOGICAL tag (a lossy
    push stores different bytes under the same logical tag and is not
    stale)."""
    remote_peers = []
    with _TIER_LOCK:
        for h in list(_KEY_HOSTS.get(key, [])):
            peer = _route_peer(h)
            if peer is not None:
                shadow = _REMOTE_SHADOW.get((h, key))
                if shadow is not None and shadow["tag"] != tag:
                    del _REMOTE_SHADOW[(h, key)]
                    _index_remove(key, h)
                    remote_peers.append(peer)
                continue
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None and obj.tag != tag:
                del store.objects[key]
                store.used_bytes -= len(obj.data)
                _index_remove(key, h)
        _update_buffered_gauge()
    for peer in remote_peers:  # RPCs outside the tier lock
        _remote_quiet(peer, "drop", key)


def key_tag(key: str) -> Optional[str]:
    """The content tag of ``key``'s current replicas (None when no
    replica survives)."""
    with _TIER_LOCK:
        for h in _KEY_HOSTS.get(key, []):
            shadow = _REMOTE_SHADOW.get((h, key))
            if shadow is not None:
                return shadow["tag"]
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None:
                return obj.tag
        return None


def key_age_s(key: str) -> Optional[float]:
    """Seconds since the newest replica of ``key`` was put (None when no
    replica survives) — the hot tier's analog of ``object_age_s``, used
    by the same age-guarded sweeps."""
    with _TIER_LOCK:
        newest: Optional[float] = None
        for h in _KEY_HOSTS.get(key, []):
            shadow = _REMOTE_SHADOW.get((h, key))
            put_t: Optional[float] = None
            if shadow is not None:
                put_t = float(shadow["put_t"])
            else:
                store = _HOSTS.get(h)
                obj = store.objects.get(key) if store is not None else None
                if obj is not None:
                    put_t = obj.put_t
            if put_t is not None and (newest is None or put_t > newest):
                newest = put_t
        return None if newest is None else max(0.0, time.time() - newest)


def key_size_bytes(key: str) -> Optional[int]:
    with _TIER_LOCK:
        for h in _KEY_HOSTS.get(key, []):
            shadow = _REMOTE_SHADOW.get((h, key))
            if shadow is not None:
                return int(shadow["nbytes"])
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None:
                return len(obj.data)
        return None


def buffered_roots() -> Dict[str, int]:
    """``{snapshot_root: buffered_bytes}`` across all hosts — the
    accounting the leak checks and reconcile sweeps fold over. Bytes are
    summed over replicas (k copies of a root count k times). Remote
    replicas count from the client-side shadow; an in-process peer
    server's local store for a remotely-registered host is the SERVER
    half of the same replicas and is excluded (it would double-count)."""
    local_scope = getattr(_LOCAL_ONLY, "active", False)
    with _TIER_LOCK:
        out: Dict[str, int] = {}
        for host_id, store in _HOSTS.items():
            if not local_scope and host_id in _REMOTE:
                continue
            for obj in store.objects.values():
                out[obj.root] = out.get(obj.root, 0) + len(obj.data)
        if not local_scope:
            for shadow in _REMOTE_SHADOW.values():
                root = str(shadow["root"])
                out[root] = out.get(root, 0) + int(shadow["nbytes"])
        return out


def keys_for_root(root: str) -> List[str]:
    """Every key whose object belongs to ``root`` (any host)."""
    root = root.rstrip("/")
    with _TIER_LOCK:
        keys = set()
        for store in _HOSTS.values():
            for key, obj in store.objects.items():
                if obj.root == root:
                    keys.add(key)
        # Index entries whose replicas all died still address the root
        # by prefix (key = "<root>/<path>"): include them so forgetting
        # a root also clears dead-host index residue.
        for key in _KEY_HOSTS:
            if key.startswith(root + "/"):
                keys.add(key)
        return sorted(keys)


def total_buffered_bytes() -> int:
    local_scope = getattr(_LOCAL_ONLY, "active", False)
    with _TIER_LOCK:
        if local_scope:
            return sum(s.used_bytes for s in _HOSTS.values())
        return sum(
            s.used_bytes
            for h, s in _HOSTS.items()
            if h not in _REMOTE
        ) + sum(int(s["nbytes"]) for s in _REMOTE_SHADOW.values())


def host_occupancy() -> Dict[int, Dict[str, object]]:
    """Per-host occupancy for the runtime sampler / ops view: used vs
    capacity bytes, liveness, object count, and the undrained share —
    the bytes that are pinned (unevictable) because the durable tier
    does not hold them yet. One pass under the tier lock, so the view
    is self-consistent."""
    local_scope = getattr(_LOCAL_ONLY, "active", False)
    with _TIER_LOCK:
        out: Dict[int, Dict[str, object]] = {}
        for host_id, store in _HOSTS.items():
            undrained = sum(
                len(o.data) for o in store.objects.values() if not o.drained
            )
            out[host_id] = {
                "alive": store.alive,
                "used_bytes": store.used_bytes,
                "capacity_bytes": store.capacity_bytes,
                "objects": len(store.objects),
                "undrained_bytes": undrained,
            }
        for host_id, peer in [] if local_scope else _REMOTE.items():
            entries = [
                s for (h, _k), s in _REMOTE_SHADOW.items() if h == host_id
            ]
            out[host_id] = {
                "alive": peer.alive,
                "used_bytes": sum(int(s["nbytes"]) for s in entries),
                "capacity_bytes": int(
                    getattr(peer, "capacity_bytes", 0) or 0
                ),
                "objects": len(entries),
                "undrained_bytes": sum(
                    int(s["nbytes"]) for s in entries if not s["drained"]
                ),
                "remote": True,
            }
        return dict(sorted(out.items()))


# ----------------------------------------------------------- snapmem
#
# Polled memory domains (providers, not push handles): the tier mutates
# its stores at dozens of call sites under _TIER_LOCK, so snapmem polls
# a one-pass aggregate at snapshot time instead of instrumenting each.
# "hottier.host" is the local stores' real host RAM (undrained bytes
# pinned — evicting them would orphan committed bytes); the remote
# shadow is the client-side LEDGER of replicas parked on peer
# processes: real bytes, but not ours, so the domain is external
# (visible in the table, excluded from this process's committed/
# headroom math — the owning peer registers them itself).


def _mem_hosts_provider():
    with _TIER_LOCK:
        used = 0
        pinned = 0
        cap = 0
        for store in _HOSTS.values():
            used += store.used_bytes
            cap += store.capacity_bytes
            pinned += sum(
                len(o.data)
                for o in store.objects.values()
                if not o.drained
            )
        return used, pinned, (cap if _HOSTS else None)


def _mem_shadow_provider():
    with _TIER_LOCK:
        used = 0
        pinned = 0
        for s in _REMOTE_SHADOW.values():
            n = int(s["nbytes"])
            used += n
            if not s["drained"]:
                pinned += n
        return used, pinned, None


memwatch.register_provider("hottier.host", _mem_hosts_provider)
memwatch.register_provider(
    "hottier.shadow", _mem_shadow_provider, external=True
)
