"""Peer-host RAM stores: the storage substrate of the hot tier.

A *host* here is a failure domain that can be preempted as a unit — in
production one TPU worker host, in tests a virtual host id. Each host
exposes one :class:`HostRamStore`: a byte-capped in-RAM object store
holding hot replicas of recently taken snapshot objects. The rendezvous
index (``key → replica hosts``) records where each object's k replicas
landed so a reader probes exactly the hosts that hold it.

This module is deliberately transport-agnostic: in-process, the
"stores" are plain dicts (each virtual host a separate failure domain
the tests can kill independently); on a multi-host pod the same
interface is what a coord-layer (DCN KV / RDMA) transport implements —
the runtime only ever speaks ``put/get/drop`` plus the index. The
failure model the harness exercises — :func:`kill_host` drops a host's
RAM wholesale, exactly what preemption does — is identical either way.

Integrity: every object carries an xs128 content fingerprint
(fingerprint.py — the same algorithm that gates incremental dedup)
computed at put time over the exact payload bytes; ``get`` recomputes
and compares, so a corrupt replica is detected at the tier boundary and
the reader falls over to the next replica (or the durable tier) instead
of handing garbage to the consume path.

Eviction: only *drained* objects (already persisted to the durable
tier) are evictable, LRU per host. An undrained object is the only copy
of committed bytes outside its k-replica set — evicting it could leave
a manifest referencing bytes that exist in no tier, the exact invariant
the crash matrix proves we never violate. A put that cannot fit even
after evicting drained objects is *refused*; the caller degrades to a
synchronous durable write-through.

One module-wide lock guards hosts + objects + index: the structures are
tiny (metadata, not payload copies beyond the stored bytes) and a
single lock makes the cross-structure invariants (index entries always
name live replicas) trivially atomic.
"""

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import telemetry
from ..fingerprint import fingerprint_host
from ..telemetry import metrics as _metric_names

import logging

logger = logging.getLogger(__name__)


class HostLostError(RuntimeError):
    """The addressed peer host is dead (preempted / unreachable)."""


def payload_tag(data) -> str:
    """Content fingerprint of raw payload bytes (xs128, fingerprint.py)."""
    return fingerprint_host(bytes(data))


@dataclass
class HotObject:
    data: bytes
    tag: str  # xs128 fingerprint of ``data`` at put time
    root: str  # snapshot root this object belongs to (reconcile grouping)
    put_t: float  # epoch seconds
    drained: bool = False  # persisted to the durable tier


class HostRamStore:
    """One host's RAM store. All mutation happens under ``_TIER_LOCK``
    (module-wide); the class only encapsulates per-host state."""

    def __init__(self, host_id: int, capacity_bytes: int) -> None:
        self.host_id = host_id
        self.capacity_bytes = capacity_bytes
        self.alive = True
        self.objects: "OrderedDict[str, HotObject]" = OrderedDict()
        self.used_bytes = 0


_TIER_LOCK = threading.RLock()
_HOSTS: Dict[int, HostRamStore] = {}
# Rendezvous index: key -> hosts holding a replica (in placement order).
_KEY_HOSTS: Dict[str, List[int]] = {}


def host_store(host_id: int, capacity_bytes: Optional[int] = None) -> HostRamStore:
    with _TIER_LOCK:
        store = _HOSTS.get(host_id)
        if store is None:
            store = HostRamStore(
                host_id,
                capacity_bytes if capacity_bytes is not None else (1 << 30),
            )
            _HOSTS[host_id] = store
        elif capacity_bytes is not None:
            store.capacity_bytes = capacity_bytes
        return store


def kill_host(host_id: int) -> None:
    """Simulate preemption: the host's RAM is gone and the host is dead.

    Index entries are NOT cleaned — a reader discovers the death on
    access (the ``dead`` fallback reason), exactly like a real
    unreachable peer."""
    with _TIER_LOCK:
        store = host_store(host_id)
        store.alive = False
        store.objects.clear()
        store.used_bytes = 0
        _update_buffered_gauge()


def revive_host(host_id: int) -> None:
    """Bring a host back (empty — preemption lost its RAM)."""
    with _TIER_LOCK:
        host_store(host_id).alive = True


def live_hosts() -> List[int]:
    with _TIER_LOCK:
        return sorted(h for h, s in _HOSTS.items() if s.alive)


def reset_hot_tier() -> None:
    """Drop every host, object, and index entry (tests)."""
    with _TIER_LOCK:
        _HOSTS.clear()
        _KEY_HOSTS.clear()
        _update_buffered_gauge()


def _update_buffered_gauge() -> None:
    # Lock held by caller.
    telemetry.gauge(_metric_names.HOT_TIER_BUFFERED_BYTES).set(
        float(sum(s.used_bytes for s in _HOSTS.values()))
    )


def _evict_for(store: HostRamStore, need: int) -> None:
    """Free >= ``need`` bytes by evicting drained objects, oldest-touch
    first. Undrained objects are never evicted (see module docstring);
    the caller refuses the put if this cannot make room."""
    if store.used_bytes + need <= store.capacity_bytes:
        return
    for key in list(store.objects):
        if store.used_bytes + need <= store.capacity_bytes:
            return
        obj = store.objects[key]
        if not obj.drained:
            continue
        del store.objects[key]
        store.used_bytes -= len(obj.data)
        _index_remove(key, store.host_id)
        telemetry.counter(_metric_names.HOT_TIER_EVICTIONS).inc()


def _index_remove(key: str, host_id: int) -> None:
    hosts = _KEY_HOSTS.get(key)
    if hosts is not None:
        try:
            hosts.remove(host_id)
        except ValueError:
            pass
        if not hosts:
            del _KEY_HOSTS[key]


def put_replica(
    key: str, host_id: int, data: bytes, tag: str, root: str,
    capacity_bytes: Optional[int] = None,
) -> bool:
    """Place one replica on ``host_id``; returns False when refused for
    capacity. Raises :class:`HostLostError` on a dead host. Replaces any
    existing replica of ``key`` (a re-written object invalidates the old
    bytes — stale replicas cannot survive a successful re-put)."""
    with _TIER_LOCK:
        store = host_store(host_id, capacity_bytes)
        if not store.alive:
            raise HostLostError(f"host {host_id} is dead")
        old = store.objects.pop(key, None)
        if old is not None:
            store.used_bytes -= len(old.data)
            _index_remove(key, host_id)
        _evict_for(store, len(data))
        if store.used_bytes + len(data) > store.capacity_bytes:
            _update_buffered_gauge()
            return False
        store.objects[key] = HotObject(
            data=bytes(data), tag=tag, root=root, put_t=time.time()
        )
        store.used_bytes += len(data)
        hosts = _KEY_HOSTS.setdefault(key, [])
        if host_id not in hosts:
            hosts.append(host_id)
        _update_buffered_gauge()
        telemetry.counter(_metric_names.HOT_TIER_REPLICAS).inc()
        return True


def get_replica(key: str, host_id: int) -> HotObject:
    """The replica on ``host_id`` — raises :class:`HostLostError` (dead
    host) or ``KeyError`` (missing). Verifying the content tag is the
    CALLER's job (the runtime counts corruption as a fallback reason)."""
    with _TIER_LOCK:
        store = _HOSTS.get(host_id)
        if store is None or not store.alive:
            raise HostLostError(f"host {host_id} is dead")
        obj = store.objects[key]  # KeyError propagates: replica missing
        store.objects.move_to_end(key)  # LRU touch
        return obj


def replica_hosts_for(key: str) -> Optional[List[int]]:
    """The rendezvous answer: hosts that (claimed to) hold ``key``, in
    placement order — or None for a key the hot tier never saw."""
    with _TIER_LOCK:
        hosts = _KEY_HOSTS.get(key)
        return list(hosts) if hosts is not None else None


def drop_replica(key: str, host_id: int) -> None:
    """Remove one (e.g. corrupt) replica."""
    with _TIER_LOCK:
        store = _HOSTS.get(host_id)
        if store is not None:
            obj = store.objects.pop(key, None)
            if obj is not None:
                store.used_bytes -= len(obj.data)
        _index_remove(key, host_id)
        _update_buffered_gauge()


def forget_key(key: str) -> bool:
    """Drop every replica of ``key``; True if any existed."""
    with _TIER_LOCK:
        hosts = _KEY_HOSTS.pop(key, None)
        existed = False
        for h in hosts or []:
            store = _HOSTS.get(h)
            if store is None:
                continue
            obj = store.objects.pop(key, None)
            if obj is not None:
                store.used_bytes -= len(obj.data)
                existed = True
        _update_buffered_gauge()
        return existed


def mark_drained(key: str, tag: Optional[str] = None) -> None:
    """Flag replicas of ``key`` as persisted (hence evictable). With
    ``tag``, only replicas holding exactly those bytes are flagged — a
    replica of a NEWER re-write of the object is not durable just
    because an older version of it reached storage."""
    with _TIER_LOCK:
        for h in _KEY_HOSTS.get(key, []):
            store = _HOSTS.get(h)
            if store is not None:
                obj = store.objects.get(key)
                if obj is not None and (tag is None or obj.tag == tag):
                    obj.drained = True


def drop_stale_replicas(key: str, tag: str) -> None:
    """Drop replicas of ``key`` whose content tag differs from ``tag``
    — superseded bytes left on hosts outside the newest placement when
    the replica set changed between writes. They must not linger: a
    self-consistent stale replica would serve old bytes to readers,
    and being undrained it would pin host RAM forever."""
    with _TIER_LOCK:
        for h in list(_KEY_HOSTS.get(key, [])):
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None and obj.tag != tag:
                del store.objects[key]
                store.used_bytes -= len(obj.data)
                _index_remove(key, h)
        _update_buffered_gauge()


def key_tag(key: str) -> Optional[str]:
    """The content tag of ``key``'s current replicas (None when no
    replica survives)."""
    with _TIER_LOCK:
        for h in _KEY_HOSTS.get(key, []):
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None:
                return obj.tag
        return None


def key_age_s(key: str) -> Optional[float]:
    """Seconds since the newest replica of ``key`` was put (None when no
    replica survives) — the hot tier's analog of ``object_age_s``, used
    by the same age-guarded sweeps."""
    with _TIER_LOCK:
        newest: Optional[float] = None
        for h in _KEY_HOSTS.get(key, []):
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None and (newest is None or obj.put_t > newest):
                newest = obj.put_t
        return None if newest is None else max(0.0, time.time() - newest)


def key_size_bytes(key: str) -> Optional[int]:
    with _TIER_LOCK:
        for h in _KEY_HOSTS.get(key, []):
            store = _HOSTS.get(h)
            obj = store.objects.get(key) if store is not None else None
            if obj is not None:
                return len(obj.data)
        return None


def buffered_roots() -> Dict[str, int]:
    """``{snapshot_root: buffered_bytes}`` across all hosts — the
    accounting the leak checks and reconcile sweeps fold over. Bytes are
    summed over replicas (k copies of a root count k times)."""
    with _TIER_LOCK:
        out: Dict[str, int] = {}
        for store in _HOSTS.values():
            for obj in store.objects.values():
                out[obj.root] = out.get(obj.root, 0) + len(obj.data)
        return out


def keys_for_root(root: str) -> List[str]:
    """Every key whose object belongs to ``root`` (any host)."""
    root = root.rstrip("/")
    with _TIER_LOCK:
        keys = set()
        for store in _HOSTS.values():
            for key, obj in store.objects.items():
                if obj.root == root:
                    keys.add(key)
        # Index entries whose replicas all died still address the root
        # by prefix (key = "<root>/<path>"): include them so forgetting
        # a root also clears dead-host index residue.
        for key in _KEY_HOSTS:
            if key.startswith(root + "/"):
                keys.add(key)
        return sorted(keys)


def total_buffered_bytes() -> int:
    with _TIER_LOCK:
        return sum(s.used_bytes for s in _HOSTS.values())


def host_occupancy() -> Dict[int, Dict[str, object]]:
    """Per-host occupancy for the runtime sampler / ops view: used vs
    capacity bytes, liveness, object count, and the undrained share —
    the bytes that are pinned (unevictable) because the durable tier
    does not hold them yet. One pass under the tier lock, so the view
    is self-consistent."""
    with _TIER_LOCK:
        out: Dict[int, Dict[str, object]] = {}
        for host_id, store in sorted(_HOSTS.items()):
            undrained = sum(
                len(o.data) for o in store.objects.values() if not o.drained
            )
            out[host_id] = {
                "alive": store.alive,
                "used_bytes": store.used_bytes,
                "capacity_bytes": store.capacity_bytes,
                "objects": len(store.objects),
                "undrained_bytes": undrained,
            }
        return out
