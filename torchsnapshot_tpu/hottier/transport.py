"""snapwire client: the hot tier's cross-host replication transport.

tier.py models peer hosts as in-process failure domains; this module
makes k of them *real*: a :class:`RemotePeer` speaks the shared
:mod:`torchsnapshot_tpu.wire` framing to a ``hottier.peer`` process
(peer.py) holding that host's RAM store, so an ``ack-at-k`` from
``hot_put`` means k replicas actually crossed a process (and, in
production, host) boundary and were fingerprint-verified by the
receiver BEFORE the ack came back.

The client side owns three robustness mechanisms:

- **Per-RPC deadlines** — every RPC is dispatched onto a shared
  background event loop and awaited with
  ``TPUSNAPSHOT_REPLICATION_DEADLINE_S``; a miss aborts the connection
  (a half-sent frame cannot be reused), counts
  ``tpusnapshot_hot_tier_replication_deadline_misses_total``, and is
  retried like any transport failure.
- **Decorrelated-jitter retry under an elapsed budget** — transport
  failures (dial refused, dropped/torn connection, deadline miss)
  retry with the same jitter shape as ``retry_storage_op``
  (uniform over ``[floor, prev*3]``, capped by
  ``TPUSNAPSHOT_REPLICATION_RETRY_CAP_S``) until
  ``TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S`` elapses; then the peer is
  marked down for a cooldown and :class:`~.tier.HostLostError` is
  raised — ``hot_put`` substitutes a spare host, and if k still cannot
  be placed the TieredPlugin degrades to the synchronous durable
  write-through *before the ack*. Ack-at-k is never a lie.
- **Delta replication + the codec stage** — each push carries
  chunk-granular deltas against the peer's *acknowledged previous cut*
  of the same object path (chunk fingerprints via
  ``fingerprint_host_chunked`` are the diff key): unchanged chunks
  travel as ``ref`` frames (offset+length only, the receiver copies
  from its stored base replica), changed chunks as ``raw`` frames
  encoded through the codec stage (``TPUSNAPSHOT_REPLICATION_CODEC``:
  ``auto`` = zstd when importable else uncompressed; ``zlib``/``zstd``
  explicit; ``none`` off) — and opt-in lossy int8 for optimizer-moment
  paths matched by ``TPUSNAPSHOT_REPLICATION_INT8_GLOBS`` (the
  EQuARX-style trade: the remote replica stores the dequantized
  moments, bounded by ``codecs.quant_error_bound``; the durable tier
  is never written from a lossy replica because the drain's tag match
  skips them — the local exact replica drains). The receiver
  reconstructs and fingerprint-verifies the full object before acking,
  so a bad basis or torn payload can only produce a NACK, never a
  wrong replica. A peer that lost the basis (eviction, restart)
  answers ``stale_basis`` and the client re-pushes full.

Deterministic wire faults (faultline's ``drop_conn`` / ``torn_frame``
/ ``slow_wire`` schedule rules) are scripted through
:func:`script_wire_fault` and consumed by the next matching RPC: a
*drop* aborts the connection before the request leaves, a *torn frame*
sends a truncated frame then aborts (the receiver's ``readexactly``
sees the tear; it never acks), a *slow* wire sleeps the RPC into its
deadline. All three surface as ordinary transport failures and take
the retry → spare-host → write-through degradation path above.

Everything here is called synchronously from tier.py (the existing
tier interface is unchanged); socket IO runs on one shared daemon
event loop so calls work from the scheduler's loop thread and drain
executor threads alike.
"""

import asyncio
import concurrent.futures
import fnmatch
import logging
import os
import random
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry, tracing, wire, wiretap
from ..fingerprint import fingerprint_host, fingerprint_host_chunked
from ..telemetry import metrics as _metric_names
from ..utils.env import env_float, env_int
from .tier import HostLostError, HotObject

logger = logging.getLogger(__name__)

ADDRS_ENV_VAR = "TPUSNAPSHOT_HOT_TIER_ADDRS"
DEADLINE_ENV_VAR = "TPUSNAPSHOT_REPLICATION_DEADLINE_S"
_DEFAULT_DEADLINE_S = 5.0
RETRY_BUDGET_ENV_VAR = "TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S"
_DEFAULT_RETRY_BUDGET_S = 10.0
RETRY_CAP_ENV_VAR = "TPUSNAPSHOT_REPLICATION_RETRY_CAP_S"
_DEFAULT_RETRY_CAP_S = 1.0
DOWN_COOLDOWN_ENV_VAR = "TPUSNAPSHOT_REPLICATION_DOWN_COOLDOWN_S"
_DEFAULT_DOWN_COOLDOWN_S = 2.0
CHUNK_ENV_VAR = "TPUSNAPSHOT_REPLICATION_CHUNK_BYTES"
_DEFAULT_CHUNK_BYTES = 1 << 20
DELTA_ENV_VAR = "TPUSNAPSHOT_REPLICATION_DELTA"
CODEC_ENV_VAR = "TPUSNAPSHOT_REPLICATION_CODEC"
INT8_GLOBS_ENV_VAR = "TPUSNAPSHOT_REPLICATION_INT8_GLOBS"

_RETRY_FLOOR_S = 0.05

# Deliberately unseeded, same contract as the storage retry layer:
# concurrent ranks must draw DIFFERENT delays.
_retry_rng = random.Random()

# Transport-level failures (the peer could not be spoken to). Server
# verdicts (stale_basis, capacity refusal, corrupt push) come back in
# well-formed response frames and are handled per-op.
_WIRE_ERRORS = (
    ConnectionError,
    OSError,
    EOFError,
    asyncio.IncompleteReadError,
    asyncio.TimeoutError,
    wire.ProtocolError,
)


# ------------------------------------------------------ snapwire op registry
#
# The single source of truth for the snapwire protocol: every op kind
# the client may put on the wire, the peer-server handler method that
# answers it, and the per-op policy (retry shape, idempotency). Runtime
# dispatch (peer.PeerServer._dispatch) and the static protocol checker
# (analysis/protocol.py, rules SNAP010/SNAP012) both read THIS dict, so
# a kind string cannot drift between client and server — an op added
# here without a matching ``_do_*`` method (or vice versa) is a lint
# failure before it is a runtime bad_request.
#
# ``retry``: "budget" ops go through the full decorrelated-jitter retry
# stack in ``_call``; "best_effort" ops try once and fail fast;
# "probe" is the un-retried liveness ping. Every op is idempotent by
# construction (put re-stores the same verified bytes under the same
# tag), which is what makes blind retry after an ambiguous failure
# safe — SNAP012 enforces that any op reaching the retry loop is
# declared in IDEMPOTENT_OPS below.
HOT_TIER_OPS: Dict[str, Dict[str, Any]] = {
    "put": {"handler": "_do_put", "retry": "budget"},
    "get": {"handler": "_do_get", "retry": "budget"},
    "query": {"handler": "_do_query", "retry": "budget"},
    "drop": {"handler": "_do_drop", "retry": "best_effort"},
    "mark_drained": {"handler": "_do_mark_drained", "retry": "best_effort"},
    "drop_stale": {"handler": "_do_drop_stale", "retry": "best_effort"},
    "stats": {"handler": "_do_stats", "retry": "budget"},
    "ping": {"handler": "_do_ping", "retry": "probe"},
}

# Ops that may be blindly re-sent after an ambiguous transport failure
# (the attempt may or may not have reached the peer). All of snapwire
# qualifies; the registry exists so the next non-idempotent op must
# make that decision explicitly.
IDEMPOTENT_OPS = frozenset(HOT_TIER_OPS)


class _WireFailure(Exception):
    """One RPC attempt failed at the transport level; retryable."""


class _DeadlineMiss(Exception):
    """The RPC's wire exchange blew TPUSNAPSHOT_REPLICATION_DEADLINE_S.
    Internal marker so _call_once counts the miss; converted to a
    retryable :class:`_WireFailure`."""


# ------------------------------------------------------- shared event loop

_LOOP_LOCK = threading.Lock()
_LOOP: Optional[asyncio.AbstractEventLoop] = None


def _loop() -> asyncio.AbstractEventLoop:
    """The shared snapwire event loop (daemon thread, lazily started)."""
    global _LOOP
    with _LOOP_LOCK:
        if _LOOP is not None and _LOOP.is_running():
            return _LOOP
        loop = asyncio.new_event_loop()
        ready = threading.Event()

        def _run() -> None:
            asyncio.set_event_loop(loop)
            loop.call_soon(ready.set)
            loop.run_forever()

        threading.Thread(
            target=_run, name="tpusnapshot-snapwire", daemon=True
        ).start()
        ready.wait(timeout=10.0)
        _LOOP = loop
        return loop


# ------------------------------------------------------------- wire stats

_TOTALS_LOCK = threading.Lock()
_TOTALS: Dict[str, int] = {
    "pushes": 0,
    "push_failures": 0,
    "payload_bytes": 0,
    "wire_bytes": 0,
    "retries": 0,
    "deadline_misses": 0,
}


def _bump(key: str, amount: int = 1) -> None:
    with _TOTALS_LOCK:
        _TOTALS[key] = _TOTALS.get(key, 0) + amount


def wire_stats_snapshot() -> Dict[str, int]:
    """Process-lifetime replication transport totals — the raw material
    of the per-take ``tier.replication`` window (runtime.py computes
    deltas between two snapshots)."""
    with _TOTALS_LOCK:
        return dict(_TOTALS)


# ---------------------------------------------------- scripted wire faults
#
# faultline's drop_conn / torn_frame / slow_wire schedule rules fire at
# deterministic op boundaries (hottier.replicate) and script the fault
# here; the next RPC to a matching host consumes and applies it. The
# indirection keeps the schedule deterministic (rules fire on the op
# stream) while the fault itself strikes the actual socket.

_SCRIPT_LOCK = threading.Lock()
_SCRIPT: List[Dict[str, Any]] = []


def script_wire_fault(
    kind: str, host: Optional[int] = None, seconds: float = 0.0
) -> None:
    """Arm one wire fault (``drop_conn`` | ``torn_frame`` |
    ``slow_wire``) for the next RPC to ``host`` (None = any host)."""
    if kind not in ("drop_conn", "torn_frame", "slow_wire"):
        raise ValueError(f"unknown wire fault kind {kind!r}")
    with _SCRIPT_LOCK:
        _SCRIPT.append({"kind": kind, "host": host, "seconds": seconds})


def clear_wire_faults() -> None:
    with _SCRIPT_LOCK:
        _SCRIPT.clear()


def _consume_faults(host_id: int) -> List[Dict[str, Any]]:
    """Pop at most ONE armed fault for this RPC (oldest matching): each
    scripted fault strikes exactly one RPC attempt, so arming N faults
    tears/drops/slows N successive attempts — the deterministic way to
    exhaust a retry budget."""
    with _SCRIPT_LOCK:
        for i, f in enumerate(_SCRIPT):
            if f["host"] is None or f["host"] == host_id:
                return [_SCRIPT.pop(i)]
        return []


# ------------------------------------------------------------- codec plan


def _resolve_codec(path: str) -> Optional[str]:
    """The per-frame codec for one object path: lossy int8 when the
    path matches an explicit ``TPUSNAPSHOT_REPLICATION_INT8_GLOBS``
    glob (comma-separated; opt-in only), else the lossless codec named
    by ``TPUSNAPSHOT_REPLICATION_CODEC`` (``auto`` = zstd when a
    backend is importable, uncompressed otherwise)."""
    from .. import codecs

    globs = (os.environ.get(INT8_GLOBS_ENV_VAR) or "").strip()
    if globs:
        for pattern in globs.split(","):
            pattern = pattern.strip()
            if pattern and fnmatch.fnmatchcase(path, pattern):
                return "int8"
    spec = (os.environ.get(CODEC_ENV_VAR) or "auto").strip().lower()
    if spec in ("none", "identity", "off", "0"):
        return None
    if spec == "auto":
        return "zstd" if "zstd" in codecs.available_codecs() else None
    codecs.check_codec(spec)
    return spec


def _lossless_fallback() -> Optional[str]:
    """The lossless codec an unsuitable int8 frame degrades to (the
    user's configured lossless choice, never another lossy codec)."""
    from .. import codecs

    spec = (os.environ.get(CODEC_ENV_VAR) or "auto").strip().lower()
    if spec in ("none", "identity", "off", "0", "int8"):
        return None
    if spec == "auto":
        return "zstd" if "zstd" in codecs.available_codecs() else None
    return spec


def _chunk_bytes() -> int:
    chunk = max(4, env_int(CHUNK_ENV_VAR, _DEFAULT_CHUNK_BYTES))
    return chunk - (chunk % 4)


def _delta_enabled() -> bool:
    return env_int(DELTA_ENV_VAR, 1) != 0


# ------------------------------------------------------------- RemotePeer


class RemotePeer:
    """Client handle for one remote peer host's RAM store.

    Implements the remote-host protocol tier.py routes to (put / get /
    drop / mark_drained / drop_stale / query / ping / kill). All
    methods are synchronous and thread-safe; RPCs are serialized per
    peer on the shared wire loop. ``process`` (when this client
    spawned the peer) enables the real ``lose_host`` semantics: a kill
    SIGKILLs the subprocess AND aborts in-flight connections so a
    blocked socket read observes the loss within the RPC deadline."""

    def __init__(
        self,
        host_id: int,
        addr: str,
        process: Any = None,
        capacity_bytes: Optional[int] = None,
        generation: int = 0,
    ) -> None:
        self.host_id = host_id
        self.addr_str = addr
        # Membership generation (snapmend): which incarnation of the
        # host this client speaks to. A ping answered by a server of a
        # DIFFERENT generation (a SIGCONT'd predecessor, a stale
        # process on a reused port) is refused — probe() returns False
        # instead of reviving a peer whose store belongs to a dead
        # membership view.
        self.generation = int(generation)
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.process = process
        self.capacity_bytes = (
            capacity_bytes
            if capacity_bytes is not None
            else env_int("TPUSNAPSHOT_HOT_TIER_BYTES", 1 << 30)
        )
        self._killed = False
        self._down_until = 0.0
        self._lock = threading.Lock()
        # Per-path delta basis: the peer's last ACKED cut of this
        # object path — {"key","stored_tag","fps","chunk","size"}.
        self._basis: Dict[str, Dict[str, Any]] = {}
        # Connection state lives on the wire loop; the asyncio.Lock is
        # created there on first use (single-threaded between awaits).
        self._conn: Optional[Tuple[Any, Any]] = None
        self._conn_lock: Optional[asyncio.Lock] = None

    # ------------------------------------------------------------ liveness

    @property
    def alive(self) -> bool:
        return not self._killed

    def _mark_down(self) -> None:
        cooldown = env_float(
            DOWN_COOLDOWN_ENV_VAR, _DEFAULT_DOWN_COOLDOWN_S
        )
        with self._lock:
            self._down_until = time.monotonic() + cooldown
        # A latched-down peer is a degrade event: flush the flight
        # recorder so the last RPCs against it survive a later crash.
        try:
            wiretap.note_degrade("peer_down", peer=self.addr_str)
        except Exception:  # pragma: no cover - defensive
            logger.debug("snapwire: blackbox dump failed", exc_info=True)

    def _is_down(self) -> bool:
        with self._lock:
            return time.monotonic() < self._down_until

    @property
    def in_cooldown(self) -> bool:
        """Inside the post-failure down cooldown right now (the repair
        tick's background re-probe targets exactly these peers, so a
        recovered host rejoins within one repair interval instead of
        waiting for the next foreground push to trip over it)."""
        return self._is_down()

    def probe(self, deadline_s: Optional[float] = None) -> bool:
        """Liveness probe: one un-retried ping RPC. A success clears a
        down cooldown early. A server answering with a DIFFERENT
        membership generation is not a success — a stale predecessor
        process (SIGCONT'd after its id moved on) must be refused, not
        revived."""
        if self._killed:
            return False
        try:
            resp, _ = self._call_once(
                {"v": wire.PROTOCOL_VERSION, "op": "ping"},
                b"",
                deadline_s or env_float(DEADLINE_ENV_VAR, _DEFAULT_DEADLINE_S),
            )
        except (_WireFailure, HostLostError):
            return False
        if resp.get("ok"):
            server_gen = resp.get("generation")
            if server_gen is not None and int(server_gen) < self.generation:
                logger.warning(
                    f"snapwire: peer at {self.addr_str} answered with "
                    f"stale generation {server_gen} (expected "
                    f"{self.generation}); refusing it"
                )
                return False
            if server_gen is not None and int(server_gen) > self.generation:
                # The SERVER is newer than this client's view — a
                # respawned (gen-up) peer reached through a client
                # rebuilt from the address book / port-file, which
                # carry no generation and default to 0. The stale side
                # is us, not the server: adopt its generation (and
                # sync the tier's membership view) instead of
                # condemning a healthy peer forever. Only a LOWER
                # generation marks a stale predecessor.
                logger.info(
                    f"snapwire: peer at {self.addr_str} answers "
                    f"generation {server_gen} (client view was "
                    f"{self.generation}); adopting"
                )
                self.generation = int(server_gen)
                from . import tier

                tier.note_host_generation(self.host_id, self.generation)
            with self._lock:
                self._down_until = 0.0
            return True
        return False

    def condemn(self) -> None:
        """Latch the peer dead WITHOUT signalling its process (snapmend:
        a hung/unreachable host is declared lost by the supervisor — the
        process may still exist, possibly on another machine). Every
        later op raises :class:`~.tier.HostLostError`; in-flight socket
        reads are aborted so nothing blocks out its full deadline."""
        with self._lock:
            if self._killed:
                return
            self._killed = True
        self.abort_connections()

    def abort_connections(self) -> None:
        """Abort the pooled connection from any thread (deadline miss,
        host kill): a blocked ``readexactly`` on it raises immediately
        instead of hanging until its own timeout."""
        loop = _LOOP
        if loop is None or not loop.is_running():
            return
        done = threading.Event()

        def _abort() -> None:
            try:
                self._abort_conn_on_loop()
            finally:
                done.set()

        loop.call_soon_threadsafe(_abort)
        done.wait(timeout=5.0)

    def _abort_conn_on_loop(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn[1].transport.abort()
            except Exception:
                logger.debug("snapwire conn abort failed", exc_info=True)

    def kill(self) -> None:
        """The real ``lose_host``: SIGKILL the peer process (when this
        client spawned it) and abort in-flight connections, then latch
        the peer dead — every later op raises
        :class:`~.tier.HostLostError` immediately. A peer already
        latched by :meth:`condemn` (which deliberately does NOT signal)
        still gets its subprocess signalled here: kill() IS the reap,
        and early-returning on the latch would leave a condemned hung
        subprocess alive past every later reap, pinning its RAM for
        the run."""
        with self._lock:
            self._killed = True
        proc = self.process
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10.0)
            except Exception:
                logger.warning(
                    f"snapwire: SIGKILL of peer host {self.host_id} "
                    f"failed",
                    exc_info=True,
                )
        self.abort_connections()

    def close(self, kill_spawned: bool = True) -> None:
        """Release the peer handle (test teardown / reset): abort
        connections; a spawned subprocess is killed so nothing leaks."""
        if kill_spawned and self.process is not None:
            self.kill()
        else:
            self.abort_connections()

    # ------------------------------------------------------------- RPC core

    async def _exchange(
        self,
        header: Dict[str, Any],
        payload: bytes,
        torn: bool,
        slow_s: float = 0.0,
    ) -> Tuple[Dict[str, Any], bytes]:
        """Dial (if needed) + one framed request/response on the pooled
        connection. Caller holds ``_conn_lock``. ``slow_s`` is the
        scripted slow_wire latency — inside the deadline window, so a
        slow wire above the deadline deterministically misses it."""
        if slow_s > 0:
            await asyncio.sleep(slow_s)
        if self._conn is None:
            self._conn = await asyncio.open_connection(*self._addr)
        reader, writer = self._conn
        if torn:
            frame = wire.encode_frame(header, payload)
            writer.write(frame[: max(1, len(frame) // 2)])
            await writer.drain()
            self._abort_conn_on_loop()
            raise ConnectionResetError("injected torn_frame")
        await wire.send_frame(writer, header, payload)
        return await wire.recv_frame(reader)

    async def _rpc(
        self, header: Dict[str, Any], payload: bytes, deadline_s: float
    ) -> Tuple[Dict[str, Any], bytes]:
        # Wire faults strike replication PUSHES only (the
        # hottier.replicate boundary that arms them guards a push): a
        # concurrent drain/query RPC consuming the fault would make the
        # schedule's replay nondeterministic under the background drain.
        faults = (
            _consume_faults(self.host_id)
            if header.get("op") == "put"
            else []
        )
        slow_s = sum(
            f["seconds"] for f in faults if f["kind"] == "slow_wire"
        )
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._killed:
                raise ConnectionResetError("peer killed while queued")
            if any(f["kind"] == "drop_conn" for f in faults):
                self._abort_conn_on_loop()
                raise ConnectionResetError("injected drop_conn")
            torn = any(f["kind"] == "torn_frame" for f in faults)
            try:
                # The per-RPC deadline bounds the WIRE EXCHANGE (dial +
                # send + recv), measured from when this RPC owns the
                # connection — time spent queued behind another RPC on
                # the same peer is not a miss, and a miss here aborts
                # only a connection this RPC actually owns (never a
                # neighbor's in-flight transfer).
                return await asyncio.wait_for(
                    self._exchange(header, payload, torn, slow_s=slow_s),
                    deadline_s,
                )
            except asyncio.TimeoutError:
                self._abort_conn_on_loop()
                raise _DeadlineMiss(
                    f"RPC deadline ({deadline_s:g}s) exceeded"
                ) from None
            except BaseException:
                self._abort_conn_on_loop()
                raise

    def _tap(
        self,
        op: str,
        start: float,
        outcome: str,
        sent: int,
        received: int,
        attempt: int,
        deadline_s: float,
    ) -> None:
        """Best-effort wiretap record for one attempt — observability
        must never take the transport down with it."""
        try:
            wiretap.record(
                "snapwire",
                op,
                seconds=time.monotonic() - start,
                outcome=outcome,
                bytes_out=sent,
                bytes_in=received,
                attempt=attempt,
                deadline_s=deadline_s,
                peer=self.addr_str,
            )
        except Exception:  # pragma: no cover - defensive
            logger.debug("snapwire: wiretap record failed", exc_info=True)

    def _call_once(
        self,
        header: Dict[str, Any],
        payload: bytes,
        deadline_s: float,
        attempt: int = 0,
    ) -> Tuple[Dict[str, Any], bytes]:
        op = header.get("op")
        if op not in HOT_TIER_OPS:
            # Programming error, not a wire condition: never retried,
            # never sent — the registry is the protocol.
            raise ValueError(f"unknown snapwire op {op!r}")
        if self._killed:
            raise HostLostError(
                f"peer host {self.host_id} ({self.addr_str}) is dead"
            )
        # Stamp the ambient snapxray trace onto the frame so the peer's
        # server-side wiretap events join the same merged trace.
        trace_id = tracing.current_trace_id()
        if trace_id is not None:
            header["trace"] = trace_id
        start = time.monotonic()
        fut = asyncio.run_coroutine_threadsafe(
            self._rpc(header, payload, deadline_s), _loop()
        )
        # The coroutine self-bounds its exchange with the deadline; the
        # outer wait only backstops a wedged wire loop. The queue wait
        # behind other RPCs on this peer is bounded by THEIR deadlines.
        backstop_s = deadline_s * 8 + 60.0
        try:
            resp, resp_payload = fut.result(timeout=backstop_s)
        except _DeadlineMiss as e:
            self._tap(
                op, start, "deadline_miss", len(payload), 0, attempt,
                deadline_s,
            )
            _bump("deadline_misses")
            telemetry.counter(
                _metric_names.HOT_TIER_REPLICATION_DEADLINE_MISSES
            ).inc()
            raise _WireFailure(str(e)) from None
        except concurrent.futures.TimeoutError:
            self._tap(
                op, start, "transport", len(payload), 0, attempt, deadline_s
            )
            fut.cancel()
            self.abort_connections()
            raise _WireFailure(
                f"RPC backstop ({backstop_s:g}s) exceeded"
            ) from None
        except _WIRE_ERRORS as e:
            self._tap(
                op,
                start,
                wiretap.classify_error(e),
                len(payload),
                0,
                attempt,
                deadline_s,
            )
            raise _WireFailure(repr(e)) from e
        outcome = (
            "ok"
            if resp.get("ok")
            else wiretap.outcome_from_wire_error(resp.get("error"))
        )
        self._tap(
            op,
            start,
            outcome,
            len(payload),
            len(resp_payload),
            attempt,
            deadline_s,
        )
        return resp, resp_payload

    def _call(
        self,
        header: Dict[str, Any],
        payload: bytes = b"",
        deadline_s: Optional[float] = None,
        best_effort: bool = False,
    ) -> Tuple[Dict[str, Any], bytes]:
        """One RPC with the full robustness stack: per-attempt deadline,
        decorrelated-jitter retry under the elapsed budget, down-
        cooldown, and :class:`~.tier.HostLostError` when the peer
        cannot be reached within the budget. ``best_effort`` ops
        (drop / mark_drained — side-effects a dead peer already has by
        being dead) try ONCE and fail fast instead of paying the whole
        retry budget per call against an unreachable peer."""
        if self._killed or self._is_down():
            raise HostLostError(
                f"peer host {self.host_id} ({self.addr_str}) is "
                f"{'dead' if self._killed else 'in down cooldown'}"
            )
        deadline = (
            deadline_s
            if deadline_s is not None
            else env_float(DEADLINE_ENV_VAR, _DEFAULT_DEADLINE_S)
        )
        if best_effort:
            try:
                return self._call_once(header, payload, deadline)
            except _WireFailure as e:
                self._mark_down()
                raise HostLostError(
                    f"peer host {self.host_id} ({self.addr_str}) "
                    f"unreachable (best-effort): {e}"
                ) from e
        budget = env_float(RETRY_BUDGET_ENV_VAR, _DEFAULT_RETRY_BUDGET_S)
        cap = env_float(RETRY_CAP_ENV_VAR, _DEFAULT_RETRY_CAP_S)
        if cap <= 0:
            cap = _DEFAULT_RETRY_CAP_S
        floor = min(_RETRY_FLOOR_S, cap)
        prev_delay = floor
        start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return self._call_once(
                    header, payload, deadline, attempt=attempt - 1
                )
            except _WireFailure as e:
                delay = min(
                    cap,
                    _retry_rng.uniform(floor, max(floor, prev_delay * 3.0)),
                )
                prev_delay = delay
                elapsed = time.monotonic() - start
                if elapsed + delay > budget:
                    self._mark_down()
                    raise HostLostError(
                        f"peer host {self.host_id} ({self.addr_str}) "
                        f"unreachable after {attempt} attempt(s), "
                        f"{elapsed:.1f}s of {budget:g}s budget: {e}"
                    ) from e
                _bump("retries")
                telemetry.counter(
                    _metric_names.HOT_TIER_REPLICATION_RETRIES
                ).inc()
                tracing.instant(
                    "snapwire.retry",
                    op=header.get("op"),
                    peer=self.addr_str,
                    attempt=attempt,
                    delay_s=round(delay, 3),
                )
                logger.warning(
                    f"snapwire: RPC to peer host {self.host_id} failed "
                    f"(attempt {attempt}): {e}; retrying in {delay:.2f}s"
                )
                time.sleep(delay)

    # ----------------------------------------------------------- operations

    @staticmethod
    def _object_path(root: str, key: str) -> str:
        prefix = root.rstrip("/") + "/"
        return key[len(prefix):] if key.startswith(prefix) else key

    def _encode_raw_frame(
        self, chunk: bytes, off: int, codec_name: Optional[str]
    ) -> Tuple[List[Any], bytes, bool]:
        """One raw frame: ``([kind, off, length, enc_len, codec],
        encoded_bytes, lossy)``. Incompressible or codec-unsuitable
        chunks degrade to uncompressed, never fail the push."""
        from .. import codecs

        length = len(chunk)
        enc = chunk
        name: Optional[str] = None
        lossy = False
        if codec_name == "int8":
            try:
                import numpy as _np

                # The wire layer has no dtype metadata: the glob opt-in
                # asserts float32 moments, and the finiteness probe
                # (chunkstore's plan-time gate, as close as a byte
                # stream allows) rejects payloads whose float32 view is
                # not finite — a wrong-dtype leaf usually reads as
                # inf/nan somewhere and degrades to lossless instead of
                # quantizing garbage. Non-float32 payloads that survive
                # the probe are the documented opt-in hazard
                # (docs/api.md): keep the globs narrow.
                view = _np.frombuffer(chunk, dtype=_np.float32)
                if not bool(_np.isfinite(view).all()):
                    raise ValueError(
                        "int8 opt-in payload is not finite float32"
                    )
                enc = codecs.encode("int8", chunk, dtype_name="float32")
                name, lossy = "int8", True
            except Exception:
                logger.debug(
                    "snapwire int8 frame degraded to lossless",
                    exc_info=True,
                )
                codec_name = _lossless_fallback()
        if not lossy and codec_name:
            try:
                cand = codecs.encode(codec_name, chunk)
                if len(cand) < length:
                    enc, name = cand, codec_name
            except Exception:
                logger.debug(
                    "snapwire codec encode degraded to raw", exc_info=True
                )
        return ["raw", off, length, len(enc), name], enc, lossy

    def put(
        self,
        key: str,
        data: bytes,
        tag: str,
        root: str,
        capacity_bytes: Optional[int] = None,
    ) -> Tuple[bool, str]:
        """Push one object replica, delta-encoded against the peer's
        acknowledged previous cut of the same path. Returns
        ``(stored, stored_tag)`` — ``stored`` False on a capacity
        refusal (the caller substitutes a spare host), ``stored_tag``
        the content tag of the bytes the peer actually holds (differs
        from ``tag`` only for lossy int8 pushes). Raises
        :class:`~.tier.HostLostError` when the peer cannot be reached
        within the deadline+retry budget."""
        path = self._object_path(root, key)
        size = len(data)
        codec_name = _resolve_codec(path)
        chunk_bytes = _chunk_bytes()
        delta_on = _delta_enabled()
        with self._lock:
            basis = dict(self._basis.get(path) or {})

        fps: Optional[List[str]] = None
        frames: List[List[Any]] = []
        parts: List[bytes] = []
        lossy = False
        used_refs = False
        if delta_on:
            fps = fingerprint_host_chunked(data, chunk_bytes)
            base_ok = bool(basis) and basis.get("chunk") == chunk_bytes
            base_fps = basis.get("fps") or []
            base_size = int(basis.get("size") or 0)
            for i, fp in enumerate(fps):
                off = i * chunk_bytes
                length = min(chunk_bytes, size - off)
                if (
                    base_ok
                    and i < len(base_fps)
                    and base_fps[i] == fp
                    and min(chunk_bytes, base_size - off) == length
                ):
                    frames.append(["ref", off, length])
                    used_refs = True
                else:
                    frame, enc, frame_lossy = self._encode_raw_frame(
                        data[off : off + length], off, codec_name
                    )
                    frames.append(frame)
                    parts.append(enc)
                    lossy = lossy or frame_lossy
        else:
            frame, enc, lossy = self._encode_raw_frame(data, 0, codec_name)
            frames.append(frame)
            parts.append(enc)

        header: Dict[str, Any] = {
            "v": wire.PROTOCOL_VERSION,
            "op": "put",
            "key": key,
            "root": root.rstrip("/"),
            "tag": tag,
            "size": size,
            "lossy": lossy,
            "frames": frames,
        }
        if used_refs:
            header["basis"] = {
                "key": basis["key"],
                "tag": basis["stored_tag"],
            }
        payload = b"".join(parts)
        try:
            resp, _ = self._call(header, payload)
        except HostLostError:
            # A push that could not reach the peer (dead, down, budget
            # exhausted): counted so the take's replication window (and
            # the replication-degraded doctor rule) sees wire distress
            # even when zero pushes succeeded.
            _bump("push_failures")
            raise
        if not resp.get("ok"):
            err = resp.get("error") or {}
            if err.get("kind") in ("stale_basis", "bad_frame") and (
                used_refs or basis
            ):
                # The peer no longer holds (or disagrees about) the
                # basis cut: drop it and re-push full — one level of
                # recursion by construction (no basis left).
                with self._lock:
                    self._basis.pop(path, None)
                _bump("retries")
                telemetry.counter(
                    _metric_names.HOT_TIER_REPLICATION_RETRIES
                ).inc()
                return self.put(key, data, tag, root, capacity_bytes)
            # A server-refused push (corrupt_push, bad_frame on a full
            # push) is a failed push too — the window and the doctor's
            # evidence must see it.
            _bump("push_failures")
            raise HostLostError(
                f"peer host {self.host_id} refused put({key}): {err!r}"
            )
        stored = bool(resp.get("stored"))
        if not stored:
            return False, tag  # capacity refusal; no ack, no basis
        stored_tag = str(resp.get("stored_tag") or tag)
        _bump("pushes")
        _bump("payload_bytes", size)
        _bump("wire_bytes", len(payload))
        telemetry.counter(
            _metric_names.HOT_TIER_REPLICATION_PUSHES
        ).inc()
        telemetry.counter(_metric_names.HOT_TIER_REPLICATION_BYTES).inc(
            size
        )
        telemetry.counter(
            _metric_names.HOT_TIER_REPLICATION_DELTA_BYTES
        ).inc(len(payload))
        with self._lock:
            if lossy or not delta_on:
                # A lossy push's stored bytes differ from ours — their
                # chunk fingerprints are unknown here, so it cannot
                # seed a delta basis.
                self._basis.pop(path, None)
            else:
                self._basis[path] = {
                    "key": key,
                    "stored_tag": stored_tag,
                    "fps": fps,
                    "chunk": chunk_bytes,
                    "size": size,
                }
        return True, stored_tag

    def get(self, key: str) -> HotObject:
        resp, payload = self._call(
            {"v": wire.PROTOCOL_VERSION, "op": "get", "key": key}
        )
        if not resp.get("ok"):
            err = resp.get("error") or {}
            if err.get("kind") == "not_found":
                raise KeyError(key)
            raise HostLostError(
                f"peer host {self.host_id} failed get({key}): {err!r}"
            )
        return HotObject(
            data=payload,
            tag=str(resp.get("tag") or ""),
            root=str(resp.get("root") or ""),
            put_t=float(resp.get("put_t") or 0.0),
            drained=bool(resp.get("drained")),
        )

    def query(self, key: str) -> Optional[Dict[str, Any]]:
        resp, _ = self._call(
            {"v": wire.PROTOCOL_VERSION, "op": "query", "key": key}
        )
        if not resp.get("ok") or not resp.get("found"):
            return None
        return {
            "tag": resp.get("tag"),
            "nbytes": resp.get("nbytes"),
            "put_t": resp.get("put_t"),
            "drained": resp.get("drained"),
        }

    def drop(self, key: str) -> None:
        self._call(
            {"v": wire.PROTOCOL_VERSION, "op": "drop", "key": key},
            best_effort=True,
        )

    def mark_drained(self, key: str, tag: Optional[str]) -> None:
        self._call(
            {
                "v": wire.PROTOCOL_VERSION,
                "op": "mark_drained",
                "key": key,
                "tag": tag,
            },
            best_effort=True,
        )

    def drop_stale(self, key: str, keep_tags: List[str]) -> None:
        self._call(
            {
                "v": wire.PROTOCOL_VERSION,
                "op": "drop_stale",
                "key": key,
                "keep_tags": list(keep_tags),
            },
            best_effort=True,
        )

    def occupancy(self) -> Optional[Dict[str, Any]]:
        try:
            resp, _ = self._call({"v": wire.PROTOCOL_VERSION, "op": "stats"})
        except HostLostError:
            return None
        return resp.get("occupancy") if resp.get("ok") else None

    def wire_stats(self) -> Optional[Dict[str, Any]]:
        """The peer's wiretap ``wire`` sample block (piggybacked on the
        ``stats`` op) — the ops CLI's fleet-wide wire view reads this.
        None when the peer is down or has recorded no RPCs yet. A
        probe, so best-effort: one attempt, no retry budget (the
        caller's verdict for an unreachable peer IS the answer)."""
        try:
            resp, _ = self._call(
                {"v": wire.PROTOCOL_VERSION, "op": "stats"},
                best_effort=True,
            )
        except HostLostError:
            return None
        block = resp.get("wire") if resp.get("ok") else None
        return block if isinstance(block, dict) else None

    def mem_stats(self) -> Optional[Dict[str, Any]]:
        """The peer's snapmem ``memory`` sample block (piggybacked on
        the ``stats`` op like :meth:`wire_stats`) — `ops --mem`'s
        fleet-wide memory table reads this. Best-effort probe."""
        try:
            resp, _ = self._call(
                {"v": wire.PROTOCOL_VERSION, "op": "stats"},
                best_effort=True,
            )
        except HostLostError:
            return None
        block = resp.get("memory") if resp.get("ok") else None
        return block if isinstance(block, dict) else None


# --------------------------------------------------------- registration


def connect_peer(
    host_id: int,
    addr: str,
    process: Any = None,
    capacity_bytes: Optional[int] = None,
    generation: int = 0,
) -> RemotePeer:
    """Create a :class:`RemotePeer` for ``addr`` and register it as the
    backing store of virtual host ``host_id`` — every tier operation
    addressing that host now crosses the wire. ``generation`` stamps
    the membership incarnation (respawned peers register one higher
    than their predecessor; see repair.py)."""
    from . import tier

    peer = RemotePeer(
        host_id,
        addr,
        process=process,
        capacity_bytes=capacity_bytes,
        generation=generation,
    )
    tier.register_remote_host(host_id, peer)
    return peer


def parse_addrs_spec(spec: str) -> Dict[str, str]:
    """Raw ``host=addr`` entries of an address-book spec (format
    ``"1=host:port,2=host:port"``), preserved verbatim — no validation,
    so a rewrite (repair.py's hot-reload) round-trips malformed-but-
    diagnosable entries instead of silently dropping them. The
    registration path validates what it consumes."""
    entries: Dict[str, str] = {}
    for entry in (spec or "").strip().split(","):
        entry = entry.strip()
        if not entry:
            continue
        host_part, sep, addr = entry.partition("=")
        # A separator-less entry is kept (with an empty addr) so the
        # registration path can still warn about it by name.
        entries[host_part.strip()] = addr.strip() if sep else ""
    return entries


def register_peers_from_env() -> Dict[int, RemotePeer]:
    """Register peers from ``TPUSNAPSHOT_HOT_TIER_ADDRS`` (format
    ``"1=host:port,2=host:port"``; host ids already registered are left
    alone). Called by ``enable_hot_tier`` so a multi-host deployment
    only needs the address book in the environment."""
    from . import tier

    out: Dict[int, RemotePeer] = {}
    for host_part, addr in parse_addrs_spec(
        os.environ.get(ADDRS_ENV_VAR) or ""
    ).items():
        if not host_part.isdigit() or ":" not in addr:
            logger.warning(
                f"snapwire: malformed {ADDRS_ENV_VAR} entry "
                f"{host_part + '=' + addr!r} (expected host_id=host:port); "
                f"skipped"
            )
            continue
        host_id = int(host_part)
        if tier.remote_host(host_id) is not None:
            continue
        out[host_id] = connect_peer(host_id, addr)
    return out
