"""hottier.peer: one host's RAM store served over the wire.

The server half of snapwire (transport.py is the client): a small
asyncio TCP service speaking the shared :mod:`torchsnapshot_tpu.wire`
framing, holding ONE virtual host's byte-capped RAM store — the same
:mod:`.tier` substrate the in-process model uses, scoped to this
process's ``--host-id``. Killing the process is killing the host:
``SIGKILL`` drops its RAM wholesale, exactly what preemption does,
which is what makes faultline's ``lose_host`` real.

Run standalone (one per peer host)::

    python -m torchsnapshot_tpu.hottier.peer \\
        --host-id 1 --addr 127.0.0.1:0 --port-file /tmp/peer1.addr

or in-process (tests: real sockets, no subprocess spawn cost)::

    server = start_local_peer(host_id=1)   # registers the RemotePeer

Ops: ``put`` (delta reconstruct → codec decode → **fingerprint-verify
→ store → ack**; a torn payload, bad frame, or missing basis NACKs and
stores nothing — ack-at-k is backed by verified bytes or not given),
``get``, ``query``, ``drop``, ``mark_drained``, ``drop_stale``
(keep-tags form: a lossy replica's stored tag differs from the
client's logical tag, so staleness is judged against the set),
``stats``, ``ping``. Requests on one connection are handled
sequentially (the client serializes per peer anyway); concurrency
comes from connections.
"""

import argparse
import asyncio
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import tracing, wire, wiretap
from ..utils.env import env_int
from . import tier

# The op registry is the client module's single source of truth
# (transport.py defines the protocol; this server half answers it).
# Importing it here is cycle-free: transport never imports peer — the
# two halves meet only over the wire (and in start_local_peer's lazy
# connect_peer import).
from .transport import HOT_TIER_OPS

logger = logging.getLogger(__name__)

_SPAWN_TIMEOUT_S = 120.0


class PeerServer:
    """Asyncio TCP server exposing one host's RAM store (tier.py,
    scoped to ``host_id``) over the snapwire ops."""

    def __init__(
        self,
        host_id: int,
        host: str = "127.0.0.1",
        port: int = 0,
        capacity_bytes: Optional[int] = None,
        generation: int = 0,
    ) -> None:
        self.host_id = host_id
        # Membership generation (snapmend): stamped by whoever spawned
        # this incarnation and echoed in every ping, so a supervisor
        # can refuse a stale predecessor process that wakes up after
        # its host id moved on to a fresh generation.
        self.generation = int(generation)
        self.capacity_bytes = (
            capacity_bytes
            if capacity_bytes is not None
            else env_int(
                "TPUSNAPSHOT_HOT_TIER_BYTES", 1 << 30
            )
        )
        self._host = host
        self._port = port
        self.addr: Optional[str] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._conn_writers: List[asyncio.StreamWriter] = []
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._startup_error: Optional[BaseException] = None
        self._killed = False
        # Ensure the host store exists (and carries the capacity) even
        # before the first put.
        tier.host_store(host_id, self.capacity_bytes)

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> str:
        loop = asyncio.get_running_loop()
        server = await asyncio.start_server(
            self._handle_conn, self._host, self._port
        )
        sock = server.sockets[0]
        host, port = sock.getsockname()[:2]
        addr = f"{host}:{port}"
        with self._lock:
            self._loop = loop
            self._server = server
            self.addr = addr
        logger.info(f"hottier.peer host {self.host_id} listening on {addr}")
        return addr

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    def kill(self, timeout_s: float = 5.0) -> None:
        """Abrupt in-process death (the subprocess form dies by real
        SIGKILL instead): close the listening socket and abort every
        live connection."""
        with self._lock:
            if self._killed:
                return
            self._killed = True
            loop = self._loop
        if loop is None or not loop.is_running():
            return
        done = threading.Event()

        def _close() -> None:
            try:
                if self._server is not None:
                    self._server.close()
                with self._lock:
                    writers = list(self._conn_writers)
                    self._conn_writers.clear()
                for writer in writers:
                    try:
                        writer.transport.abort()
                    except Exception:
                        logger.debug(
                            "hottier.peer kill: abort failed", exc_info=True
                        )
            finally:
                done.set()

        loop.call_soon_threadsafe(_close)
        if not done.wait(timeout_s):
            logger.warning("hottier.peer kill did not settle in time")

    def stop(self, timeout_s: float = 5.0) -> None:
        self.kill(timeout_s)
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout_s)

    # ---------------------------------------------------------- connections

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        with self._lock:
            self._conn_writers.append(writer)
        try:
            while True:
                try:
                    header, payload = await wire.recv_frame(reader)
                except (
                    asyncio.IncompleteReadError,
                    ConnectionError,
                ):
                    break  # torn frame / dropped conn: no ack, ever
                except wire.ProtocolError:
                    logger.warning(
                        "hottier.peer: protocol violation; closing "
                        "connection",
                        exc_info=True,
                    )
                    break
                response, resp_payload = self._handle_request(
                    header, payload
                )
                try:
                    await wire.send_frame(writer, response, resp_payload)
                except (ConnectionError, OSError):
                    break
        finally:
            with self._lock:
                if writer in self._conn_writers:
                    self._conn_writers.remove(writer)
            try:
                writer.close()
            except Exception:
                logger.debug(
                    "hottier.peer connection close failed", exc_info=True
                )

    # ------------------------------------------------------------- handlers

    def _handle_request(
        self, header: Dict[str, Any], payload: bytes
    ) -> Tuple[Dict[str, Any], bytes]:
        op = header.get("op")
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        start = time.monotonic()
        # Adopt the client's trace id off the frame so this server-side
        # wiretap event joins the same merged snapxray trace.
        trace_id = header.get("trace")
        with tracing.adopt_trace(
            trace_id if isinstance(trace_id, str) else None
        ):
            # The server half of the wire addresses its LOCAL store even
            # when this same process registered the host id as remote
            # (the in-process test form) — without the scope, tier calls
            # would route back through the RemotePeer into this very
            # server.
            with tier.serve_local():
                response, resp_payload = self._dispatch(
                    op, base, header, payload
                )
            try:
                # Unknown ops stay out of the wiretap: the telemetry
                # key space is exactly the PROTOCOL.md op inventory
                # (the conformance test holds us to it); a bad_request
                # probe must not mint a new label.
                if op in HOT_TIER_OPS:
                    wiretap.record(
                        "snapwire",
                        op,
                        seconds=time.monotonic() - start,
                        outcome=(
                            "ok"
                            if response.get("ok")
                            else wiretap.outcome_from_wire_error(
                                response.get("error")
                            )
                        ),
                        bytes_in=len(payload),
                        bytes_out=len(resp_payload),
                    )
            except Exception:  # pragma: no cover - defensive
                logger.debug(
                    "hottier.peer: wiretap record failed", exc_info=True
                )
        return response, resp_payload

    def _dispatch(
        self,
        op: Any,
        base: Dict[str, Any],
        header: Dict[str, Any],
        payload: bytes,
    ) -> Tuple[Dict[str, Any], bytes]:
        # Table-driven off the shared registry: the ops this server
        # answers ARE the ops the client may send, by construction —
        # adding one means adding a ``_do_*`` method AND a registry row,
        # and snapcheck's SNAP010 fails the build if either half drifts.
        meta = HOT_TIER_OPS.get(op) if isinstance(op, str) else None
        if meta is None:
            return (
                {
                    **base,
                    "ok": False,
                    "error": {
                        "kind": "bad_request",
                        "message": f"unknown op {op!r}",
                    },
                },
                b"",
            )
        try:
            handler = getattr(self, meta["handler"])
            return handler(header, payload)
        except Exception as e:
            return (
                {**base, "ok": False, "error": wire.error_to_wire(e)},
                b"",
            )

    def _do_put(
        self, header: Dict[str, Any], payload: bytes
    ) -> Tuple[Dict[str, Any], bytes]:
        from .. import codecs
        from ..fingerprint import fingerprint_host

        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}

        def _err(kind: str, message: str) -> Tuple[Dict[str, Any], bytes]:
            return (
                {
                    **base,
                    "ok": False,
                    "error": {"kind": kind, "message": message},
                },
                b"",
            )

        key = str(header.get("key"))
        root = str(header.get("root"))
        tag = str(header.get("tag"))
        size = int(header.get("size") or 0)
        lossy = bool(header.get("lossy"))
        frames = header.get("frames") or []
        basis = header.get("basis")
        base_bytes: Optional[bytes] = None
        if basis:
            try:
                base_obj = tier.get_replica(
                    str(basis.get("key")), self.host_id
                )
            except (KeyError, tier.HostLostError):
                base_obj = None
            if base_obj is None or base_obj.tag != basis.get("tag"):
                return _err(
                    "stale_basis",
                    f"basis {basis.get('key')!r} not held at tag "
                    f"{basis.get('tag')!r}",
                )
            base_bytes = base_obj.data
        out = bytearray(size)
        cursor = 0
        for frame in frames:
            kind, off, length = frame[0], int(frame[1]), int(frame[2])
            if off < 0 or off + length > size:
                return _err("bad_frame", f"frame out of bounds: {frame!r}")
            if kind == "ref":
                if base_bytes is None or off + length > len(base_bytes):
                    return _err(
                        "stale_basis", f"ref frame without basis: {frame!r}"
                    )
                out[off : off + length] = base_bytes[off : off + length]
                continue
            enc_len, codec_name = int(frame[3]), frame[4]
            chunk = payload[cursor : cursor + enc_len]
            cursor += enc_len
            if len(chunk) != enc_len:
                return _err("bad_frame", "payload shorter than frame table")
            try:
                dec = codecs.decode(codec_name, chunk)
            except Exception as e:
                return _err("bad_frame", f"codec decode failed: {e!r}")
            if len(dec) != length:
                return _err(
                    "bad_frame",
                    f"decoded {len(dec)} bytes, frame claims {length}",
                )
            out[off : off + length] = dec
        if cursor != len(payload):
            return _err("bad_frame", "payload longer than frame table")
        data = bytes(out)
        # The ack gate: the reconstructed object must fingerprint back
        # to the pushed content tag (lossy int8 pushes are tagged by
        # their own reconstructed bytes — the client is told which
        # bytes were actually stored, and the drain's strict tag match
        # keeps them out of the durable tier).
        stored_tag = fingerprint_host(data)
        if not lossy and stored_tag != tag:
            return _err(
                "corrupt_push",
                f"reconstructed fingerprint {stored_tag} != pushed "
                f"tag {tag}",
            )
        stored = tier.put_replica(
            key,
            self.host_id,
            data,
            stored_tag,
            root,
            capacity_bytes=self.capacity_bytes,
        )
        return (
            {
                **base,
                "ok": True,
                "stored": stored,
                "stored_tag": stored_tag,
            },
            b"",
        )

    def _do_get(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        key = str(header.get("key"))
        try:
            obj = tier.get_replica(key, self.host_id)
        except KeyError:
            return (
                {
                    **base,
                    "ok": False,
                    "error": {"kind": "not_found", "message": key},
                },
                b"",
            )
        return (
            {
                **base,
                "ok": True,
                "tag": obj.tag,
                "root": obj.root,
                "put_t": obj.put_t,
                "drained": obj.drained,
            },
            obj.data,
        )

    def _do_query(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        key = str(header.get("key"))
        try:
            obj = tier.get_replica(key, self.host_id)
        except KeyError:
            return {**base, "ok": True, "found": False}, b""
        return (
            {
                **base,
                "ok": True,
                "found": True,
                "tag": obj.tag,
                "nbytes": len(obj.data),
                "put_t": obj.put_t,
                "drained": obj.drained,
            },
            b"",
        )

    def _do_drop_stale(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        key = str(header.get("key"))
        keep = set(header.get("keep_tags") or [])
        try:
            obj = tier.get_replica(key, self.host_id)
        except KeyError:
            return {**base, "ok": True, "dropped": False}, b""
        if obj.tag in keep:
            return {**base, "ok": True, "dropped": False}, b""
        tier.drop_replica(key, self.host_id)
        return {**base, "ok": True, "dropped": True}, b""

    def _do_drop(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        tier.drop_replica(str(header.get("key")), self.host_id)
        return {**base, "ok": True}, b""

    def _do_mark_drained(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        tier.mark_drained(str(header.get("key")), header.get("tag"))
        return {**base, "ok": True}, b""

    def _do_stats(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        occ = tier.host_occupancy().get(self.host_id) or {
            "alive": True,
            "used_bytes": 0,
            "capacity_bytes": self.capacity_bytes,
            "objects": 0,
            "undrained_bytes": 0,
        }
        resp = {**base, "ok": True, "occupancy": occ}
        # This peer's own wire view rides the stats op so the ops CLI's
        # fleet-wide wire section can aggregate peers without a new op.
        try:
            block = wiretap.sample_block()
            if block.get("ops"):
                resp["wire"] = block
        except Exception:  # pragma: no cover - defensive
            logger.debug(
                "hottier.peer: wiretap sample failed", exc_info=True
            )
        # The memory plane rides the same op (`ops --mem` fleet table).
        try:
            from ..telemetry import memwatch

            mem = memwatch.sample_block()
            if mem.get("domains"):
                resp["memory"] = mem
        except Exception:  # pragma: no cover - defensive
            logger.debug(
                "hottier.peer: memwatch sample failed", exc_info=True
            )
        return resp, b""

    def _do_ping(
        self, header: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        base: Dict[str, Any] = {"v": wire.PROTOCOL_VERSION}
        return (
            {
                **base,
                "ok": True,
                "host": self.host_id,
                "generation": self.generation,
            },
            b"",
        )


# ------------------------------------------------- in-process / subprocess


def start_local_peer(
    host_id: int,
    capacity_bytes: Optional[int] = None,
    register: bool = True,
    generation: int = 0,
):
    """Run a peer server on a daemon thread of THIS process (real
    sockets, no spawn cost — the fast-test form). With ``register``
    the matching :class:`~.transport.RemotePeer` is registered so the
    tier routes host ``host_id`` over the wire; returns
    ``(server, peer_or_None)``."""
    server = PeerServer(
        host_id, capacity_bytes=capacity_bytes, generation=generation
    )

    def _run() -> None:
        async def _main() -> None:
            try:
                await server.start()
            except BaseException as e:
                server._startup_error = e
                server._ready.set()
                raise
            server._ready.set()
            assert server._server is not None
            try:
                async with server._server:
                    await server._server.serve_forever()
            except asyncio.CancelledError:
                logger.debug("hottier.peer local loop cancelled")

        try:
            asyncio.run(_main())
        except Exception:
            logger.warning("hottier.peer local server exited", exc_info=True)

    thread = threading.Thread(
        target=_run, name=f"hottier-peer-{host_id}", daemon=True
    )
    server._thread = thread
    thread.start()
    if not server._ready.wait(timeout=10.0):
        raise RuntimeError("hottier.peer failed to bind in time")
    if server._startup_error is not None:
        raise RuntimeError(
            f"hottier.peer failed to start: {server._startup_error!r}"
        )
    peer = None
    if register:
        from .transport import connect_peer

        peer = connect_peer(
            host_id,
            server.addr,
            capacity_bytes=capacity_bytes,
            generation=generation,
        )
    return server, peer


def spawn_peer(
    host_id: int,
    capacity_bytes: Optional[int] = None,
    register: bool = True,
    timeout_s: float = _SPAWN_TIMEOUT_S,
    generation: int = 0,
    port_file: Optional[str] = None,
):
    """Spawn a REAL peer subprocess (``python -m
    torchsnapshot_tpu.hottier.peer``) on an ephemeral port, discover
    the bound address through ``--port-file``, and (by default)
    register its :class:`~.transport.RemotePeer`. Returns
    ``(process, addr, peer_or_None)`` — killing ``process`` with
    SIGKILL is a real host loss (``tier.kill_host`` does exactly that
    for registered spawned peers).

    ``generation`` stamps the membership incarnation (the repair
    plane respawns a lost host one generation up). With ``port_file``
    the bound address is KEPT at that path after discovery — the hot
    tier's address-book file the supervisor hot-reloads on every
    respawn, so sidecar tooling rediscovers the peer without a process
    restart; without it a temp file is used and removed."""
    keep_port_file = port_file is not None
    if port_file is None:
        fd, port_file = tempfile.mkstemp(
            prefix="hottier-peer-", suffix=".addr"
        )
        os.close(fd)
    if os.path.exists(port_file):
        os.unlink(port_file)  # the peer writes it atomically when bound
    cmd = [
        sys.executable,
        "-m",
        "torchsnapshot_tpu.hottier.peer",
        "--host-id",
        str(host_id),
        "--addr",
        "127.0.0.1:0",
        "--port-file",
        port_file,
        "--generation",
        str(generation),
    ]
    if capacity_bytes is not None:
        cmd += ["--capacity-bytes", str(capacity_bytes)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        cmd,
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + timeout_s
    addr: Optional[str] = None
    try:
        while time.monotonic() < deadline:
            if os.path.exists(port_file):
                with open(port_file) as f:
                    addr = f.read().strip()
                if addr:
                    break
            if proc.poll() is not None:
                raise RuntimeError(
                    f"hottier.peer subprocess exited rc={proc.returncode} "
                    f"before binding"
                )
            time.sleep(0.05)
        if not addr:
            raise RuntimeError(
                f"hottier.peer subprocess did not bind within {timeout_s:g}s"
            )
    except BaseException:
        if proc.poll() is None:
            proc.kill()
        raise
    finally:
        if not keep_port_file:
            try:
                os.unlink(port_file)
            except OSError:
                pass
    peer = None
    if register:
        from .transport import connect_peer

        peer = connect_peer(
            host_id,
            addr,
            process=proc,
            capacity_bytes=capacity_bytes,
            generation=generation,
        )
        # The repair plane's respawn reuses the configured port-file so
        # the address book on disk follows the host across generations.
        peer.spawn_port_file = port_file if keep_port_file else None
    return proc, addr, peer


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.hottier.peer",
        description="snapwire peer: one host's hot-tier RAM store "
        "served over TCP.",
    )
    parser.add_argument(
        "--host-id", type=int, required=True, help="virtual host id"
    )
    parser.add_argument(
        "--addr",
        default="127.0.0.1:0",
        help="host:port to bind (port 0 = ephemeral; the bound address "
        "is printed and optionally written to --port-file)",
    )
    parser.add_argument(
        "--capacity-bytes",
        type=int,
        default=None,
        help="RAM store cap (default $TPUSNAPSHOT_HOT_TIER_BYTES or 1 GiB)",
    )
    parser.add_argument(
        "--port-file",
        default=None,
        help="write the bound host:port here once listening (lets "
        "spawning scripts discover an ephemeral port)",
    )
    parser.add_argument(
        "--generation",
        type=int,
        default=0,
        help="membership generation this incarnation serves (snapmend "
        "supervisors bump it per respawn; echoed in every ping)",
    )
    args = parser.parse_args(argv)
    host, _, port = args.addr.rpartition(":")
    server = PeerServer(
        args.host_id,
        host=host or "127.0.0.1",
        port=int(port or 0),
        capacity_bytes=args.capacity_bytes,
        generation=args.generation,
    )

    async def _main() -> None:
        addr = await server.start()
        print(f"hottier.peer host {args.host_id} on {addr}", flush=True)
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(addr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, args.port_file)
        await server.serve_forever()

    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        logger.info("hottier.peer: interrupted; shutting down")
    return 0


if __name__ == "__main__":
    sys.exit(main())
