"""Hot-tier runtime: replication, ack, background tier-down, reconcile.

The lifecycle the tiered backend implements (ROADMAP item 5):

1. **replicate** — every payload object a take writes is placed,
   k-replicated (``TPUSNAPSHOT_HOT_TIER_K``, default 2), into peer-host
   RAM stores (tier.py). Placement is rendezvous-deterministic: rank
   ``r``'s objects land on hosts ``r, r+1, … r+k-1 (mod world)``, the
   rank/world identities coming from the coord layer; a dead or full
   ring host is substituted by the next spare host around the ring.
2. **ack** — the write returns once k replicas are placed; the take's
   commit protocol (completion markers, metadata-last) proceeds
   unchanged, so ``async_take`` acknowledges at RAM speed. If fewer
   than k replicas could be placed anywhere (dead or full peers), the
   write degrades to a synchronous durable write-through BEFORE the
   ack — an acknowledged object is always either k-replicated in RAM
   or already durable, never resting on a lone RAM copy.
3. **tier-down** — a drainer persists each object to the durable plugin
   in the background and, once a committed root is fully drained,
   records a ``.tierdown`` watermark next to the manifest. A replica
   becomes evictable only after ITS durable write succeeded, so at
   every instant every manifest-referenced byte exists in >= 1 tier —
   the crash matrix enumerates every boundary of this pipeline
   (``hottier.replicate`` / ``hottier.drain`` / ``hottier.tierdown``
   op hooks) and proves it.
4. **restore** — reads prefer the hot tier (fingerprint-verified per
   object; see tier.py) and fall back per-object to the durable tier
   when replicas are dead, missing, or corrupt; fallbacks are counted
   and surface in the flight report / ledger / doctor
   (``hot-tier-degraded``).

Drain modes: ``"background"`` (production — a daemon thread drains as
the take proceeds) and ``"manual"`` (the fault harness — tier-down runs
synchronously via :func:`drain_now`, keeping faultline's op stream
deterministic so crash points replay exactly).

The durable plugin the drainer writes through is resolved via
``url_to_storage_plugin`` with THIS module's wrap bypassed (thread-
local), so it still passes every other installed wrapper — faultline's
FaultPlugin in particular: injected faults and crash points strike the
tier-down writes exactly as they would a foreground write, under the
real retry policy.
"""

import asyncio
import json
import logging
import os
import socket
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from .. import telemetry, tracing
from ..coord import Coordinator, get_coordinator
from ..io_types import IOReq, emit_storage_op, io_payload
from ..storage_plugin import is_ref_location
from ..telemetry import metrics as _metric_names
from ..utils.env import env_float, env_int
from . import tier

logger = logging.getLogger(__name__)

K_ENV_VAR = "TPUSNAPSHOT_HOT_TIER_K"
_DEFAULT_K = 2
BYTES_ENV_VAR = "TPUSNAPSHOT_HOT_TIER_BYTES"
_DEFAULT_CAPACITY_BYTES = 1 << 30

# The tier-down watermark, recorded next to the manifest once every
# payload object of a committed take reached the durable tier. Dot-
# prefixed (control plane): always written through, never hot-tiered.
TIERDOWN_FNAME = ".tierdown"
_METADATA_FNAME = ".snapshot_metadata"

_DRAIN_MAX_ATTEMPTS = 3

# Thread-local bypass: the drainer resolves the DURABLE plugin through
# url_to_storage_plugin with the hot-tier wrap skipped (other wraps —
# faultline — still apply); see module docstring.
_BYPASS = threading.local()


def is_payload_path(path: str) -> bool:
    """Payload objects ride the hot tier; everything dot-prefixed
    (metadata, markers, telemetry, reports, ``.tierdown``), incremental
    back-link markers (``refs/``), and base references (``@base…``) are
    control plane: written through to the durable tier synchronously —
    they ARE the commit protocol and must obey its durability ordering."""
    return not (
        path.startswith(".")
        or path.startswith("refs/")
        or is_ref_location(path)
    )


class _RootState:
    """Per-snapshot-root drain bookkeeping."""

    def __init__(self) -> None:
        self.pending: Set[str] = set()  # payload paths not yet durable
        # Content tag of the NEWEST bytes written at each pending path —
        # the tag a drain item must match to retire the path. A drain of
        # superseded bytes (the object was re-written while its drain
        # was queued or in flight) is recognized by the mismatch and
        # neither clears pending nor marks the new replicas evictable.
        self.tags: Dict[str, str] = {}
        # Ack timestamp (monotonic) and payload size per pending path:
        # the raw material of the durability-lag accounting (ack →
        # drained per object) and the sampler's at-risk-bytes view.
        self.ack_t: Dict[str, float] = {}
        self.sizes: Dict[str, int] = {}
        # Per-root tier-down progress: bytes enqueued for drain vs bytes
        # already durable (drained or written through) — what the
        # background drain's .progress/tierdown/<rank> records render.
        self.enqueued_bytes = 0
        self.drained_bytes = 0
        self.committed = False  # .snapshot_metadata observed
        self.commit_t: Optional[float] = None  # monotonic, at on_commit
        self.tierdown_done = False
        # Per-take durability lag (commit ack → .tierdown), recorded
        # when the watermark lands; also stamped INTO the watermark.
        self.durability_lag_s: Optional[float] = None
        self.drain_lost = 0  # objects whose every replica died pre-drain
        self.drained_objects = 0  # THIS root's objects tiered down
        self.write_through = 0  # THIS root's objects written through
        # The originating take's snapxray trace id (captured on the
        # take path at enqueue/commit): drain + tierdown spans adopt
        # it, so async tier-down appears in THAT take's causal trace
        # however long after the ack it runs.
        self.trace: Optional[str] = None
        # Items that exhausted their drain attempts: still pending (their
        # hot replicas stay unevictable — the only copy), re-driven by
        # the next drain_now(). wait_drained() reports them truthfully.
        self.stranded: Set[str] = set()
        self.tierdown_attempts = 0
        self.tierdown_stranded = False


class _DrainPluginCache:
    """Size-1 durable-plugin cache for one drain executor: a take's
    items share a root, so backend-client construction/teardown is paid
    per ROOT CHANGE instead of per drained object. close() after an
    item failure (the client may be poisoned) and when the executor
    exits."""

    def __init__(self, runtime: "HotTierRuntime") -> None:
        self._runtime = runtime
        self._root: Optional[str] = None
        self._plugin: Any = None

    def get(self, root: str) -> Any:
        if self._plugin is None or self._root != root:
            self.close()
            self._plugin = self._runtime._durable_plugin(root)
            self._root = root
        return self._plugin

    def close(self) -> None:
        plugin, self._plugin, self._root = self._plugin, None, None
        if plugin is not None:
            try:
                plugin.close()
            except Exception as e:
                logger.warning(f"drain plugin close failed: {e!r}")


class HotTierRuntime:
    """One process's hot-tier brain: placement, stats, the drain queue."""

    def __init__(
        self,
        rank: int,
        world: int,
        k: int,
        capacity_bytes: int,
        drain: str = "background",
    ) -> None:
        if drain not in ("background", "manual"):
            raise ValueError(
                f'drain must be "background" or "manual"; got {drain!r}'
            )
        self.rank = rank
        self.world = max(1, world)
        self.k = max(1, min(k, self.world))
        self.capacity_bytes = capacity_bytes
        self.drain_mode = drain
        self.active = True
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # Queue items: (root, path, tag, attempts); a watermark-only
        # item is (root, None, None, 0).
        self._queue: Deque[
            Tuple[str, Optional[str], Optional[str], int]
        ] = deque()
        self._roots: Dict[str, _RootState] = {}
        self._inflight = 0
        # In-flight drain items by (root, path): what forget_object /
        # forget_root condition-wait on, so a delete returns only after
        # any drain already holding the object bytes has finished (and
        # its forgotten-root re-check has run).
        self._inflight_items: Dict[Tuple[str, Optional[str]], int] = {}
        # Roots dropped by forget_root. An in-flight drain re-checks
        # this around its durable write: a write that raced a delete is
        # skipped (pre-check) or undone (post-check) so a deleted
        # snapshot's objects are never resurrected as durable garbage.
        self._forgotten: Set[str] = set()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.drain_error: Optional[BaseException] = None
        # Drain executor heartbeat (monotonic): refreshed at every loop
        # iteration of the background drainer (and drain_now); the
        # sampler derives "drain event-loop lag" from its age while the
        # queue is non-empty.
        self._drain_beat: Optional[float] = None
        # Tier-down progress publication state, per root (background
        # drain only — manual mode keeps the op stream deterministic
        # for the fault harness): last emit monotonic, seq, started-at.
        self._progress_emit: Dict[str, float] = {}
        self._progress_seq: Dict[str, int] = {}
        self._progress_start: Dict[str, float] = {}
        # Cumulative counters (stats_snapshot/delta power the per-restore
        # tier summary; concurrent operations smear, same contract as the
        # process-wide telemetry counters).
        self._stats: Dict[str, int] = {
            "hot_objects": 0,
            "hot_bytes": 0,
            "fallback_objects": 0,
            "fallback_bytes": 0,
            "replicas": 0,
            "write_through": 0,
            "write_through_bytes": 0,
            "replicated_ack_bytes": 0,
            "degraded_puts": 0,
            "drained_objects": 0,
            "drained_bytes": 0,
            "drain_lost": 0,
        }
        self._peer_failures: Dict[int, int] = {}
        self._reason_counts: Dict[str, int] = {}
        # snapmend repair plane (repair.py): attached by enable_hot_tier
        # when a repair mode is configured; None = no self-healing.
        self.repair_plane: Any = None

    def request_repair_scan(self) -> None:
        """Nudge the repair plane (a degraded read just proved a
        replica is gone — no reason to wait out the full interval)."""
        plane = self.repair_plane
        if plane is not None:
            plane.request_scan()

    # ---------------------------------------------------------- placement

    def _placement_ring(self) -> List[int]:
        """Every host in this rank's deterministic placement order: the
        preferred ring hosts first, then the spares around the ring —
        derived from (rank, world) alone, the same information every
        peer derives from the coord rendezvous."""
        return [(self.rank + i) % self.world for i in range(self.world)]

    def replica_hosts(self) -> List[int]:
        """This rank's PREFERRED replica set: itself plus the next k-1
        hosts in ring order. hot_put tries these first and continues to
        the remaining ring hosts (spares) when they cannot give k
        replicas."""
        return self._placement_ring()[: self.k]

    @staticmethod
    def _key(root: str, path: str) -> str:
        return f"{root.rstrip('/')}/{path}"

    # -------------------------------------------------------- write side

    def hot_put(
        self, root: str, path: str, payload: bytes
    ) -> Tuple[int, str]:
        """Replicate one payload object into peer RAM; returns
        ``(placed, tag)`` — how many replicas were placed and the
        payload's content tag (so callers never recompute or re-read
        it). The ring hosts are tried first; if they cannot give k
        replicas (dead or full peers), placement continues around the
        ring to spare hosts outside the replica set, so a single lost
        peer does not silently halve the replication factor. Fewer than
        k placed = the ack-at-k contract cannot be met from RAM: the
        caller must write through to the durable tier before
        acknowledging (0 placed additionally means no hot copy at all).
        Each replica placement is a storage-op boundary
        (``hottier.replicate``) so the crash-point enumerator can strike
        between replicas."""
        key = self._key(root, path)
        tag = tier.payload_tag(payload)
        placed = 0
        # Runs on the take path: the span inherits the take's ambient
        # trace id, so peer replication shows up inside the take's
        # causal trace (the drain later re-adopts the same id).
        with tracing.span(
            "hottier.replicate", path=path, bytes=len(payload)
        ):
            for i, host in enumerate(self._placement_ring()):
                if i >= self.k and placed >= self.k:
                    break
                emit_storage_op("hottier.replicate", f"host{host}:{path}")
                try:
                    if tier.put_replica(
                        key, host, payload, tag, root.rstrip("/"),
                        capacity_bytes=self.capacity_bytes,
                    ):
                        placed += 1
                except tier.HostLostError:
                    self._note_peer_failure(host, "dead")
        if placed == 0:
            # No replica landed: any stale replicas of an earlier object
            # at this key must not survive a write they no longer match.
            tier.forget_key(key)
        else:
            # The replica set may have changed since the last write of
            # this key (dead ring peer, spare substitution): replicas of
            # superseded bytes on hosts this placement did not revisit
            # would serve stale reads and pin RAM undrained forever.
            tier.drop_stale_replicas(key, tag)
        with self._lock:
            self._stats["replicas"] += placed
        return placed, tag

    def _cancel_queued_locked(
        self, root: str, path: Optional[str] = None
    ) -> None:
        """``_cond`` held: remove queued drain items of ``root`` — one
        path, or (path None) every item of the root, watermark
        sentinels included."""
        self._queue = deque(
            item
            for item in self._queue
            if not (
                item[0] == root and (path is None or item[1] == path)
            )
        )

    def begin_write_through(self, root: str, path: str) -> None:
        """Quiesce the drain pipeline for ``path`` ahead of a
        synchronous durable write-through: the queued drain item (if
        any) is removed and any IN-FLIGHT drain of the path waited out,
        so a drain still holding superseded bytes can never land its
        durable write after (and over) the write-through's. The pending
        entry deliberately SURVIVES until :meth:`note_write_through`
        (success) or :meth:`abort_write_through` (failure) — a failed
        write-through must not silently retire the durability
        obligation. Call BEFORE the durable write."""
        root = root.rstrip("/")
        with self._cond:
            self._cancel_queued_locked(root, path)
            self._cond.notify_all()
            if not self._wait_inflight_locked(
                lambda: self._inflight_items.get((root, path), 0)
            ):
                logger.warning(
                    f"begin_write_through: in-flight drain of "
                    f"{root}/{path} did not finish in time; its durable "
                    f"write may land after the write-through's"
                )

    def abort_write_through(
        self, root: str, path: str, tag: Optional[str], placed: int
    ) -> None:
        """The synchronous durable write of a degraded put FAILED: the
        newest bytes exist only in the ``placed`` (< k) replicas hot_put
        left behind. Re-arm the drain pipeline for them so the
        obligation stays visible — pending/tags point at the newest tag
        and a drain item is re-queued; its hot replicas stay unevictable
        until it lands. With placed == 0 the bytes exist in NO tier and
        the failed write is propagating to the caller (the take fails):
        drop any stale pending entry so it cannot block another object's
        truthful bookkeeping."""
        root_key = root.rstrip("/")
        if placed > 0:
            self.enqueue_drain(root, path, tag)
            return
        with self._cond:
            state = self._roots.get(root_key)
            if state is not None:
                state.pending.discard(path)
                state.tags.pop(path, None)
                state.ack_t.pop(path, None)
                state.sizes.pop(path, None)
                state.stranded.discard(path)
            self._cond.notify_all()

    def note_write_through(
        self,
        root: str,
        path: str,
        tag: Optional[str],
        placed: int,
        nbytes: Optional[int] = None,
    ) -> None:
        """The object was written through to the durable tier
        synchronously before ack — either no replica landed (placed ==
        0) or fewer than k did (a DEGRADED put: durability is restored
        by the synchronous write, at storage speed instead of RAM
        speed). Retires the path's pending entry (the durable tier now
        holds the newest bytes) and marks surviving replicas of ``tag``
        drained, i.e. evictable and still serving hot reads. Call AFTER
        the durable write SUCCEEDED (and after
        :meth:`begin_write_through`)."""
        root = root.rstrip("/")
        key = self._key(root, path)
        degraded = 0 < placed < self.k
        watermark_due = False
        now = time.monotonic()
        with self._cond:
            self._stats["write_through"] += 1
            if nbytes is not None:
                # Acked-bytes attribution for the replication-degraded
                # doctor rule: bytes whose pre-ack durability came from
                # the synchronous write-through path, not k replicas.
                self._stats["write_through_bytes"] += nbytes
            if degraded:
                self._stats["degraded_puts"] += 1
            self._forgotten.discard(root)
            state = self._roots.setdefault(root, _RootState())
            state.write_through += 1
            # Ack→durable lag of a write-through: 0 unless the path
            # carried a pending obligation from an earlier ack (a
            # re-armed degraded write) — the object is durable AT its
            # ack, which is the whole point of the degraded path.
            ack = state.ack_t.pop(path, None)
            object_lag_s = max(0.0, now - ack) if ack is not None else 0.0
            size = state.sizes.pop(path, None)
            if nbytes is None:
                nbytes = size
            if nbytes is not None:
                if path not in state.pending and size is None:
                    # Brand-new write-through (never enqueued): count it
                    # into the root's tier-down progress totals so
                    # bytes_done/total stay commensurable.
                    state.enqueued_bytes += nbytes
                state.drained_bytes += nbytes
            state.pending.discard(path)
            state.tags.pop(path, None)
            state.stranded.discard(path)
            if (
                state.committed
                and not state.pending
                and not state.tierdown_done
            ):
                # This write-through retired the root's last pending
                # object after commit: no drain item will ever visit the
                # watermark path, so enqueue the watermark-only sentinel
                # here (idempotent — _maybe_tierdown checks
                # tierdown_done).
                self._queue.append((root, None, None, 0))
                watermark_due = True
            self._cond.notify_all()
        if tag is not None:
            tier.mark_drained(key, tag)
        telemetry.counter(_metric_names.HOT_TIER_WRITE_THROUGH).inc()
        telemetry.histogram(_metric_names.HOT_TIER_OBJECT_LAG).observe(
            object_lag_s
        )
        if degraded:
            telemetry.counter(_metric_names.HOT_TIER_DEGRADED_PUTS).inc()
            logger.warning(
                f"hot tier degraded: only {placed}/{self.k} replicas of "
                f"{key} could be placed; the object was written through "
                f"to the durable tier before ack"
            )
        if watermark_due and self.drain_mode == "background":
            self._ensure_thread()

    def enqueue_drain(
        self,
        root: str,
        path: str,
        tag: Optional[str] = None,
        nbytes: Optional[int] = None,
        ack_t: Optional[float] = None,
    ) -> None:
        """``nbytes``/``ack_t`` (new writes: the payload size and the
        ack moment, stamped by the plugin) feed the durability-lag and
        at-risk accounting; a re-arm (abort_write_through, stranded
        re-drive) passes neither — the ORIGINAL ack keeps the clock, the
        obligation is as old as the ack that created it."""
        root = root.rstrip("/")
        if tag is None:
            tag = tier.key_tag(self._key(root, path))
        if nbytes is None:
            nbytes = tier.key_size_bytes(self._key(root, path))
        ambient_trace = tracing.current_trace_id()
        with self._cond:
            self._forgotten.discard(root)
            state = self._roots.setdefault(root, _RootState())
            if ambient_trace is not None:
                # Newest take to touch this root owns its drain trace.
                state.trace = ambient_trace
            was_pending = path in state.pending
            prev = state.tags.get(path) if was_pending else None
            was_stranded = path in state.stranded
            state.stranded.discard(path)
            state.pending.add(path)
            if ack_t is not None or path not in state.ack_t:
                state.ack_t[path] = (
                    ack_t if ack_t is not None else time.monotonic()
                )
            if nbytes is not None:
                if not was_pending:
                    state.enqueued_bytes += nbytes
                elif path in state.sizes:
                    # Re-write while pending: the root's total tracks
                    # the NEWEST bytes at each path.
                    state.enqueued_bytes += nbytes - state.sizes[path]
                state.sizes[path] = nbytes
            if tag is not None:
                state.tags[path] = tag
            if was_pending:
                # Only a previously-pending path can have a queued or
                # in-flight item — the brand-new-object hot path (the
                # common case per take) skips the O(queue) scans below.
                if (
                    prev is not None
                    and prev == tag
                    and not was_stranded
                    and (
                        any(
                            i[0] == root and i[1] == path
                            for i in self._queue
                        )
                        or self._inflight_items.get((root, path), 0) > 0
                    )
                ):
                    # Retried write of the same bytes AND a queued or
                    # in-flight item actually owns it: nothing to do.
                    # The ownership check matters — begin_write_through
                    # cancels the queued item while leaving pending/tags
                    # intact, so a same-tag re-arm (abort_write_through)
                    # must re-queue or the obligation would be silently
                    # dropped.
                    return
                # A queued item for this path (if any) names superseded
                # bytes — replace it so the drain persists what the
                # replicas actually hold now. An IN-FLIGHT item of the
                # old bytes is left to finish: its tag mismatch makes it
                # a no-op.
                self._cancel_queued_locked(root, path)
            self._queue.append((root, path, tag, 0))
            self._cond.notify_all()
        if self.drain_mode == "background":
            self._ensure_thread()

    def on_commit(self, root: str) -> None:
        """The root's metadata document was written (the take's commit
        point). Once its pending set drains empty, the ``.tierdown``
        watermark goes down; a root that committed with nothing pending
        (all write-through, or drained already) gets a watermark-only
        queue item."""
        root = root.rstrip("/")
        ambient_trace = tracing.current_trace_id()
        with self._cond:
            self._forgotten.discard(root)
            state = self._roots.setdefault(root, _RootState())
            if ambient_trace is not None:
                state.trace = ambient_trace
            state.committed = True
            if state.commit_t is None:
                # The take's ack point: the durability-lag clock the
                # .tierdown watermark closes starts here.
                state.commit_t = time.monotonic()
            if not state.pending and not state.tierdown_done:
                self._queue.append((root, None, None, 0))
                self._cond.notify_all()
        if self.drain_mode == "background":
            self._ensure_thread()

    # --------------------------------------------------------- read side

    def hot_get(
        self, root: str, path: str, byte_range: Optional[tuple]
    ) -> Tuple[Optional[bytes], bool]:
        """``(payload, attempted)``: the object from the first healthy
        replica, fingerprint-verified — or ``(None, attempted)`` where
        ``attempted`` says whether the hot tier KNEW this object (and
        every replica failed: a genuine degraded fallback) vs. never saw
        it (a cold read that must not count as degradation)."""
        key = self._key(root, path)
        hosts = tier.replica_hosts_for(key)
        if not hosts:
            return None, False
        # Prefer the local host's replica (no network hop in production).
        ordered = sorted(hosts, key=lambda h: h != self.rank)
        for host in ordered:
            try:
                obj = tier.get_replica(key, host)
            except tier.HostLostError:
                self._note_peer_failure(host, "dead")
                continue
            except KeyError:
                self._note_peer_failure(host, "missing")
                continue
            if tier.payload_tag(obj.data) != obj.tag:
                # Corrupt replica: drop it so nothing reads it again.
                self._note_peer_failure(host, "corrupt")
                tier.drop_replica(key, host)
                continue
            data = obj.data
            if byte_range is not None:
                start, end = byte_range
                data = data[start:end]
            with self._lock:
                self._stats["hot_objects"] += 1
                self._stats["hot_bytes"] += len(data)
            telemetry.counter(_metric_names.HOT_TIER_READS, tier="hot").inc()
            telemetry.counter(
                _metric_names.HOT_TIER_READ_BYTES, tier="hot"
            ).inc(len(data))
            return data, True
        with self._lock:
            self._stats["fallback_objects"] += 1
        telemetry.counter(
            _metric_names.HOT_TIER_READS, tier="durable"
        ).inc()
        return None, True

    def note_replicated_ack(self, nbytes: int) -> None:
        """The object was acked AT k replicas (no write-through): the
        other half of the acked-bytes attribution the
        replication-degraded doctor rule splits."""
        with self._lock:
            self._stats["replicated_ack_bytes"] += nbytes

    def replication_stats_begin(self) -> Dict[str, Any]:
        """Token for per-take replication attribution: a snapshot of
        the runtime's put-side stats plus the snapwire transport's
        process totals (transport.wire_stats_snapshot)."""
        from . import transport

        return {
            "rt": self.stats_snapshot(),
            "wire": transport.wire_stats_snapshot(),
        }

    def replication_stats_collect(
        self, token: Optional[Dict[str, Any]]
    ) -> Optional[Dict[str, Any]]:
        """The ``tier.replication`` block for the operation since
        ``token``: pushes/bytes/wire-bytes (and their ratio —
        ``delta_ratio``, the certified unchanged-retake number),
        retries, deadline misses, and the acked-bytes split between
        k-replication and the degraded write-through path. None when
        the window saw no wire traffic at all (the in-process tier's
        behavior is unchanged — this block exists for real
        transports)."""
        from . import transport

        if token is None:
            return None
        rt_now = self.stats_snapshot()
        wire_now = transport.wire_stats_snapshot()

        def _dw(field: str) -> int:
            return int(wire_now.get(field, 0)) - int(
                (token.get("wire") or {}).get(field, 0)
            )

        def _dr(field: str) -> int:
            return int(rt_now.get(field, 0)) - int(
                (token.get("rt") or {}).get(field, 0)
            )

        pushes = _dw("pushes")
        push_failures = _dw("push_failures")
        retries = _dw("retries")
        deadline_misses = _dw("deadline_misses")
        if (
            pushes <= 0
            and push_failures <= 0
            and retries <= 0
            and deadline_misses <= 0
        ):
            return None
        payload_bytes = _dw("payload_bytes")
        wire_bytes = _dw("wire_bytes")
        block: Dict[str, Any] = {
            "pushes": pushes,
            "push_failures": push_failures,
            "payload_bytes": payload_bytes,
            "wire_bytes": wire_bytes,
            "delta_ratio": (
                round(wire_bytes / payload_bytes, 4)
                if payload_bytes > 0
                else None
            ),
            "retries": retries,
            "deadline_misses": deadline_misses,
            "replicated_ack_bytes": _dr("replicated_ack_bytes"),
            "write_through_objects": _dr("write_through"),
            "write_through_bytes": _dr("write_through_bytes"),
            "degraded_puts": _dr("degraded_puts"),
        }
        return block

    def note_fallback_bytes(self, nbytes: int) -> None:
        with self._lock:
            self._stats["fallback_bytes"] += nbytes
        telemetry.counter(
            _metric_names.HOT_TIER_READ_BYTES, tier="durable"
        ).inc(nbytes)

    def _note_peer_failure(self, host: int, reason: str) -> None:
        with self._lock:
            self._peer_failures[host] = self._peer_failures.get(host, 0) + 1
            self._reason_counts[reason] = (
                self._reason_counts.get(reason, 0) + 1
            )
        telemetry.counter(
            _metric_names.HOT_TIER_FALLBACKS, reason=reason
        ).inc()

    # -------------------------------------------------- delete/reconcile

    def _wait_inflight_locked(
        self, count_fn, timeout_s: float = 60.0
    ) -> bool:
        """Condition-wait (``_cond`` held) until ``count_fn()`` drops to
        zero; False on timeout."""
        deadline = time.monotonic() + timeout_s
        while count_fn() > 0:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self._cond.wait(timeout=min(0.2, remaining))
        return True

    def forget_object(self, root: str, path: str) -> bool:
        """Drop every replica of one object and cancel its pending drain
        (a deleted object must never be resurrected into the durable
        tier by a later drain): the queued item is removed and any
        IN-FLIGHT drain of the object is waited out, so by the time this
        returns the caller's own durable delete cannot be overtaken by a
        racing tier-down write. True if the hot tier held it."""
        key = self._key(root, path)
        existed = tier.forget_key(key)
        root = root.rstrip("/")
        with self._cond:
            state = self._roots.get(root)
            if state is not None and path in state.pending:
                state.pending.discard(path)
                state.tags.pop(path, None)
                state.ack_t.pop(path, None)
                state.sizes.pop(path, None)
                state.stranded.discard(path)
                self._cancel_queued_locked(root, path)
                existed = True
                self._cond.notify_all()
            if not self._wait_inflight_locked(
                lambda: self._inflight_items.get((root, path), 0)
            ):
                logger.warning(
                    f"forget_object: in-flight drain of {root}/{path} "
                    f"did not finish in time; its durable write may "
                    f"land after the delete"
                )
        return existed

    def forget_root(self, root: str) -> int:
        """Drop every buffered object of ``root`` and cancel its drains
        (``Snapshot.delete`` / prune). Queued items are removed, the
        root is latched forgotten (an in-flight drain re-checks the
        latch around its durable write and skips or undoes a write that
        raced the delete), and in-flight items are waited out so the
        caller's durable deletes run strictly after any tier-down write
        already holding the object bytes. Returns objects dropped."""
        root = root.rstrip("/")
        dropped = 0
        for key in tier.keys_for_root(root):
            if tier.forget_key(key):
                dropped += 1
        with self._cond:
            self._roots.pop(root, None)
            self._forgotten.add(root)
            self._cancel_queued_locked(root)
            self._cond.notify_all()
            if self._wait_inflight_locked(
                lambda: sum(
                    c
                    for (r, _p), c in self._inflight_items.items()
                    if r == root
                )
            ):
                # Nothing of this root remains queued or in flight:
                # drop the latch so it neither leaks (one entry per
                # pruned step, forever) nor sabotages a snapshot later
                # re-created at the same root.
                self._forgotten.discard(root)
            else:
                logger.warning(
                    f"forget_root: in-flight drain of {root} did not "
                    f"finish in time; its durable write is undone by "
                    f"the drain's own forgotten-root re-check"
                )
        return dropped

    def object_age_s(self, root: str, path: str) -> Optional[float]:
        return tier.key_age_s(self._key(root, path))

    def object_size_bytes(self, root: str, path: str) -> Optional[int]:
        return tier.key_size_bytes(self._key(root, path))

    # -------------------------------------------------------- drain side

    def _ensure_thread(self) -> None:
        with self._lock:
            if self.drain_error is not None:
                # A crashed drainer stays crashed (the fault model:
                # process death); wait_drained() reports it and only an
                # explicit reset_pending()/new runtime clears it.
                return
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._drain_loop, name="tpusnapshot-hottier-drain",
                daemon=True,
            )
            self._thread.start()

    def _inflight_begin_locked(
        self, root: str, path: Optional[str]
    ) -> None:
        self._inflight += 1
        item = (root, path)
        self._inflight_items[item] = self._inflight_items.get(item, 0) + 1

    def _inflight_end_locked(self, root: str, path: Optional[str]) -> None:
        self._inflight -= 1
        item = (root, path)
        n = self._inflight_items.get(item, 0) - 1
        if n <= 0:
            self._inflight_items.pop(item, None)
        else:
            self._inflight_items[item] = n
        self._cond.notify_all()

    def _pop_runnable_locked(
        self,
    ) -> Optional[Tuple[str, Optional[str], Optional[str], int]]:
        """``_cond`` held: pop the next item whose path has NO in-flight
        drain (None if the queue is empty or everything is deferred).
        Two executors (the background drainer plus a drain_now
        re-drive) must never drain the same path concurrently — the tag
        ordering between their durable writes would be lost, and a
        stale write landing last would leave superseded bytes durable."""
        for _ in range(len(self._queue)):
            item = self._queue.popleft()
            if (
                item[1] is not None
                and self._inflight_items.get((item[0], item[1]), 0)
            ):
                self._queue.append(item)  # deferred behind the in-flight
                continue
            return item
        return None

    def _drain_loop(self) -> None:
        cache = _DrainPluginCache(self)
        try:
            while True:
                with self._cond:
                    while True:
                        self._drain_beat = time.monotonic()
                        item = self._pop_runnable_locked()
                        if item is not None:
                            break
                        if self._stop and not self._queue:
                            return
                        self._cond.wait(timeout=0.2)
                    root, path, tag, attempts = item
                    self._inflight_begin_locked(root, path)
                try:
                    self._drain_item(
                        root, path, tag, attempts, plugin=cache.get(root)
                    )
                except Exception as e:
                    # Per-item failures (e.g. a transient .tierdown
                    # write error) must not kill the drainer — the
                    # item's own requeue/leave-pending handling already
                    # ran; later items (or drain_now) re-drive what's
                    # left. The cached client may be poisoned: drop it.
                    cache.close()
                    logger.warning(f"hot-tier drain item failed: {e!r}")
                except BaseException as e:  # crashed drainer stays crashed
                    self.drain_error = e
                    logger.warning(f"hot-tier drain died: {e!r}")
                    return  # inflight released by the finally below
                finally:
                    with self._cond:
                        self._inflight_end_locked(root, path)
        finally:
            cache.close()

    def _requeue_stranded(self) -> None:
        """Move every stranded object/watermark back into the queue with
        fresh attempt budgets — drain_now()'s re-drive of work that
        exhausted its attempts (a backend outage that outlasted the
        retry layer)."""
        with self._cond:
            for root, state in self._roots.items():
                for path in sorted(state.stranded):
                    self._queue.append(
                        (root, path, state.tags.get(path), 0)
                    )
                state.stranded.clear()
                if state.tierdown_stranded:
                    state.tierdown_stranded = False
                    state.tierdown_attempts = 0
                    self._queue.append((root, None, None, 0))
            self._cond.notify_all()

    def drain_now(self) -> None:
        """Synchronous tier-down of everything pending — including
        re-driving stranded items (manual mode and tests; also usable to
        force-flush a background drainer). Runs on the caller's thread
        so faultline's op stream stays deterministic; a SimulatedCrash
        propagates to the caller like any crash."""
        self._requeue_stranded()
        cache = _DrainPluginCache(self)
        try:
            while True:
                with self._cond:
                    if not self._queue:
                        # Force-flush contract: another executor (the
                        # background drainer) may still hold an item in
                        # flight — wait it out (it may also requeue on
                        # failure) before reporting flushed.
                        while self._inflight and not self._queue:
                            self._cond.wait(timeout=0.2)
                        if not self._queue:
                            return
                    self._drain_beat = time.monotonic()
                    item = self._pop_runnable_locked()
                    if item is None:
                        # Everything queued is deferred behind an
                        # in-flight drain of the same path (another
                        # executor): wait for it to finish, then re-try.
                        self._cond.wait(timeout=0.2)
                        continue
                    root, path, tag, attempts = item
                    self._inflight_begin_locked(root, path)
                try:
                    self._drain_item(
                        root, path, tag, attempts, plugin=cache.get(root)
                    )
                finally:
                    with self._cond:
                        self._inflight_end_locked(root, path)
        finally:
            cache.close()

    def _durable_plugin(self, root: str):
        from ..storage_plugin import url_to_storage_plugin

        _BYPASS.active = True
        try:
            return url_to_storage_plugin(root)
        finally:
            _BYPASS.active = False

    def _drain_item(
        self,
        root: str,
        path: Optional[str],
        tag: Optional[str],
        attempts: int,
        plugin: Any = None,
    ) -> None:
        owned = plugin is None
        if owned:
            plugin = self._durable_plugin(root)
        try:
            if path is not None:
                self._drain_object(plugin, root, path, tag, attempts)
            self._maybe_tierdown(plugin, root)
        finally:
            if owned:
                plugin.close()

    def _item_current_locked(
        self, root: str, path: str, tag: Optional[str]
    ) -> bool:
        """``_cond`` held: does (root, path, tag) still name work to do?
        False when the root was forgotten (delete), the path's drain was
        canceled, or the object was re-written since this item was
        queued (a newer item owns the path; draining OUR bytes would
        persist stale data)."""
        if root in self._forgotten:
            return False
        state = self._roots.get(root)
        if state is None or path not in state.pending:
            return False
        expected = state.tags.get(path)
        return tag is None or expected is None or expected == tag

    def _drain_object(
        self,
        plugin: Any,
        root: str,
        path: str,
        tag: Optional[str],
        attempts: int,
    ) -> None:
        key = self._key(root, path)
        with self._cond:
            if not self._item_current_locked(root, path, tag):
                return  # canceled or superseded while queued
            state_trace = (
                self._roots[root].trace if root in self._roots else None
            )
        data: Optional[bytes] = None
        data_tag: Optional[str] = tag
        for host in tier.replica_hosts_for(key) or []:
            try:
                obj = tier.get_replica(key, host)
            except (tier.HostLostError, KeyError):
                continue
            if tag is not None and obj.tag != tag:
                continue  # replica of a different write of this object
            if tier.payload_tag(obj.data) == obj.tag:
                data = obj.data
                data_tag = obj.tag
                break
        if data is None:
            requeued = False
            with self._cond:
                if not self._item_current_locked(root, path, tag):
                    return  # superseded mid-probe: not a loss
                if attempts + 1 < _DRAIN_MAX_ATTEMPTS:
                    # No matching replica RIGHT NOW — but a foreground
                    # re-write may be mid-flight between replacing the
                    # replicas (hot_put) and updating the drain
                    # bookkeeping (enqueue_drain / write-through), which
                    # would make this item stale, not the bytes lost.
                    # Re-drive instead of declaring loss; a real loss is
                    # declared once the budget is spent with the
                    # bookkeeping still naming this item.
                    self._queue.append((root, path, tag, attempts + 1))
                    self._cond.notify_all()
                    requeued = True
                else:
                    # Every replica died before tier-down: the bytes
                    # are gone. The loss is counted and the pending
                    # entry retired — the root can never tier down
                    # clean, and a restore of this object will fail
                    # loudly at the durable tier (detect, not silent
                    # corruption).
                    self._stats["drain_lost"] += 1
                    state = self._roots.get(root)
                    if state is not None:
                        state.pending.discard(path)
                        state.tags.pop(path, None)
                        state.ack_t.pop(path, None)
                        state.sizes.pop(path, None)
                        state.drain_lost += 1
            if requeued:
                # Give a mid-flight foreground re-write time to land
                # its bookkeeping before the re-probe — back-to-back
                # re-pops would burn the whole budget in microseconds
                # and declare a phantom loss.
                time.sleep(0.01 * (attempts + 1))
            else:
                logger.warning(
                    f"hot-tier drain: every replica of {key} lost before "
                    f"tier-down; the object was never persisted"
                )
            return
        emit_storage_op("hottier.drain", path)
        try:
            # The drain executor runs on its own thread long after the
            # take returned: adopt the ORIGINATING take's trace id so
            # this tier-down write appears in that take's causal trace.
            with tracing.adopt_trace(state_trace), tracing.span(
                "hottier.drain", path=path, bytes=len(data)
            ):
                asyncio.run(plugin.write(IOReq(path=path, data=data)))
        except Exception as e:
            if attempts + 1 < _DRAIN_MAX_ATTEMPTS:
                with self._cond:
                    self._queue.append((root, path, tag, attempts + 1))
                    self._cond.notify_all()
                logger.warning(
                    f"hot-tier drain of {key} failed "
                    f"(attempt {attempts + 1}): {e!r}; requeued"
                )
                return
            # Out of attempts: the object stays pending AND is marked
            # stranded — its hot replicas stay unevictable (the only
            # copy), wait_drained() reports the root un-flushed, and the
            # next drain_now() re-drives it; the root's .tierdown is
            # withheld, which is the truthful state.
            with self._cond:
                state = self._roots.get(root)
                if state is not None:
                    state.stranded.add(path)
                self._cond.notify_all()
            logger.warning(
                f"hot-tier drain of {key} failed permanently: {e!r}; "
                f"object remains hot-tier-only (re-driven by the next "
                f"drain_now; no .tierdown until it lands)"
            )
            return
        # Only replicas of the bytes actually written become evictable:
        # a re-write racing this drain keeps ITS replicas pinned until
        # its own item lands.
        tier.mark_drained(key, data_tag)
        now = time.monotonic()
        object_lag_s: Optional[float] = None
        with self._cond:
            forgotten = root in self._forgotten
            state = self._roots.get(root)
            # Retire the pending entry only if the ITEM tag is still the
            # path's expected tag (strict: a popped/changed entry means
            # the write raced a delete or supersession and a newer item
            # — deferred behind us by _pop_runnable_locked — owns it).
            current = state is not None and state.tags.get(path) == tag
            if current and not forgotten:
                # An undone (deleted-root) or superseded (re-converged
                # and counted by its own item) write must not inflate
                # the tier-down throughput accounting.
                self._stats["drained_objects"] += 1
                self._stats["drained_bytes"] += len(data)
            if current:
                state.pending.discard(path)
                state.tags.pop(path, None)
                ack = state.ack_t.pop(path, None)
                state.sizes.pop(path, None)
                if ack is not None:
                    object_lag_s = max(0.0, now - ack)
                state.drained_bytes += len(data)
                state.drained_objects += 1
        if current and not forgotten:
            telemetry.counter(_metric_names.HOT_TIER_DRAINED_BYTES).inc(
                len(data)
            )
            if object_lag_s is not None:
                # The per-object durability-lag distribution: how long
                # each acked object rested on RAM replicas alone.
                telemetry.histogram(
                    _metric_names.HOT_TIER_OBJECT_LAG
                ).observe(object_lag_s)
            self._publish_drain_progress(plugin, root)
        if forgotten:
            # The snapshot was deleted while our durable write was in
            # flight: the object must not outlive it as durable garbage.
            try:
                asyncio.run(plugin.delete(path))
            except Exception as e:
                logger.warning(
                    f"hot-tier drain: undo of {key} after delete "
                    f"failed: {e!r}"
                )
        elif not current:
            # Our write raced a supersession whose bookkeeping already
            # retired the path (e.g. a write-through that outlasted
            # begin_write_through's bounded wait): the durable tier may
            # now hold OUR superseded bytes on top of the newer write's.
            # Re-converge on the newest replicas (idempotent — if the
            # newer item is simply deferred behind us, enqueue_drain
            # dedupes against it).
            newest = tier.key_tag(key)
            if newest is not None and newest != tag:
                self.enqueue_drain(root, path, newest)

    def _maybe_tierdown(self, plugin: Any, root: str) -> None:
        with self._cond:
            state = self._roots.get(root)
            ready = (
                state is not None
                and state.committed
                and not state.pending
                and not state.tierdown_done
                and state.drain_lost == 0
            )
            if not ready:
                return
            drained_objects = state.drained_objects
            write_through = state.write_through
            commit_t = state.commit_t
            state_trace = state.trace
        # Per-take durability lag: the take's ack (its metadata commit,
        # observed by on_commit) → this watermark. THE number that
        # bounds the RPO exposure window the hot tier opened.
        durability_lag_s = (
            round(max(0.0, time.monotonic() - commit_t), 6)
            if commit_t is not None
            else None
        )
        emit_storage_op("hottier.tierdown", TIERDOWN_FNAME)
        # Counts are THIS root's and THIS process's: in a multi-rank job
        # every metadata-writing process records its own drain progress;
        # the watermark does not (yet) assert other ranks' objects
        # drained — cross-rank drain coordination is future work, and
        # the explicit scope field keeps operators/sweeps honest.
        doc = {
            "format_version": 1,
            "drained_objects": drained_objects,
            "write_through_objects": write_through,
            "durability_lag_s": durability_lag_s,
            "scope": "process",
            "ts_epoch_s": round(time.time(), 3),
        }
        try:
            with tracing.adopt_trace(state_trace), tracing.span(
                "hottier.tierdown", root=root
            ):
                asyncio.run(
                    plugin.write(
                        IOReq(
                            path=TIERDOWN_FNAME,
                            data=json.dumps(doc, sort_keys=True).encode(
                                "utf-8"
                            ),
                        )
                    )
                )
        except Exception as e:
            # A failed watermark write must leave a re-drive trigger: the
            # root is fully drained, so no object item will ever call
            # back here — requeue the watermark-only sentinel (bounded
            # attempts, then stranded for the next drain_now()).
            with self._cond:
                state = self._roots.get(root)
                if state is not None:
                    state.tierdown_attempts += 1
                    if state.tierdown_attempts < _DRAIN_MAX_ATTEMPTS:
                        self._queue.append((root, None, None, 0))
                    else:
                        state.tierdown_stranded = True
                self._cond.notify_all()
            logger.warning(
                f"hot-tier .tierdown write for {root} failed: {e!r}; "
                f"will re-drive"
            )
            return
        with self._cond:
            forgotten = root in self._forgotten
            state = self._roots.get(root)
            if state is not None:
                state.tierdown_done = True
                state.durability_lag_s = durability_lag_s
            self._cond.notify_all()
        if forgotten:
            # Deleted mid-watermark-write: take the marker back out.
            try:
                asyncio.run(plugin.delete(TIERDOWN_FNAME))
            except Exception as e:
                logger.warning(
                    f"hot-tier drain: undo of {root}/{TIERDOWN_FNAME} "
                    f"after delete failed: {e!r}"
                )
            return
        if durability_lag_s is not None:
            telemetry.histogram(_metric_names.HOT_TIER_TAKE_LAG).observe(
                durability_lag_s
            )
        # Post-watermark observability fan-out, all best-effort: stamp
        # durability_lag_s into the take's flight report, append the
        # drain event record to the telemetry ledger (the "null until
        # drained" contract — ledger.py), and retire the tier-down
        # progress record. None of it may fail the drain.
        self._annotate_report_lag(plugin, durability_lag_s)
        self._append_tierdown_ledger(
            root, durability_lag_s, drained_objects, write_through
        )
        self._retire_drain_progress(plugin, root)

    # ------------------------------------------- tier-down observability
    #
    # Everything below is observability fan-out from the drain pipeline:
    # best-effort by contract (an Exception is logged, never propagated
    # — a SimulatedCrash still rips through like everywhere else), and
    # the live-progress records are BACKGROUND-mode only so the manual
    # fault harness keeps its deterministic op stream.

    _DRAIN_PROGRESS_TAKE_ID = "tierdown"

    def _drain_progress_path(self) -> str:
        return f".progress/{self._DRAIN_PROGRESS_TAKE_ID}/{self.rank}"

    def _publish_drain_progress(
        self, plugin: Any, root: str, force: bool = False
    ) -> None:
        """Publish ``root``'s tier-down progress record (phase
        ``tierdown``, bytes drained/total) to
        ``.progress/tierdown/<rank>`` in the root's own prefix — the
        same transport and lifecycle as take/restore progress records,
        so ``watch``/``ops`` show the background drain instead of going
        dark after commit. Rate-limited on the progress cadence;
        swept by :meth:`_retire_drain_progress` at the watermark, by
        ``Snapshot.delete``, and by ``reconcile`` like all ``.progress``
        debris."""
        if self.drain_mode != "background":
            return
        from ..telemetry import progress as liveprog

        now = time.monotonic()
        if not force:
            last = self._progress_emit.get(root, 0.0)
            if now - last < liveprog._interval_s():
                return
        self._progress_emit[root] = now
        with self._cond:
            state = self._roots.get(root)
            if state is None:
                return
            seq = self._progress_seq.get(root, 0) + 1
            self._progress_seq[root] = seq
            started = self._progress_start.setdefault(root, time.time())
            record = {
                "format_version": liveprog.PROGRESS_FORMAT_VERSION,
                "kind": "tierdown",
                "path": root,
                "take_id": self._DRAIN_PROGRESS_TAKE_ID,
                "rank": self.rank,
                "world_size": self.world,
                "phase": "tierdown",
                "bytes_done": state.drained_bytes,
                "bytes_total": state.enqueued_bytes or None,
                "ops": {
                    "drain": state.drained_objects,
                    "write_through": state.write_through,
                },
                "retries": 0,
                "seq": seq,
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "started_at": round(started, 3),
                "heartbeat_at": round(time.time(), 3),
            }
        try:
            asyncio.run(
                plugin.write(
                    IOReq(
                        path=self._drain_progress_path(),
                        data=json.dumps(record, sort_keys=True).encode(
                            "utf-8"
                        ),
                    )
                )
            )
        except Exception as e:
            logger.debug("tier-down progress write failed: %r", e)

    def _retire_drain_progress(self, plugin: Any, root: str) -> None:
        """The root fully tiered down: its progress record describes a
        finished operation — remove it (the drainer is the record's sole
        writer, so unlike take records there is no later sweep point and
        no republish race)."""
        if self.drain_mode != "background":
            return
        self._progress_emit.pop(root, None)
        self._progress_seq.pop(root, None)
        self._progress_start.pop(root, None)
        try:
            asyncio.run(plugin.delete(self._drain_progress_path()))
        except Exception as e:
            logger.debug("tier-down progress cleanup failed: %r", e)

    def _annotate_report_lag(
        self, plugin: Any, durability_lag_s: Optional[float]
    ) -> None:
        """Back-fill ``durability_lag_s`` into the committed take's
        ``.report.json`` so ``inspect --doctor`` (the
        ``durability-lag-above-budget`` rule) sees the closed exposure
        window. Best-effort: the report may not exist yet (a fast drain
        racing the commit route's report write) — the ledger's tierdown
        record still carries the number."""
        if durability_lag_s is None:
            return
        from ..io_types import io_payload as _io_payload
        from ..telemetry import report as flight

        try:

            async def _annotate() -> None:
                io_req = IOReq(path=flight.REPORT_FNAME)
                await plugin.read(io_req)
                doc = json.loads(bytes(_io_payload(io_req)).decode("utf-8"))
                doc["durability_lag_s"] = durability_lag_s
                await plugin.write(
                    IOReq(
                        path=flight.REPORT_FNAME,
                        data=json.dumps(
                            doc, indent=2, sort_keys=True
                        ).encode("utf-8"),
                    )
                )

            asyncio.run(_annotate())
        except Exception as e:
            logger.debug(
                "durability-lag report annotation skipped: %r", e
            )

    def _append_tierdown_ledger(
        self,
        root: str,
        durability_lag_s: Optional[float],
        drained_objects: int,
        write_through: int,
    ) -> None:
        """Append the drain event record (kind ``tierdown``) to the
        telemetry ledger: the take's own digest carries
        ``durability_lag_s: null`` (it is written at commit, when the
        window is still open); this record closes it."""
        from ..telemetry import ledger as runledger

        try:
            runledger.append_for_snapshot(
                root,
                runledger.tierdown_record(
                    path=root,
                    durability_lag_s=durability_lag_s,
                    drained_objects=drained_objects,
                    write_through_objects=write_through,
                ),
            )
        except Exception as e:
            telemetry.counter(_metric_names.LEDGER_APPEND_FAILURES).inc()
            logger.warning("tierdown ledger append failed: %r", e)

    # ----------------------------------------------------- introspection

    def introspect(self) -> Dict[str, Any]:
        """Lock-consistent snapshot of the drain pipeline's live state —
        what the runtime sampler (telemetry/sampler.py), the ops view,
        and tests consume. One pass under the runtime lock (per-host
        occupancy is read from the tier's own lock afterwards, so the
        two sections are each self-consistent)."""
        now = time.monotonic()
        with self._cond:
            queued_objects = sum(
                1 for it in self._queue if it[1] is not None
            )
            queued_watermarks = len(self._queue) - queued_objects
            roots: Dict[str, Any] = {}
            at_risk_bytes = 0
            at_risk_by_root: Dict[str, int] = {}
            pending_objects = 0
            stranded_objects = 0
            stranded_roots: List[str] = []
            oldest_age: Optional[float] = None
            oldest_at_risk_age: Optional[float] = None
            for root, st in sorted(self._roots.items()):
                pending_bytes = sum(
                    st.sizes.get(p, 0) for p in st.pending
                )
                pending_objects += len(st.pending)
                stranded_objects += len(st.stranded)
                if st.stranded or st.tierdown_stranded:
                    stranded_roots.append(root)
                at_risk = st.committed and not st.tierdown_done
                if at_risk:
                    at_risk_bytes += pending_bytes
                    if pending_bytes:
                        at_risk_by_root[root] = pending_bytes
                for p in st.pending:
                    t = st.ack_t.get(p)
                    if t is not None:
                        age = max(0.0, now - t)
                        if oldest_age is None or age > oldest_age:
                            oldest_age = age
                        # The RPO-relevant age is COMMITTED roots only:
                        # an in-flight take's pending objects are not
                        # an acked checkpoint's exposure window, and
                        # pairing their age with another root's at-risk
                        # bytes would fire a false lag alert.
                        if at_risk and (
                            oldest_at_risk_age is None
                            or age > oldest_at_risk_age
                        ):
                            oldest_at_risk_age = age
                roots[root] = {
                    "committed": st.committed,
                    "tierdown_done": st.tierdown_done,
                    "pending_objects": len(st.pending),
                    "pending_bytes": pending_bytes,
                    "stranded_objects": len(st.stranded),
                    "tierdown_stranded": st.tierdown_stranded,
                    "drain_lost": st.drain_lost,
                    "drained_bytes": st.drained_bytes,
                    "enqueued_bytes": st.enqueued_bytes,
                    "durability_lag_s": st.durability_lag_s,
                }
            beat = self._drain_beat
            plane = self.repair_plane
            doc: Dict[str, Any] = {
                "rank": self.rank,
                "world": self.world,
                "k": self.k,
                "drain_mode": self.drain_mode,
                "queue_depth": queued_objects,
                "queued_watermarks": queued_watermarks,
                "inflight": self._inflight,
                "pending_objects": pending_objects,
                "oldest_pending_age_s": (
                    round(oldest_age, 3) if oldest_age is not None else None
                ),
                "oldest_at_risk_age_s": (
                    round(oldest_at_risk_age, 3)
                    if oldest_at_risk_age is not None
                    else None
                ),
                "at_risk_bytes": at_risk_bytes,
                "at_risk_by_root": at_risk_by_root,
                "stranded_objects": stranded_objects,
                "stranded_roots": stranded_roots,
                "drain_error": (
                    repr(self.drain_error)
                    if self.drain_error is not None
                    else None
                ),
                "drain_heartbeat_age_s": (
                    round(max(0.0, now - beat), 3)
                    if beat is not None
                    else None
                ),
                "roots": roots,
                "stats": dict(self._stats),
            }
        doc["hosts"] = {
            str(h): occ for h, occ in tier.host_occupancy().items()
        }
        # snapmend: the repair/membership block (under-replication
        # accounting, per-host generation + liveness, repair stats) —
        # the sampler publishes it and the replication-underreplicated
        # live rule and the ops CLI read it.
        doc["repair"] = plane.introspect() if plane is not None else None
        telemetry.gauge(_metric_names.HOT_TIER_AT_RISK_BYTES).set(
            float(at_risk_bytes)
        )
        return doc

    def _dirty_pending_locked(self) -> bool:
        """``_cond`` held: is any pending path NOT accounted for by
        stranded? Such a path is owned by a queued/in-flight item or a
        foreground degraded write-through (queue-invisible between
        begin_write_through and note/abort) — work that is still
        resolving and must keep wait_drained waiting. Stranded paths
        are terminal until a drain_now() re-drive, so they exit the
        wait and fail the final cleanliness check instead."""
        return any(
            s.pending - s.stranded for s in self._roots.values()
        )

    def wait_drained(self, timeout_s: float = 120.0) -> bool:
        """Block until the drain queue is empty, nothing is in flight,
        and no non-stranded pending work remains (including a degraded
        write-through mid-flight on the foreground, which owns no queue
        item); True only on a genuinely clean flush — False on timeout,
        a dead drainer, or STRANDED work (objects/watermarks that
        exhausted their attempts and await a drain_now() re-drive):
        claiming success while committed bytes are still hot-tier-only
        would let a caller tear the tier down over the only copy."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while (
                self._queue
                or self._inflight
                or self._dirty_pending_locked()
            ):
                if self.drain_error is not None:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(0.2, remaining))
            return not any(
                s.stranded or s.tierdown_stranded
                for s in self._roots.values()
            )

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=10.0)

    def root_state(self, root: str) -> Optional[_RootState]:
        with self._lock:
            return self._roots.get(root.rstrip("/"))

    # ------------------------------------------------------------- stats

    def stats_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            snap: Dict[str, Any] = dict(self._stats)
            snap["peers"] = dict(self._peer_failures)
            snap["reasons"] = dict(self._reason_counts)
            return snap


# ---------------------------------------------------------- process-global

_RUNTIME: Optional[HotTierRuntime] = None
_PREV_HOOK: Any = None
_ENABLE_LOCK = threading.Lock()


def runtime() -> Optional[HotTierRuntime]:
    return _RUNTIME


def is_enabled() -> bool:
    return _RUNTIME is not None and _RUNTIME.active


def enable_hot_tier(
    rank: Optional[int] = None,
    world: Optional[int] = None,
    k: Optional[int] = None,
    capacity_bytes: Optional[int] = None,
    drain: str = "background",
    repair: Optional[str] = None,
    coord: Optional[Coordinator] = None,
) -> HotTierRuntime:
    """Turn the hot tier on process-wide: every storage plugin resolved
    from here on is wrapped in a :class:`~.plugin.TieredPlugin` (the
    same ``set_plugin_wrap_hook`` seam faultline uses; hooks chain, so
    enabling inside a faultline ``inject`` block — or vice versa —
    composes). ``rank``/``world`` default to the coord layer's identity
    (``jax.distributed`` on a pod, single-host otherwise); ``k`` and
    ``capacity_bytes`` default to ``TPUSNAPSHOT_HOT_TIER_K`` (2) and
    ``TPUSNAPSHOT_HOT_TIER_BYTES`` (1 GiB per host).

    ``repair`` attaches the snapmend self-healing plane (repair.py):
    ``"background"`` supervises peers and repairs under-replication on
    a daemon thread every ``TPUSNAPSHOT_REPAIR_INTERVAL_S``;
    ``"manual"`` constructs the plane but leaves ``repair_tick()`` to
    the caller (the fault harness's deterministic form); ``"off"``
    (the default, or ``TPUSNAPSHOT_REPAIR_MODE`` when unset here)
    disables it."""
    global _RUNTIME, _PREV_HOOK
    from .. import storage_plugin as _sp
    from .plugin import TieredPlugin
    from .repair import MODE_ENV_VAR, RepairPlane

    with _ENABLE_LOCK:
        if _RUNTIME is not None:
            raise RuntimeError(
                "hot tier is already enabled; disable_hot_tier() first"
            )
        if rank is None or world is None:
            coordinator = get_coordinator(coord)
            rank = coordinator.get_rank() if rank is None else rank
            world = (
                coordinator.get_world_size() if world is None else world
            )
        rt = HotTierRuntime(
            rank=rank,
            world=world,
            k=k if k is not None else env_int(K_ENV_VAR, _DEFAULT_K),
            capacity_bytes=(
                capacity_bytes
                if capacity_bytes is not None
                else env_int(BYTES_ENV_VAR, _DEFAULT_CAPACITY_BYTES)
            ),
            drain=drain,
        )
        if repair is None:
            repair = (
                os.environ.get(MODE_ENV_VAR) or "off"
            ).strip().lower() or "off"
        if repair != "off":
            rt.repair_plane = RepairPlane(rt, mode=repair)

        def _hook(plugin, url):
            base = (
                _PREV_HOOK(plugin, url) if _PREV_HOOK is not None else plugin
            )
            if getattr(_BYPASS, "active", False):
                return base  # drainer: durable tier, faults still apply
            return TieredPlugin(base, rt, url)

        _PREV_HOOK = _sp.set_plugin_wrap_hook(_hook)
        _RUNTIME = rt
        # Multi-host address book: TPUSNAPSHOT_HOT_TIER_ADDRS registers
        # real peer processes ("1=host:port,2=host:port") so replica
        # hosts named there are reached over the snapwire transport.
        from . import transport as _transport

        _transport.register_peers_from_env()
        if rt.repair_plane is not None:
            rt.repair_plane.start()
        return rt


def disable_hot_tier(flush: bool = True, timeout_s: float = 120.0) -> None:
    """Uninstall the hot tier (LIFO with any other wrap-hook users, like
    faultline's ``inject``). ``flush=True`` drains everything pending
    first so no committed bytes are stranded hot-only; plugins already
    resolved keep their wrapper but it deactivates (pass-through)."""
    global _RUNTIME, _PREV_HOOK
    from .. import storage_plugin as _sp

    with _ENABLE_LOCK:
        rt = _RUNTIME
        if rt is None:
            return
        try:
            if flush:
                if rt.drain_mode == "manual":
                    rt.drain_now()
                else:
                    rt._ensure_thread()
                    if not rt.wait_drained(timeout_s=timeout_s):
                        logger.warning(
                            "disable_hot_tier: drain did not flush "
                            f"within {timeout_s:g}s; undrained objects "
                            f"remain hot-tier-only"
                        )
        finally:
            # Uninstall UNCONDITIONALLY — a flush that raises (e.g. a
            # faultline SimulatedCrash striking a drain op) must not
            # leak the wrap hook and the runtime global, or the tier
            # could never be disabled or re-enabled again.
            if rt.repair_plane is not None:
                try:
                    rt.repair_plane.close()
                except Exception as e:
                    logger.warning(f"repair plane close failed: {e!r}")
            rt.stop()
            rt.active = False
            _sp.set_plugin_wrap_hook(_PREV_HOOK)
            _PREV_HOOK = None
            _RUNTIME = None


@contextmanager
def hot_tier(**kwargs):
    """``with hot_tier(world=4, k=2): ...`` — enable/disable scoped."""
    rt = enable_hot_tier(**kwargs)
    try:
        yield rt
    finally:
        disable_hot_tier()


# ------------------------------------------------------- module-level API


def drain_now() -> None:
    rt = _RUNTIME
    if rt is not None:
        rt.drain_now()


def repair_plane():
    """The attached snapmend repair plane (None when repair is off)."""
    rt = _RUNTIME
    return rt.repair_plane if rt is not None else None


def repair_tick() -> Optional[Dict[str, Any]]:
    """Run one synchronous supervise→restart→repair pass (manual-mode
    tests and the fault harness; also usable to force an immediate pass
    on a background plane). None when no plane is attached."""
    plane = repair_plane()
    return plane.tick() if plane is not None else None


def wait_drained(timeout_s: float = 120.0) -> bool:
    rt = _RUNTIME
    return rt.wait_drained(timeout_s=timeout_s) if rt is not None else True


def reset_pending() -> None:
    """Drop ALL drain bookkeeping (queue + per-root state + a dead
    drainer's error latch) without touching the stores — the fault
    harness's fresh-context hook: each crash-point replay starts from an
    empty op-relevant queue so the enumerated op stream is identical
    across replays."""
    rt = _RUNTIME
    if rt is None:
        return
    with rt._cond:
        rt._queue.clear()
        rt._roots.clear()
        rt._forgotten.clear()
        rt._progress_emit.clear()
        rt._progress_seq.clear()
        rt._progress_start.clear()
        rt.drain_error = None
        rt._cond.notify_all()
    plane = rt.repair_plane
    if plane is not None:
        # Crash-replay determinism: every replay starts with a fresh
        # under-replication clock and a live (un-crashed) plane.
        plane.reset_for_replay()


def introspect() -> Optional[Dict[str, Any]]:
    """Live drain-pipeline state (:meth:`HotTierRuntime.introspect`),
    or None when the tier is disabled — the sampler/ops entry point."""
    rt = _RUNTIME
    return rt.introspect() if rt is not None and rt.active else None


def durability_lag_s(root: str) -> Optional[float]:
    """The recorded per-take durability lag (commit ack → ``.tierdown``)
    for ``root``: None until the watermark landed (or tier disabled)."""
    rt = _RUNTIME
    if rt is None:
        return None
    state = rt.root_state(root)
    return state.durability_lag_s if state is not None else None


def forget_root(root: str) -> int:
    """Drop every hot replica of ``root`` and cancel its pending drains
    (``Snapshot.delete``/prune hook). Works with the runtime disabled
    too — registry-level state must not outlive its snapshot."""
    rt = _RUNTIME
    if rt is not None:
        return rt.forget_root(root)
    dropped = 0
    for key in tier.keys_for_root(root):
        if tier.forget_key(key):
            dropped += 1
    return dropped


def reconcile_hot_tier(
    base_path: str,
    keep_roots: Set[str],
    min_age_s: Optional[float] = None,
) -> List[str]:
    """Sweep orphaned hot-tier buffers under ``base_path``: roots not in
    ``keep_roots`` (the manager passes every step with committed
    metadata OR a step marker — so a committed-but-not-yet-drained
    take's replicas are structurally unreachable by this sweep) whose
    buffers have aged past the ``TPUSNAPSHOT_SWEEP_MIN_AGE_S`` guard,
    the same knob and fail-closed posture as every storage sweep.
    Returns the roots whose buffers were dropped."""
    if min_age_s is None:
        min_age_s = env_float("TPUSNAPSHOT_SWEEP_MIN_AGE_S", 3600.0)
    base = base_path.rstrip("/")
    keep = {r.rstrip("/") for r in keep_roots}
    dropped: List[str] = []
    for root, _nbytes in sorted(tier.buffered_roots().items()):
        if not (root == base or root.startswith(base + "/")):
            continue
        if root in keep:
            continue
        if min_age_s > 0:
            ages = [
                tier.key_age_s(key) for key in tier.keys_for_root(root)
            ]
            known = [a for a in ages if a is not None]
            # Fail closed: unknown age (or any young object) spares the
            # whole root — it may be an in-flight take's buffers.
            if not known or min(known) < min_age_s:
                continue
        forget_root(root)
        dropped.append(root)
    return dropped


def replication_stats_begin() -> Optional[Dict[str, Any]]:
    """Token for per-take replication attribution (None = tier off)."""
    rt = _RUNTIME
    return (
        rt.replication_stats_begin()
        if rt is not None and rt.active
        else None
    )


def replication_stats_collect(
    token: Optional[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """The take's ``tier.replication`` flight-report block since
    ``token`` (see :meth:`HotTierRuntime.replication_stats_collect`);
    None when the tier is off or the window had no wire traffic."""
    rt = _RUNTIME
    if token is None or rt is None:
        return None
    return rt.replication_stats_collect(token)


def restore_stats_begin() -> Optional[Dict[str, Any]]:
    """Token for per-restore tier attribution (None = tier disabled)."""
    rt = _RUNTIME
    return rt.stats_snapshot() if rt is not None and rt.active else None


def restore_stats_collect(
    token: Optional[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """The tier summary for the restore since ``token``: hot/fallback
    object+byte counts, the peers that failed, and why — the dict the
    flight report carries as ``tier`` and the ``hot-tier-degraded``
    doctor rule reads. None when the tier is off or saw no traffic."""
    rt = _RUNTIME
    if token is None or rt is None:
        return None
    now = rt.stats_snapshot()

    def _d(field: str) -> int:
        return int(now.get(field, 0)) - int(token.get(field, 0))

    summary = {
        "hot_objects": _d("hot_objects"),
        "hot_bytes": _d("hot_bytes"),
        "fallback_objects": _d("fallback_objects"),
        "fallback_bytes": _d("fallback_bytes"),
    }
    if not any(summary.values()):
        return None
    old_peers = token.get("peers") or {}
    summary["degraded_peers"] = sorted(
        h
        for h, c in (now.get("peers") or {}).items()
        if c > int(old_peers.get(h, 0))
    )
    old_reasons = token.get("reasons") or {}
    reasons = {
        r: c - int(old_reasons.get(r, 0))
        for r, c in (now.get("reasons") or {}).items()
        if c > int(old_reasons.get(r, 0))
    }
    if reasons:
        summary["fallback_reasons"] = reasons
    return summary
