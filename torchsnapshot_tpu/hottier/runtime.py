"""Hot-tier runtime: replication, ack, background tier-down, reconcile.

The lifecycle the tiered backend implements (ROADMAP item 5):

1. **replicate** — every payload object a take writes is placed,
   k-replicated (``TPUSNAPSHOT_HOT_TIER_K``, default 2), into peer-host
   RAM stores (tier.py). Placement is rendezvous-deterministic: rank
   ``r``'s objects land on hosts ``r, r+1, … r+k-1 (mod world)``, the
   rank/world identities coming from the coord layer.
2. **ack** — the write returns once the replicas are placed; the take's
   commit protocol (completion markers, metadata-last) proceeds
   unchanged, so ``async_take`` acknowledges at RAM speed.
3. **tier-down** — a drainer persists each object to the durable plugin
   in the background and, once a committed root is fully drained,
   records a ``.tierdown`` watermark next to the manifest. A replica
   becomes evictable only after ITS durable write succeeded, so at
   every instant every manifest-referenced byte exists in >= 1 tier —
   the crash matrix enumerates every boundary of this pipeline
   (``hottier.replicate`` / ``hottier.drain`` / ``hottier.tierdown``
   op hooks) and proves it.
4. **restore** — reads prefer the hot tier (fingerprint-verified per
   object; see tier.py) and fall back per-object to the durable tier
   when replicas are dead, missing, or corrupt; fallbacks are counted
   and surface in the flight report / ledger / doctor
   (``hot-tier-degraded``).

Drain modes: ``"background"`` (production — a daemon thread drains as
the take proceeds) and ``"manual"`` (the fault harness — tier-down runs
synchronously via :func:`drain_now`, keeping faultline's op stream
deterministic so crash points replay exactly).

The durable plugin the drainer writes through is resolved via
``url_to_storage_plugin`` with THIS module's wrap bypassed (thread-
local), so it still passes every other installed wrapper — faultline's
FaultPlugin in particular: injected faults and crash points strike the
tier-down writes exactly as they would a foreground write, under the
real retry policy.
"""

import asyncio
import json
import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from .. import telemetry
from ..coord import Coordinator, get_coordinator
from ..io_types import IOReq, emit_storage_op, io_payload
from ..storage_plugin import is_ref_location
from ..telemetry import metrics as _metric_names
from ..utils.env import env_float, env_int
from . import tier

logger = logging.getLogger(__name__)

K_ENV_VAR = "TPUSNAPSHOT_HOT_TIER_K"
_DEFAULT_K = 2
BYTES_ENV_VAR = "TPUSNAPSHOT_HOT_TIER_BYTES"
_DEFAULT_CAPACITY_BYTES = 1 << 30

# The tier-down watermark, recorded next to the manifest once every
# payload object of a committed take reached the durable tier. Dot-
# prefixed (control plane): always written through, never hot-tiered.
TIERDOWN_FNAME = ".tierdown"
_METADATA_FNAME = ".snapshot_metadata"

_DRAIN_MAX_ATTEMPTS = 3

# Thread-local bypass: the drainer resolves the DURABLE plugin through
# url_to_storage_plugin with the hot-tier wrap skipped (other wraps —
# faultline — still apply); see module docstring.
_BYPASS = threading.local()


def is_payload_path(path: str) -> bool:
    """Payload objects ride the hot tier; everything dot-prefixed
    (metadata, markers, telemetry, reports, ``.tierdown``), incremental
    back-link markers (``refs/``), and base references (``@base…``) are
    control plane: written through to the durable tier synchronously —
    they ARE the commit protocol and must obey its durability ordering."""
    return not (
        path.startswith(".")
        or path.startswith("refs/")
        or is_ref_location(path)
    )


class _RootState:
    """Per-snapshot-root drain bookkeeping."""

    def __init__(self) -> None:
        self.pending: Set[str] = set()  # payload paths not yet durable
        self.committed = False  # .snapshot_metadata observed
        self.tierdown_done = False
        self.drain_lost = 0  # objects whose every replica died pre-drain
        # Items that exhausted their drain attempts: still pending (their
        # hot replicas stay unevictable — the only copy), re-driven by
        # the next drain_now(). wait_drained() reports them truthfully.
        self.stranded: Set[str] = set()
        self.tierdown_attempts = 0
        self.tierdown_stranded = False


class HotTierRuntime:
    """One process's hot-tier brain: placement, stats, the drain queue."""

    def __init__(
        self,
        rank: int,
        world: int,
        k: int,
        capacity_bytes: int,
        drain: str = "background",
    ) -> None:
        if drain not in ("background", "manual"):
            raise ValueError(
                f'drain must be "background" or "manual"; got {drain!r}'
            )
        self.rank = rank
        self.world = max(1, world)
        self.k = max(1, min(k, self.world))
        self.capacity_bytes = capacity_bytes
        self.drain_mode = drain
        self.active = True
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue: Deque[Tuple[str, Optional[str], int]] = deque()
        self._roots: Dict[str, _RootState] = {}
        self._inflight = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.drain_error: Optional[BaseException] = None
        # Cumulative counters (stats_snapshot/delta power the per-restore
        # tier summary; concurrent operations smear, same contract as the
        # process-wide telemetry counters).
        self._stats: Dict[str, int] = {
            "hot_objects": 0,
            "hot_bytes": 0,
            "fallback_objects": 0,
            "fallback_bytes": 0,
            "replicas": 0,
            "write_through": 0,
            "drained_objects": 0,
            "drained_bytes": 0,
            "drain_lost": 0,
        }
        self._peer_failures: Dict[int, int] = {}
        self._reason_counts: Dict[str, int] = {}

    # ---------------------------------------------------------- placement

    def replica_hosts(self) -> List[int]:
        """This rank's replica set: itself plus the next k-1 hosts in
        ring order — deterministic from (rank, world, k) alone, the same
        information every peer derives from the coord rendezvous."""
        return [(self.rank + i) % self.world for i in range(self.k)]

    @staticmethod
    def _key(root: str, path: str) -> str:
        return f"{root.rstrip('/')}/{path}"

    # -------------------------------------------------------- write side

    def hot_put(self, root: str, path: str, payload: bytes) -> int:
        """Replicate one payload object into peer RAM; returns how many
        replicas were placed (0 = refused everywhere: caller degrades to
        durable write-through). Each replica placement is a storage-op
        boundary (``hottier.replicate``) so the crash-point enumerator
        can strike between replicas."""
        key = self._key(root, path)
        tag = tier.payload_tag(payload)
        placed = 0
        for host in self.replica_hosts():
            emit_storage_op("hottier.replicate", f"host{host}:{path}")
            try:
                if tier.put_replica(
                    key, host, payload, tag, root.rstrip("/"),
                    capacity_bytes=self.capacity_bytes,
                ):
                    placed += 1
            except tier.HostLostError:
                self._note_peer_failure(host, "dead")
        if placed == 0:
            # No replica landed: any stale replicas of an earlier object
            # at this key must not survive a write they no longer match.
            tier.forget_key(key)
        with self._lock:
            self._stats["replicas"] += placed
        return placed

    def note_write_through(self, nbytes: int) -> None:
        with self._lock:
            self._stats["write_through"] += 1
        telemetry.counter(_metric_names.HOT_TIER_WRITE_THROUGH).inc()

    def enqueue_drain(self, root: str, path: str) -> None:
        root = root.rstrip("/")
        with self._cond:
            state = self._roots.setdefault(root, _RootState())
            if path in state.pending:
                return  # retried write of the same object: already queued
            state.pending.add(path)
            self._queue.append((root, path, 0))
            self._cond.notify_all()
        if self.drain_mode == "background":
            self._ensure_thread()

    def on_commit(self, root: str) -> None:
        """The root's metadata document was written (the take's commit
        point). Once its pending set drains empty, the ``.tierdown``
        watermark goes down; a root that committed with nothing pending
        (all write-through, or drained already) gets a watermark-only
        queue item."""
        root = root.rstrip("/")
        with self._cond:
            state = self._roots.setdefault(root, _RootState())
            state.committed = True
            if not state.pending and not state.tierdown_done:
                self._queue.append((root, None, 0))
                self._cond.notify_all()
        if self.drain_mode == "background":
            self._ensure_thread()

    # --------------------------------------------------------- read side

    def hot_get(
        self, root: str, path: str, byte_range: Optional[tuple]
    ) -> Tuple[Optional[bytes], bool]:
        """``(payload, attempted)``: the object from the first healthy
        replica, fingerprint-verified — or ``(None, attempted)`` where
        ``attempted`` says whether the hot tier KNEW this object (and
        every replica failed: a genuine degraded fallback) vs. never saw
        it (a cold read that must not count as degradation)."""
        key = self._key(root, path)
        hosts = tier.replica_hosts_for(key)
        if not hosts:
            return None, False
        # Prefer the local host's replica (no network hop in production).
        ordered = sorted(hosts, key=lambda h: h != self.rank)
        for host in ordered:
            try:
                obj = tier.get_replica(key, host)
            except tier.HostLostError:
                self._note_peer_failure(host, "dead")
                continue
            except KeyError:
                self._note_peer_failure(host, "missing")
                continue
            if tier.payload_tag(obj.data) != obj.tag:
                # Corrupt replica: drop it so nothing reads it again.
                self._note_peer_failure(host, "corrupt")
                tier.drop_replica(key, host)
                continue
            data = obj.data
            if byte_range is not None:
                start, end = byte_range
                data = data[start:end]
            with self._lock:
                self._stats["hot_objects"] += 1
                self._stats["hot_bytes"] += len(data)
            telemetry.counter(_metric_names.HOT_TIER_READS, tier="hot").inc()
            telemetry.counter(
                _metric_names.HOT_TIER_READ_BYTES, tier="hot"
            ).inc(len(data))
            return data, True
        with self._lock:
            self._stats["fallback_objects"] += 1
        telemetry.counter(
            _metric_names.HOT_TIER_READS, tier="durable"
        ).inc()
        return None, True

    def note_fallback_bytes(self, nbytes: int) -> None:
        with self._lock:
            self._stats["fallback_bytes"] += nbytes
        telemetry.counter(
            _metric_names.HOT_TIER_READ_BYTES, tier="durable"
        ).inc(nbytes)

    def _note_peer_failure(self, host: int, reason: str) -> None:
        with self._lock:
            self._peer_failures[host] = self._peer_failures.get(host, 0) + 1
            self._reason_counts[reason] = (
                self._reason_counts.get(reason, 0) + 1
            )
        telemetry.counter(
            _metric_names.HOT_TIER_FALLBACKS, reason=reason
        ).inc()

    # -------------------------------------------------- delete/reconcile

    def forget_object(self, root: str, path: str) -> bool:
        """Drop every replica of one object and cancel its pending drain
        (a deleted object must never be resurrected into the durable
        tier by a later drain). True if the hot tier held it."""
        key = self._key(root, path)
        existed = tier.forget_key(key)
        root = root.rstrip("/")
        with self._cond:
            state = self._roots.get(root)
            if state is not None and path in state.pending:
                state.pending.discard(path)
                self._queue = deque(
                    item
                    for item in self._queue
                    if not (item[0] == root and item[1] == path)
                )
                existed = True
                self._cond.notify_all()
        return existed

    def forget_root(self, root: str) -> int:
        """Drop every buffered object of ``root`` and cancel its drains
        (``Snapshot.delete`` / prune). Returns objects dropped."""
        root = root.rstrip("/")
        dropped = 0
        for key in tier.keys_for_root(root):
            if tier.forget_key(key):
                dropped += 1
        with self._cond:
            self._roots.pop(root, None)
            self._queue = deque(
                item for item in self._queue if item[0] != root
            )
            self._cond.notify_all()
        return dropped

    def object_age_s(self, root: str, path: str) -> Optional[float]:
        return tier.key_age_s(self._key(root, path))

    def object_size_bytes(self, root: str, path: str) -> Optional[int]:
        return tier.key_size_bytes(self._key(root, path))

    # -------------------------------------------------------- drain side

    def _ensure_thread(self) -> None:
        with self._lock:
            if self.drain_error is not None:
                # A crashed drainer stays crashed (the fault model:
                # process death); wait_drained() reports it and only an
                # explicit reset_pending()/new runtime clears it.
                return
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._drain_loop, name="tpusnapshot-hottier-drain",
                daemon=True,
            )
            self._thread.start()

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(timeout=0.2)
                if self._stop and not self._queue:
                    return
                root, path, attempts = self._queue.popleft()
                self._inflight += 1
            try:
                self._drain_item(root, path, attempts)
            except Exception as e:
                # Per-item failures (e.g. a transient .tierdown write
                # error) must not kill the drainer — the item's own
                # requeue/leave-pending handling already ran; later
                # items (or drain_now) re-drive what's left.
                logger.warning(f"hot-tier drain item failed: {e!r}")
            except BaseException as e:  # a crashed drainer stays crashed
                self.drain_error = e
                logger.warning(f"hot-tier drain died: {e!r}")
                return  # inflight released by the finally below
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _requeue_stranded(self) -> None:
        """Move every stranded object/watermark back into the queue with
        fresh attempt budgets — drain_now()'s re-drive of work that
        exhausted its attempts (a backend outage that outlasted the
        retry layer)."""
        with self._cond:
            for root, state in self._roots.items():
                for path in sorted(state.stranded):
                    self._queue.append((root, path, 0))
                state.stranded.clear()
                if state.tierdown_stranded:
                    state.tierdown_stranded = False
                    state.tierdown_attempts = 0
                    self._queue.append((root, None, 0))
            self._cond.notify_all()

    def drain_now(self) -> None:
        """Synchronous tier-down of everything pending — including
        re-driving stranded items (manual mode and tests; also usable to
        force-flush a background drainer). Runs on the caller's thread
        so faultline's op stream stays deterministic; a SimulatedCrash
        propagates to the caller like any crash."""
        self._requeue_stranded()
        while True:
            with self._cond:
                if not self._queue:
                    return
                root, path, attempts = self._queue.popleft()
                self._inflight += 1
            try:
                self._drain_item(root, path, attempts)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _durable_plugin(self, root: str):
        from ..storage_plugin import url_to_storage_plugin

        _BYPASS.active = True
        try:
            return url_to_storage_plugin(root)
        finally:
            _BYPASS.active = False

    def _drain_item(
        self, root: str, path: Optional[str], attempts: int
    ) -> None:
        plugin = self._durable_plugin(root)
        try:
            if path is not None:
                self._drain_object(plugin, root, path, attempts)
            self._maybe_tierdown(plugin, root)
        finally:
            plugin.close()

    def _drain_object(
        self, plugin: Any, root: str, path: str, attempts: int
    ) -> None:
        key = self._key(root, path)
        data: Optional[bytes] = None
        for host in tier.replica_hosts_for(key) or []:
            try:
                obj = tier.get_replica(key, host)
            except (tier.HostLostError, KeyError):
                continue
            if tier.payload_tag(obj.data) == obj.tag:
                data = obj.data
                break
        if data is None:
            # Every replica died before tier-down: the bytes are gone.
            # The loss is counted and the pending entry retired — the
            # root can never tier down clean, and a restore of this
            # object will fail loudly at the durable tier (detect, not
            # silent corruption).
            logger.warning(
                f"hot-tier drain: every replica of {key} lost before "
                f"tier-down; the object was never persisted"
            )
            with self._cond:
                self._stats["drain_lost"] += 1
                state = self._roots.get(root)
                if state is not None:
                    state.pending.discard(path)
                    state.drain_lost += 1
            return
        emit_storage_op("hottier.drain", path)
        try:
            asyncio.run(plugin.write(IOReq(path=path, data=data)))
        except Exception as e:
            if attempts + 1 < _DRAIN_MAX_ATTEMPTS:
                with self._cond:
                    self._queue.append((root, path, attempts + 1))
                    self._cond.notify_all()
                logger.warning(
                    f"hot-tier drain of {key} failed "
                    f"(attempt {attempts + 1}): {e!r}; requeued"
                )
                return
            # Out of attempts: the object stays pending AND is marked
            # stranded — its hot replicas stay unevictable (the only
            # copy), wait_drained() reports the root un-flushed, and the
            # next drain_now() re-drives it; the root's .tierdown is
            # withheld, which is the truthful state.
            with self._cond:
                state = self._roots.get(root)
                if state is not None:
                    state.stranded.add(path)
                self._cond.notify_all()
            logger.warning(
                f"hot-tier drain of {key} failed permanently: {e!r}; "
                f"object remains hot-tier-only (re-driven by the next "
                f"drain_now; no .tierdown until it lands)"
            )
            return
        tier.mark_drained(key)
        with self._cond:
            self._stats["drained_objects"] += 1
            self._stats["drained_bytes"] += len(data)
            state = self._roots.get(root)
            if state is not None:
                state.pending.discard(path)
        telemetry.counter(_metric_names.HOT_TIER_DRAINED_BYTES).inc(
            len(data)
        )

    def _maybe_tierdown(self, plugin: Any, root: str) -> None:
        with self._cond:
            state = self._roots.get(root)
            ready = (
                state is not None
                and state.committed
                and not state.pending
                and not state.tierdown_done
                and state.drain_lost == 0
            )
            if not ready:
                return
        emit_storage_op("hottier.tierdown", TIERDOWN_FNAME)
        doc = {
            "format_version": 1,
            "drained_objects": self._stats["drained_objects"],
            "ts_epoch_s": round(time.time(), 3),
        }
        try:
            asyncio.run(
                plugin.write(
                    IOReq(
                        path=TIERDOWN_FNAME,
                        data=json.dumps(doc, sort_keys=True).encode("utf-8"),
                    )
                )
            )
        except Exception as e:
            # A failed watermark write must leave a re-drive trigger: the
            # root is fully drained, so no object item will ever call
            # back here — requeue the watermark-only sentinel (bounded
            # attempts, then stranded for the next drain_now()).
            with self._cond:
                state = self._roots.get(root)
                if state is not None:
                    state.tierdown_attempts += 1
                    if state.tierdown_attempts < _DRAIN_MAX_ATTEMPTS:
                        self._queue.append((root, None, 0))
                    else:
                        state.tierdown_stranded = True
                self._cond.notify_all()
            logger.warning(
                f"hot-tier .tierdown write for {root} failed: {e!r}; "
                f"will re-drive"
            )
            return
        with self._cond:
            state = self._roots.get(root)
            if state is not None:
                state.tierdown_done = True
            self._cond.notify_all()

    def wait_drained(self, timeout_s: float = 120.0) -> bool:
        """Block until the drain queue is empty and nothing is in
        flight; True only on a genuinely clean flush — False on timeout,
        a dead drainer, or STRANDED work (objects/watermarks that
        exhausted their attempts and await a drain_now() re-drive):
        claiming success while committed bytes are still hot-tier-only
        would let a caller tear the tier down over the only copy."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._queue or self._inflight:
                if self.drain_error is not None:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(0.2, remaining))
            return not any(
                s.stranded or s.tierdown_stranded
                for s in self._roots.values()
            )

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=10.0)

    def root_state(self, root: str) -> Optional[_RootState]:
        with self._lock:
            return self._roots.get(root.rstrip("/"))

    # ------------------------------------------------------------- stats

    def stats_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            snap: Dict[str, Any] = dict(self._stats)
            snap["peers"] = dict(self._peer_failures)
            snap["reasons"] = dict(self._reason_counts)
            return snap


# ---------------------------------------------------------- process-global

_RUNTIME: Optional[HotTierRuntime] = None
_PREV_HOOK: Any = None
_ENABLE_LOCK = threading.Lock()


def runtime() -> Optional[HotTierRuntime]:
    return _RUNTIME


def is_enabled() -> bool:
    return _RUNTIME is not None and _RUNTIME.active


def enable_hot_tier(
    rank: Optional[int] = None,
    world: Optional[int] = None,
    k: Optional[int] = None,
    capacity_bytes: Optional[int] = None,
    drain: str = "background",
    coord: Optional[Coordinator] = None,
) -> HotTierRuntime:
    """Turn the hot tier on process-wide: every storage plugin resolved
    from here on is wrapped in a :class:`~.plugin.TieredPlugin` (the
    same ``set_plugin_wrap_hook`` seam faultline uses; hooks chain, so
    enabling inside a faultline ``inject`` block — or vice versa —
    composes). ``rank``/``world`` default to the coord layer's identity
    (``jax.distributed`` on a pod, single-host otherwise); ``k`` and
    ``capacity_bytes`` default to ``TPUSNAPSHOT_HOT_TIER_K`` (2) and
    ``TPUSNAPSHOT_HOT_TIER_BYTES`` (1 GiB per host)."""
    global _RUNTIME, _PREV_HOOK
    from .. import storage_plugin as _sp
    from .plugin import TieredPlugin

    with _ENABLE_LOCK:
        if _RUNTIME is not None:
            raise RuntimeError(
                "hot tier is already enabled; disable_hot_tier() first"
            )
        if rank is None or world is None:
            coordinator = get_coordinator(coord)
            rank = coordinator.get_rank() if rank is None else rank
            world = (
                coordinator.get_world_size() if world is None else world
            )
        rt = HotTierRuntime(
            rank=rank,
            world=world,
            k=k if k is not None else env_int(K_ENV_VAR, _DEFAULT_K),
            capacity_bytes=(
                capacity_bytes
                if capacity_bytes is not None
                else env_int(BYTES_ENV_VAR, _DEFAULT_CAPACITY_BYTES)
            ),
            drain=drain,
        )

        def _hook(plugin, url):
            base = (
                _PREV_HOOK(plugin, url) if _PREV_HOOK is not None else plugin
            )
            if getattr(_BYPASS, "active", False):
                return base  # drainer: durable tier, faults still apply
            return TieredPlugin(base, rt, url)

        _PREV_HOOK = _sp.set_plugin_wrap_hook(_hook)
        _RUNTIME = rt
        return rt


def disable_hot_tier(flush: bool = True, timeout_s: float = 120.0) -> None:
    """Uninstall the hot tier (LIFO with any other wrap-hook users, like
    faultline's ``inject``). ``flush=True`` drains everything pending
    first so no committed bytes are stranded hot-only; plugins already
    resolved keep their wrapper but it deactivates (pass-through)."""
    global _RUNTIME, _PREV_HOOK
    from .. import storage_plugin as _sp

    with _ENABLE_LOCK:
        rt = _RUNTIME
        if rt is None:
            return
        if flush:
            if rt.drain_mode == "manual":
                rt.drain_now()
            else:
                rt._ensure_thread()
                if not rt.wait_drained(timeout_s=timeout_s):
                    logger.warning(
                        "disable_hot_tier: drain did not flush within "
                        f"{timeout_s:g}s; undrained objects remain "
                        f"hot-tier-only"
                    )
        rt.stop()
        rt.active = False
        _sp.set_plugin_wrap_hook(_PREV_HOOK)
        _PREV_HOOK = None
        _RUNTIME = None


@contextmanager
def hot_tier(**kwargs):
    """``with hot_tier(world=4, k=2): ...`` — enable/disable scoped."""
    rt = enable_hot_tier(**kwargs)
    try:
        yield rt
    finally:
        disable_hot_tier()


# ------------------------------------------------------- module-level API


def drain_now() -> None:
    rt = _RUNTIME
    if rt is not None:
        rt.drain_now()


def wait_drained(timeout_s: float = 120.0) -> bool:
    rt = _RUNTIME
    return rt.wait_drained(timeout_s=timeout_s) if rt is not None else True


def reset_pending() -> None:
    """Drop ALL drain bookkeeping (queue + per-root state + a dead
    drainer's error latch) without touching the stores — the fault
    harness's fresh-context hook: each crash-point replay starts from an
    empty op-relevant queue so the enumerated op stream is identical
    across replays."""
    rt = _RUNTIME
    if rt is None:
        return
    with rt._cond:
        rt._queue.clear()
        rt._roots.clear()
        rt.drain_error = None
        rt._cond.notify_all()


def forget_root(root: str) -> int:
    """Drop every hot replica of ``root`` and cancel its pending drains
    (``Snapshot.delete``/prune hook). Works with the runtime disabled
    too — registry-level state must not outlive its snapshot."""
    rt = _RUNTIME
    if rt is not None:
        return rt.forget_root(root)
    dropped = 0
    for key in tier.keys_for_root(root):
        if tier.forget_key(key):
            dropped += 1
    return dropped


def reconcile_hot_tier(
    base_path: str,
    keep_roots: Set[str],
    min_age_s: Optional[float] = None,
) -> List[str]:
    """Sweep orphaned hot-tier buffers under ``base_path``: roots not in
    ``keep_roots`` (the manager passes every step with committed
    metadata OR a step marker — so a committed-but-not-yet-drained
    take's replicas are structurally unreachable by this sweep) whose
    buffers have aged past the ``TPUSNAPSHOT_SWEEP_MIN_AGE_S`` guard,
    the same knob and fail-closed posture as every storage sweep.
    Returns the roots whose buffers were dropped."""
    if min_age_s is None:
        min_age_s = env_float("TPUSNAPSHOT_SWEEP_MIN_AGE_S", 3600.0)
    base = base_path.rstrip("/")
    keep = {r.rstrip("/") for r in keep_roots}
    dropped: List[str] = []
    for root, _nbytes in sorted(tier.buffered_roots().items()):
        if not (root == base or root.startswith(base + "/")):
            continue
        if root in keep:
            continue
        if min_age_s > 0:
            ages = [
                tier.key_age_s(key) for key in tier.keys_for_root(root)
            ]
            known = [a for a in ages if a is not None]
            # Fail closed: unknown age (or any young object) spares the
            # whole root — it may be an in-flight take's buffers.
            if not known or min(known) < min_age_s:
                continue
        forget_root(root)
        dropped.append(root)
    return dropped


def restore_stats_begin() -> Optional[Dict[str, Any]]:
    """Token for per-restore tier attribution (None = tier disabled)."""
    rt = _RUNTIME
    return rt.stats_snapshot() if rt is not None and rt.active else None


def restore_stats_collect(
    token: Optional[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """The tier summary for the restore since ``token``: hot/fallback
    object+byte counts, the peers that failed, and why — the dict the
    flight report carries as ``tier`` and the ``hot-tier-degraded``
    doctor rule reads. None when the tier is off or saw no traffic."""
    rt = _RUNTIME
    if token is None or rt is None:
        return None
    now = rt.stats_snapshot()

    def _d(field: str) -> int:
        return int(now.get(field, 0)) - int(token.get(field, 0))

    summary = {
        "hot_objects": _d("hot_objects"),
        "hot_bytes": _d("hot_bytes"),
        "fallback_objects": _d("fallback_objects"),
        "fallback_bytes": _d("fallback_bytes"),
    }
    if not any(summary.values()):
        return None
    old_peers = token.get("peers") or {}
    summary["degraded_peers"] = sorted(
        h
        for h, c in (now.get("peers") or {}).items()
        if c > int(old_peers.get(h, 0))
    )
    old_reasons = token.get("reasons") or {}
    reasons = {
        r: c - int(old_reasons.get(r, 0))
        for r, c in (now.get("reasons") or {}).items()
        if c > int(old_reasons.get(r, 0))
    }
    if reasons:
        summary["fallback_reasons"] = reasons
    return summary
