"""snapmend: the hot tier's self-healing repair plane.

snapwire made ack-at-k real across processes, but the membership was
static: peers were reached by an address book, and one SIGKILL
permanently degraded every affected object to k-1 (or to write-through)
until the run ended. A disaggregated fleet only works if it tolerates
*continuous* worker churn — losses detected, capacity restored, and the
replication invariant **repaired**, not merely survived. This module is
that loop. Three duties, one deterministic ``tick()``:

1. **Peer supervision (generation-stamped membership).** Every
   registered remote peer is probed through the existing transport ping
   each tick. A peer whose subprocess exited, or whose pings have
   failed for ``TPUSNAPSHOT_REPAIR_DEADLINE_S``, is classified **lost**:
   its client handle is condemned (latched dead, connections aborted —
   the process itself may be hung, partitioned, or on another machine
   and is never assumed killable) and the client-side shadow index is
   invalidated for the host. Membership is *generation-stamped*: a
   replacement peer registers one generation up, and a stale
   predecessor that wakes later (SIGCONT after its id moved on) is
   refused by the ping's generation echo — a respawned peer holds an
   empty store and is recognized as *new*, never trusted to hold its
   predecessor's replicas. Peers latched into the transport's down
   cooldown are also re-probed here in the background, so a recovered
   host rejoins within one repair interval instead of waiting for the
   next foreground push to trip over it.

2. **Auto-restart.** A lost peer that this process spawned
   (``spawn_peer``) is respawned as a fresh subprocess at the next
   generation (``TPUSNAPSHOT_REPAIR_AUTO_RESTART``, default on), and
   the hot tier's address book is hot-reloaded: the host's
   ``TPUSNAPSHOT_HOT_TIER_ADDRS`` entry and its port-file (when one was
   configured) are rewritten in place, so rejoin needs no process
   restart anywhere.

3. **Anti-entropy repair + deadline-bounded escalation.** The loop
   scans the runtime's committed, undrained objects and counts *live*
   replicas (``tier.live_replicas`` — current-generation state only,
   never the rendezvous claim). An object below k is re-replicated
   from a surviving fingerprint-verified replica onto ring/spare hosts
   through the existing delta/codec push path, honoring every
   hard-won invariant: **tag-strict** (a source replica must carry the
   path's current tag — superseded bytes are never repaired, and a
   re-write racing the repair drops the stale placements),
   **forget-root latch** (a root deleted mid-repair has the placements
   undone — a deleted snapshot's objects are never resurrected), and
   **drain bookkeeping** (an object that tiered down mid-repair gets
   its repaired replicas marked drained/evictable). An object that
   cannot reach k within ``TPUSNAPSHOT_REPAIR_DEADLINE_S`` of first
   being observed under-replicated **escalates** to the existing
   synchronous durable write-through ladder (the drain item runs
   inline under the same serialization, latch re-checks, and undo
   paths as the background drainer), so at-risk bytes are a
   deadline-bounded quantity, not an unbounded exposure —
   ``tpusnapshot_hot_tier_underreplicated_bytes`` returns to 0.

Modes mirror the drainer: ``"background"`` runs ``tick()`` on a daemon
thread every ``TPUSNAPSHOT_REPAIR_INTERVAL_S``; ``"manual"`` leaves the
tick to the caller (the fault harness — repair op boundaries
``hottier.repair`` enter the deterministic crash-point stream only when
the test drives them). A read that fell back to the durable tier nudges
the plane (``request_scan``) so repair starts within one tick of the
first degraded read, not the next full interval.
"""

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import telemetry, tracing
from ..io_types import emit_storage_op
from ..telemetry import metrics as _metric_names
from ..utils.env import env_float, env_int
from . import tier

logger = logging.getLogger(__name__)

MODE_ENV_VAR = "TPUSNAPSHOT_REPAIR_MODE"
INTERVAL_ENV_VAR = "TPUSNAPSHOT_REPAIR_INTERVAL_S"
_DEFAULT_INTERVAL_S = 2.0
DEADLINE_ENV_VAR = "TPUSNAPSHOT_REPAIR_DEADLINE_S"
_DEFAULT_DEADLINE_S = 30.0
AUTO_RESTART_ENV_VAR = "TPUSNAPSHOT_REPAIR_AUTO_RESTART"

# The attempt index an escalation passes to _drain_item: past the
# drain's own attempt budget, so a failed escalation STRANDS the object
# (pending, replicas pinned, stranded-drains fires) instead of churning
# the drain queue from two sides — the next tick re-escalates.
_ESCALATE_ATTEMPT = 10**6

# How many consecutive ticks an escalation may find NO matching source
# replica before the loss verdict is made official. A foreground
# re-write can be mid-flight between replacing the replicas (hot_put)
# and updating the drain bookkeeping — one tick's "no replica" is
# stale bookkeeping, not loss; three full intervals apart is not.
_ESCALATE_NOREPLICA_TICKS = 3

# Condemned hung peers are kept unsignalled (the process may be merely
# paused, or unreachable rather than dead) so close() can reap spawned
# ones — but under continuous churn the handles, and the hung
# subprocesses pinning their replica RAM, must not accumulate for the
# life of the run. Beyond this many, the oldest are reaped eagerly.
_MAX_CONDEMNED = 8


def repair_interval_s() -> float:
    return env_float(INTERVAL_ENV_VAR, _DEFAULT_INTERVAL_S)


def repair_deadline_s() -> float:
    return env_float(DEADLINE_ENV_VAR, _DEFAULT_DEADLINE_S)


def _auto_restart_enabled() -> bool:
    return env_int(AUTO_RESTART_ENV_VAR, 1) != 0


def _update_addrs_env(host_id: int, addr: str) -> None:
    """Hot-reload the address book: rewrite (or append) the host's
    ``TPUSNAPSHOT_HOT_TIER_ADDRS`` entry in THIS process's environment
    so any later ``enable_hot_tier``/``register_peers_from_env`` sees
    the respawned peer — no process restart needed. A job that never
    set the address book keeps not having one."""
    from .transport import ADDRS_ENV_VAR, parse_addrs_spec

    spec = (os.environ.get(ADDRS_ENV_VAR) or "").strip()
    if not spec:
        return
    entries = parse_addrs_spec(spec)
    entries[str(host_id)] = addr
    os.environ[ADDRS_ENV_VAR] = ",".join(
        f"{h}={a}"
        for h, a in sorted(
            entries.items(), key=lambda kv: int(kv[0]) if kv[0].isdigit() else 1 << 30
        )
    )


# Serializes respawns of any host: a faultline flap revival (op-stream
# thread) and the background plane's _restart can race on the same
# lost host — without the lock both spawn a subprocess and the losing
# registration's process handle is dropped untracked (a leak no reap
# ever finds). Under the lock the second caller sees the first's
# replacement alive and returns it instead.
_RESPAWN_LOCK = threading.Lock()


def respawn_host(host_id: int) -> Optional[Any]:
    """Replace a lost wire-backed host with a FRESH peer subprocess one
    membership generation up, re-register it, and hot-reload the
    address book (env entry + port-file). The new process starts with
    an empty store — the repair loop re-replicates what belongs there.
    Returns the new RemotePeer, or None when the host id is not
    wire-backed (in-process hosts revive via ``tier.revive_host``).
    Idempotent under races: when a concurrent caller already respawned
    the host, its live replacement is returned rather than spawning a
    second (orphaned) process."""
    from .peer import spawn_peer

    with _RESPAWN_LOCK:
        return _respawn_host_locked(host_id, spawn_peer)


def _respawn_host_locked(host_id: int, spawn_peer: Any) -> Optional[Any]:
    old = tier.remote_host(host_id)
    if old is None:
        return None
    if getattr(old, "alive", False):
        # A racing caller's replacement is already up: callers only
        # respawn LOST hosts, so an alive registered peer IS the
        # replacement.
        return old
    capacity = getattr(old, "capacity_bytes", None)
    port_file = getattr(old, "spawn_port_file", None)
    gen = tier.host_generation(host_id) + 1
    _proc, addr, peer = spawn_peer(
        host_id,
        capacity_bytes=capacity,
        register=True,
        generation=gen,
        port_file=port_file,
    )
    _update_addrs_env(host_id, addr)
    logger.warning(
        f"snapmend: host {host_id} respawned as generation {gen} at "
        f"{addr}"
    )
    return peer


class _HostView:
    """One host's membership row: what the supervisor believes."""

    def __init__(self, host_id: int, peer: Any) -> None:
        self.host_id = host_id
        # The peer OBJECT this row describes: a replacement registered
        # over the host id (respawn, or an external supervisor's
        # connect_peer — possibly at the same generation number) is a
        # different object and gets a fresh row, so a stale LOST view
        # can never outlive the peer it judged.
        self.peer = peer
        self.generation = int(getattr(peer, "generation", 0))
        self.addr = getattr(peer, "addr_str", None)
        self.restartable = getattr(peer, "process", None) is not None
        self.lost = False
        self.failed_since: Optional[float] = None
        self.last_ok_t: Optional[float] = None

    def as_dict(self, now: float) -> Dict[str, Any]:
        return {
            "generation": self.generation,
            "addr": self.addr,
            "alive": not self.lost,
            "restartable": self.restartable,
            "failing_for_s": (
                round(now - self.failed_since, 3)
                if self.failed_since is not None
                else None
            ),
            "last_ok_age_s": (
                round(now - self.last_ok_t, 3)
                if self.last_ok_t is not None
                else None
            ),
        }


class RepairPlane:
    """One process's repair brain: supervision + anti-entropy loop over
    its :class:`~.runtime.HotTierRuntime`."""

    def __init__(self, runtime: Any, mode: str = "background") -> None:
        if mode not in ("background", "manual"):
            raise ValueError(
                f'repair mode must be "background" or "manual"; got {mode!r}'
            )
        self._rt = runtime
        self.mode = mode
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        # One tick at a time: a manual tick and the background thread
        # (or two callers) must not interleave repair placements.
        self._tick_lock = threading.Lock()
        self._views: Dict[int, _HostView] = {}
        # Lost peers we condemned but could not (or must not) signal:
        # their handles are kept so close() can reap spawned processes.
        self._condemned: List[Any] = []
        # key -> monotonic time the object was FIRST observed below k;
        # the escalation deadline and the time-to-k histogram both
        # measure from here.
        self._under_since: Dict[str, float] = {}
        # key -> consecutive escalation ticks that found NO matching
        # source replica (the loss-verdict debounce — see _escalate).
        self._esc_noreplica: Dict[str, int] = {}
        self._under_bytes = 0
        self._under_objects = 0
        self._oldest_under_age_s: Optional[float] = None
        self._stats: Dict[str, int] = {
            "objects_repaired": 0,
            "bytes_repaired": 0,
            "repairs_failed": 0,
            "escalation_attempts": 0,
            "escalated_write_throughs": 0,
            "peer_restarts": 0,
            "hosts_lost": 0,
            "reprobes": 0,
        }
        self._last_tick_t: Optional[float] = None
        self.repair_error: Optional[BaseException] = None
        self._scan_requested = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self.mode != "background":
            return
        with self._lock:
            if self.repair_error is not None:
                return  # a crashed plane stays crashed (process death)
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop,
                name="tpusnapshot-hottier-repair",
                daemon=True,
            )
            self._thread.start()

    def reset_for_replay(self) -> None:
        """Crash-replay determinism hook (``runtime.reset_pending``):
        every replay starts with a fresh under-replication clock and a
        live plane. Taken under ``_tick_lock`` so a concurrently
        running tick cannot interleave with the clear; a background
        loop that died on a SimulatedCrash is restarted (a replayed
        process is a NEW process — its plane runs again)."""
        with self._tick_lock:
            with self._lock:
                self._under_since.clear()
                self._esc_noreplica.clear()
                self.repair_error = None
        self.start()  # no-op in manual mode / when already running

    def request_scan(self) -> None:
        """Wake the background loop early (a degraded read just proved
        a replica is gone — start repairing within one tick, not one
        full interval). Latched, not just notified: a nudge landing
        while a tick is IN PROGRESS (no thread waiting on the
        condition) must trigger the next tick immediately, not be
        silently dropped back to a full-interval wait. No-op in manual
        mode."""
        with self._wake:
            self._scan_requested = True
            self._wake.notify_all()

    def close(self, kill_condemned: bool = True) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=10.0)
        if kill_condemned:
            with self._lock:
                condemned, self._condemned = self._condemned, []
            for peer in condemned:
                try:
                    peer.kill()
                except Exception as e:
                    logger.warning(
                        f"snapmend: condemned-peer reap failed: {e!r}"
                    )

    def _loop(self) -> None:
        while True:
            with self._wake:
                if self._stop:
                    return
                if not self._scan_requested:
                    self._wake.wait(timeout=repair_interval_s())
                self._scan_requested = False
                if self._stop:
                    return
            try:
                self.tick()
            except Exception as e:
                # A failing tick must not kill the plane: supervision
                # retries next interval (transient probe/storage
                # errors are its weather).
                logger.warning(f"snapmend tick failed: {e!r}")
            except BaseException as e:
                # A crash (SimulatedCrash) rips the plane dead, like
                # the drainer: a dead process does not keep repairing.
                self.repair_error = e
                logger.warning(f"snapmend repair plane died: {e!r}")
                return

    # ----------------------------------------------------------------- tick

    def tick(self) -> Dict[str, Any]:
        """One synchronous supervise→restart→repair pass. Deterministic
        given the op stream (the fault harness drives it in manual
        mode); returns a summary of what this pass did."""
        with self._tick_lock:
            lost = self._supervise()
            restarted = self._restart(lost)
            summary = self._repair_pass()
            summary["hosts_lost"] = lost
            summary["peer_restarts"] = restarted
            with self._lock:
                self._last_tick_t = time.monotonic()
            return summary

    # ---------------------------------------------------------- supervision

    def _supervise(self) -> List[int]:
        """Probe every registered remote peer; classify the dead and the
        deadline-hung as LOST (condemn + shadow invalidation). Returns
        the host ids newly lost this tick."""
        from .transport import DEADLINE_ENV_VAR, _DEFAULT_DEADLINE_S

        now = time.monotonic()
        deadline = repair_deadline_s()
        # Probes run serially under the tick lock: bound each one below
        # the full wire RPC deadline so one hung (SIGSTOP'd) peer can't
        # stall the whole tick — and every other host's repair — for
        # 5s per interval until it is classified.
        probe_deadline = max(
            0.5,
            min(
                env_float(DEADLINE_ENV_VAR, _DEFAULT_DEADLINE_S),
                repair_interval_s(),
            ),
        )
        newly_lost: List[int] = []
        remotes = tier.remote_hosts()
        with self._lock:
            # Prune views of hosts that were UNREGISTERED (condemned
            # hosts stay registered, so lost-host views survive): a
            # stale view would report a nonexistent host in the
            # membership block forever and feed _restart a candidate
            # whose respawn can never succeed.
            for host_id in [h for h in self._views if h not in remotes]:
                del self._views[host_id]
        for host_id, peer in sorted(remotes.items()):
            with self._lock:
                view = self._views.get(host_id)
                if view is None or peer is not view.peer:
                    view = _HostView(host_id, peer)
                    self._views[host_id] = view
            if not getattr(peer, "alive", False):
                # Already latched dead (kill_host / a prior condemn):
                # membership reflects it; restart may still apply.
                if not view.lost:
                    view.lost = True
                    with self._lock:
                        self._stats["hosts_lost"] += 1
                    newly_lost.append(host_id)
                continue
            proc = getattr(peer, "process", None)
            if proc is not None and proc.poll() is not None:
                # The subprocess exited — the RAM is gone with it; no
                # deadline needed to know.
                self._declare_lost(host_id, peer, view, reason="exited")
                newly_lost.append(host_id)
                continue
            # The existing transport ping IS the liveness probe. It
            # doubles as the down-cooldown background re-probe: probe()
            # bypasses the cooldown gate and clears it on success, so a
            # recovered peer rejoins within one repair interval instead
            # of waiting for the next foreground push to trip over it.
            was_down = bool(getattr(peer, "in_cooldown", False))
            ok = False
            try:
                ok = bool(peer.probe(deadline_s=probe_deadline))
            except Exception as e:
                # A failed probe IS the signal: the deadline clock below
                # acts on it. Log the cause for the ops trail.
                logger.debug(
                    "snapmend: probe of host %d failed: %r", host_id, e
                )
                ok = False
            if ok:
                view.failed_since = None
                view.last_ok_t = time.monotonic()
                if was_down:
                    with self._lock:
                        self._stats["reprobes"] += 1
                continue
            if view.failed_since is None:
                view.failed_since = now
                continue
            if now - view.failed_since >= deadline:
                # Hung-not-dead (SIGSTOP, partition): past the repair
                # deadline the peer is LOST whether or not its process
                # still exists somewhere.
                self._declare_lost(
                    host_id, peer, view, reason="probe deadline"
                )
                newly_lost.append(host_id)
        return newly_lost

    def _declare_lost(
        self, host_id: int, peer: Any, view: _HostView, reason: str
    ) -> None:
        logger.warning(
            f"snapmend: host {host_id} (gen {view.generation}) classified "
            f"LOST ({reason}); condemning and invalidating its shadow"
        )
        # A declared host loss is postmortem time: flush this process's
        # flight recorder so the victim's last RPCs survive on disk.
        try:
            from .. import wiretap

            wiretap.note_degrade(
                "host_lost", peer=getattr(peer, "addr_str", None)
            )
        except Exception:  # pragma: no cover - defensive
            logger.debug("snapmend: blackbox dump failed", exc_info=True)
        # Latch the JUDGED peer object directly, and clear the host's
        # shadow only while that object is still the registered one
        # (only_if): a replacement registered mid-tick must never be
        # condemned on its predecessor's probe failures.
        condemn = getattr(peer, "condemn", None)
        if condemn is not None:
            condemn()
        tier.condemn_host(host_id, only_if=peer)
        view.lost = True
        reap: List[Any] = []
        with self._lock:
            self._stats["hosts_lost"] += 1
            if getattr(peer, "process", None) is not None:
                self._condemned.append(peer)
                while len(self._condemned) > _MAX_CONDEMNED:
                    reap.append(self._condemned.pop(0))
        for old in reap:
            # Bound the churn leak: beyond the cap the oldest condemned
            # hung subprocesses (each pinning its replica RAM) are
            # reaped now instead of at close().
            try:
                old.kill()
            except Exception as e:
                logger.warning(
                    f"snapmend: condemned-peer reap failed: {e!r}"
                )

    def _restart(self, lost_hosts: List[int]) -> int:
        """Respawn lost hosts this process spawned (auto-restart);
        non-restartable hosts (remote machines from the address book)
        stay lost until an external supervisor replaces them — repair
        re-replicates around them either way. Candidates are EVERY
        still-lost restartable view, not just this tick's losses: a
        respawn that failed (spawn timeout, transient fork error) is
        retried next tick instead of forfeiting the host for the run."""
        if not _auto_restart_enabled():
            return 0
        with self._lock:
            candidates = sorted(
                set(lost_hosts)
                | {
                    h
                    for h, v in self._views.items()
                    if v.lost and v.restartable
                }
            )
        restarted = 0
        for host_id in candidates:
            view = self._views.get(host_id)
            if view is None or not view.restartable or not view.lost:
                continue
            peer = tier.remote_host(host_id)
            if peer is not None and getattr(peer, "alive", False):
                continue  # a replacement already took the id over
            try:
                peer = respawn_host(host_id)
            except Exception as e:
                logger.warning(
                    f"snapmend: respawn of host {host_id} failed: {e!r}"
                )
                continue
            if peer is None:
                continue
            with self._lock:
                self._views[host_id] = _HostView(host_id, peer)
                self._views[host_id].last_ok_t = time.monotonic()
                self._stats["peer_restarts"] += 1
            restarted += 1
        return restarted

    # --------------------------------------------------------------- repair

    def _scan_targets(self) -> List[Dict[str, Any]]:
        """Committed, undrained objects (the at-risk set) snapshotted
        under the runtime lock — the repair work list."""
        rt = self._rt
        targets: List[Dict[str, Any]] = []
        with rt._cond:
            for root, state in sorted(rt._roots.items()):
                if not state.committed or root in rt._forgotten:
                    continue
                for path in sorted(state.pending):
                    targets.append(
                        {
                            "root": root,
                            "path": path,
                            "tag": state.tags.get(path),
                            "nbytes": state.sizes.get(path),
                        }
                    )
        return targets

    def _repair_pass(self) -> Dict[str, Any]:
        rt = self._rt
        now = time.monotonic()
        deadline = repair_deadline_s()
        with self._lock:
            attempts0 = self._stats["escalation_attempts"]
        repaired = 0
        escalated = 0
        failed = 0
        remaining_bytes = 0
        remaining_objects = 0
        oldest_age: Optional[float] = None
        live_keys = set()
        by_root: Dict[str, Dict[str, int]] = {}
        for t in self._scan_targets():
            key = rt._key(t["root"], t["path"])
            live_keys.add(key)
            live = tier.live_replicas(key, t["tag"])
            if len(live) >= rt.k:
                self._under_since.pop(key, None)
                # A recovered object also resets the loss-verdict
                # debounce: stale misses from an earlier incident must
                # not let the NEXT incident's first no-replica tick
                # jump straight to the drain's loss budget.
                self._esc_noreplica.pop(key, None)
                continue
            first = self._under_since.setdefault(key, now)
            rec = by_root.setdefault(
                t["root"],
                {
                    "objects": 0,
                    "bytes": 0,
                    "failed": 0,
                    "escalated": 0,
                    "remaining": 0,
                },
            )
            fixed = False
            if now - first >= deadline:
                # Past the at-risk deadline: stop waiting for peers
                # and make the bytes durable NOW via the existing
                # synchronous write-through ladder. An object with
                # ZERO surviving replicas escalates too — the drain
                # item owns the loss verdict (after the phantom-loss
                # guard below), and only that verdict can retire the
                # obligation; silently skipping it would leave the
                # worst state (unrecoverable committed bytes) the one
                # state that never goes critical.
                with self._lock:
                    self._stats["escalation_attempts"] += 1
                fixed, wrote = self._escalate(
                    t["root"], t["path"], t["tag"]
                )
                if wrote:
                    # Count only escalations that actually RAN the
                    # drain item (a durable write attempt or the loss
                    # verdict) — debounce deferrals and drainer-owned
                    # no-ops are attempts, not write-throughs, and
                    # inflating this count misreports the ledger and
                    # the ops view.
                    escalated += 1
                    rec["escalated"] += 1
                    with self._lock:
                        self._stats["escalated_write_throughs"] += 1
                    telemetry.counter(
                        _metric_names.HOT_TIER_REPAIR_ESCALATIONS
                    ).inc()
            else:
                outcome = self._repair_object(
                    t["root"], t["path"], t["tag"], live
                )
                if outcome is None:
                    failed += 1
                    rec["failed"] += 1
                else:
                    placed_bytes, reached_k = outcome
                    if placed_bytes:
                        repaired += 1
                        rec["objects"] += 1
                        rec["bytes"] += placed_bytes
                    fixed = reached_k
                    if reached_k:
                        telemetry.histogram(
                            _metric_names.HOT_TIER_REPAIR_TIME_TO_K
                        ).observe(max(0.0, time.monotonic() - first))
            if fixed:
                self._under_since.pop(key, None)
                self._esc_noreplica.pop(key, None)
            else:
                remaining_objects += 1
                remaining_bytes += int(t["nbytes"] or 0)
                rec["remaining"] += int(t["nbytes"] or 0)
                age = time.monotonic() - first
                if oldest_age is None or age > oldest_age:
                    oldest_age = age
        # Objects that drained/vanished since last tick must not pin a
        # stale under-replication clock (or loss-verdict debounce).
        for key in [k for k in self._under_since if k not in live_keys]:
            del self._under_since[key]
        for key in [k for k in self._esc_noreplica if k not in live_keys]:
            del self._esc_noreplica[key]
        with self._lock:
            self._stats["repairs_failed"] += failed
            self._under_bytes = remaining_bytes
            self._under_objects = remaining_objects
            self._oldest_under_age_s = oldest_age
        telemetry.gauge(_metric_names.HOT_TIER_UNDERREPLICATED_BYTES).set(
            float(remaining_bytes)
        )
        if repaired or escalated:
            self._append_repair_ledger(by_root)
        with self._lock:
            attempts = self._stats["escalation_attempts"] - attempts0
        return {
            "objects_repaired": repaired,
            "escalation_attempts": attempts,
            "escalated_write_throughs": escalated,
            "repairs_failed": failed,
            "underreplicated_objects": remaining_objects,
            "underreplicated_bytes": remaining_bytes,
        }

    def _repair_object(
        self,
        root: str,
        path: str,
        tag: Optional[str],
        live: List[int],
    ) -> Optional[tuple]:
        """Re-replicate one under-replicated object from a surviving
        verified replica. Returns ``(bytes_placed, reached_k)`` or None
        when no usable source replica survives (the drain loop owns the
        loss verdict)."""
        rt = self._rt
        key = rt._key(root, path)
        data: Optional[bytes] = None
        src_tag: Optional[str] = tag
        unusable = set()
        for host in live:
            try:
                obj = tier.get_replica(key, host)
            except (tier.HostLostError, KeyError):
                unusable.add(host)
                continue
            if tag is not None and obj.tag != tag:
                unusable.add(host)
                continue  # tag-strict: never repair superseded bytes
            if tier.payload_tag(obj.data) != obj.tag:
                tier.drop_replica(key, host)  # corrupt source
                unusable.add(host)
                continue
            data = bytes(obj.data)
            src_tag = obj.tag
            break
        if data is None:
            telemetry.counter(_metric_names.HOT_TIER_REPAIRS_FAILED).inc()
            return None
        placed_hosts: List[int] = []
        # A host whose replica the loop just disproved (dead, missing,
        # wrong tag, corrupt-dropped) does NOT count toward k — leaving
        # it in would stop the placement loop one replica short.
        holders = set(live) - unusable
        with tracing.span(
            "hottier.repair", path=path, bytes=len(data)
        ):
            for host in rt._placement_ring():
                if len(holders) + len(placed_hosts) >= rt.k:
                    break
                if host in holders:
                    continue
                # A repair placement is a storage-op boundary: the
                # crash-point enumerator strikes between placements
                # exactly as it does between foreground replications.
                emit_storage_op("hottier.repair", f"host{host}:{path}")
                try:
                    if tier.put_replica(
                        key,
                        host,
                        data,
                        src_tag or tier.payload_tag(data),
                        root,
                        capacity_bytes=rt.capacity_bytes,
                    ):
                        placed_hosts.append(host)
                except tier.HostLostError:
                    continue
        placed_bytes = len(data) * len(placed_hosts)
        # Post-placement invariants: the world may have moved while the
        # placements were in flight.
        with rt._cond:
            forgotten = root in rt._forgotten
            state = rt._roots.get(root)
            current_tag = state.tags.get(path) if state is not None else None
            still_pending = state is not None and path in state.pending
        if forgotten or state is None:
            # Deleted mid-repair: a deleted snapshot's objects are never
            # resurrected — take every replica (ours included) back out.
            tier.forget_key(key)
            return (0, False)
        if (
            src_tag is not None
            and current_tag is not None
            and current_tag != src_tag
        ):
            # Re-written mid-repair: our placements hold superseded
            # bytes; drop everything not matching the newest tag.
            tier.drop_stale_replicas(key, current_tag)
            return (0, False)
        if not still_pending and src_tag is not None:
            # Tiered down (or written through) mid-repair: repaired
            # replicas inherit the drained/evictable state.
            tier.mark_drained(key, src_tag)
        if placed_hosts:
            with self._lock:
                self._stats["objects_repaired"] += 1
                self._stats["bytes_repaired"] += placed_bytes
            telemetry.counter(_metric_names.HOT_TIER_REPAIR_OBJECTS).inc()
            telemetry.counter(_metric_names.HOT_TIER_REPAIR_BYTES).inc(
                placed_bytes
            )
        reached_k = len(tier.live_replicas(key, current_tag or src_tag)) >= rt.k
        return (placed_bytes, reached_k)

    def _escalate(
        self, root: str, path: str, tag: Optional[str]
    ) -> tuple:
        """Deadline exceeded: make the object durable NOW through the
        existing synchronous write-through ladder. The drain item runs
        inline under the drainer's own serialization (never two
        executors on one path) and inherits every latch re-check and
        undo path — a racing delete or re-write behaves exactly as it
        does against the background drainer. Returns
        ``(retired, wrote)``: ``retired`` when the durability
        obligation is gone (written through, loss verdict, superseded,
        or deleted); ``wrote`` only when the drain item actually RAN —
        debounce deferrals and drainer-owned no-ops must not count as
        write-throughs in the stats/ledger."""
        rt = self._rt
        key = rt._key(root, path)
        logger.warning(
            f"snapmend: {root}/{path} under-replicated past the "
            f"{repair_deadline_s():g}s repair deadline; escalating to "
            f"synchronous durable write-through"
        )
        if not tier.live_replicas(key, tag):
            # No matching source replica RIGHT NOW. A foreground
            # re-write may be mid-flight between replacing the replicas
            # (hot_put) and updating the drain bookkeeping — that is
            # stale bookkeeping, not loss, and _drain_item at the
            # escalation attempt index would declare loss on the FIRST
            # probe (no re-drive budget left). Debounce across ticks:
            # each retry is a full interval apart, far longer than any
            # bookkeeping race; only a persistent absence makes the
            # loss verdict official below.
            with rt._cond:
                current = rt._item_current_locked(root, path, tag)
            if not current:
                self._esc_noreplica.pop(key, None)
                return (True, False)  # superseded/deleted: nothing left
            misses = self._esc_noreplica.get(key, 0) + 1
            self._esc_noreplica[key] = misses
            if misses < _ESCALATE_NOREPLICA_TICKS:
                logger.warning(
                    f"snapmend: escalation of {root}/{path} found no "
                    f"matching source replica (tick {misses}/"
                    f"{_ESCALATE_NOREPLICA_TICKS}); deferring the loss "
                    f"verdict one interval"
                )
                return (False, False)
        else:
            self._esc_noreplica.pop(key, None)
        with rt._cond:
            if not rt._item_current_locked(root, path, tag):
                self._esc_noreplica.pop(key, None)
                return (True, False)  # superseded/deleted: nothing left
            if rt._inflight_items.get((root, path), 0):
                # The drainer already owns it; let it land.
                return (False, False)
            rt._cancel_queued_locked(root, path)
            rt._inflight_begin_locked(root, path)
        try:
            rt._drain_item(root, path, tag, attempts=_ESCALATE_ATTEMPT)
        except Exception as e:
            logger.warning(
                f"snapmend: escalation of {root}/{path} failed: {e!r}"
            )
            return (False, True)
        finally:
            with rt._cond:
                rt._inflight_end_locked(root, path)
        with rt._cond:
            state = rt._roots.get(root)
            retired = state is None or path not in state.pending
        if retired:
            self._esc_noreplica.pop(key, None)
        return (retired, True)

    # --------------------------------------------------------- observability

    def _append_repair_ledger(
        self, by_root: Dict[str, Dict[str, int]]
    ) -> None:
        from ..telemetry import ledger as runledger

        for root, rec in sorted(by_root.items()):
            if not (rec["objects"] or rec["escalated"] or rec["failed"]):
                continue
            try:
                runledger.append_for_snapshot(
                    root,
                    runledger.repair_record(
                        path=root,
                        objects_repaired=rec["objects"],
                        bytes_repaired=rec["bytes"],
                        repairs_failed=rec["failed"],
                        escalated_write_throughs=rec["escalated"],
                        # THIS root's deficit, not the pass-global one:
                        # a fully-repaired root's durable record must
                        # not claim another root's at-risk bytes.
                        underreplicated_bytes=rec["remaining"],
                    ),
                )
            except Exception as e:
                telemetry.counter(
                    _metric_names.LEDGER_APPEND_FAILURES
                ).inc()
                logger.warning(f"repair ledger append failed: {e!r}")

    def introspect(self) -> Dict[str, Any]:
        """The repair/membership block of ``hottier.introspect()`` —
        what the sampler publishes and the ``replication-
        underreplicated`` live rule and the ops CLI consume."""
        now = time.monotonic()
        with self._lock:
            doc: Dict[str, Any] = {
                "mode": self.mode,
                "interval_s": repair_interval_s(),
                "deadline_s": repair_deadline_s(),
                "underreplicated_bytes": self._under_bytes,
                "underreplicated_objects": self._under_objects,
                "oldest_underreplicated_age_s": (
                    round(self._oldest_under_age_s, 3)
                    if self._oldest_under_age_s is not None
                    else None
                ),
                "last_tick_age_s": (
                    round(now - self._last_tick_t, 3)
                    if self._last_tick_t is not None
                    else None
                ),
                "repair_error": (
                    repr(self.repair_error)
                    if self.repair_error is not None
                    else None
                ),
                "stats": dict(self._stats),
                "membership": {
                    str(h): v.as_dict(now)
                    for h, v in sorted(self._views.items())
                },
            }
        for h, v in doc["membership"].items():
            v["current_generation"] = tier.host_generation(int(h))
        return doc
