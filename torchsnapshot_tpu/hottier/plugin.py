"""TieredPlugin: the hot tier as a composable StoragePlugin decorator.

Installed by :func:`~.runtime.enable_hot_tier` through the same
``set_plugin_wrap_hook`` seam faultline uses (hooks chain, so the two
compose in either order); ``url_to_storage_plugin`` then wraps the
result in the retry layer as usual::

    RetryingStoragePlugin( [FaultPlugin(] TieredPlugin( backend ) [)] )

Routing:

- **payload objects** (``<rank>/…``, ``replicated/…``, ``chunked/…``)
  write into peer-host RAM, k-replicated, and ACK without touching the
  durable tier; the runtime's drainer persists them in the background
  and records the ``.tierdown`` watermark (runtime.py). A put that
  cannot reach k replicas (dead or full peers, spare hosts included)
  writes through to the durable tier synchronously before the ack.
  Reads prefer a fingerprint-verified hot replica and fall back
  per-object to the durable tier, counting the degradation.
- **control plane** (anything dot-prefixed — metadata, completion
  markers, step markers, reports, progress, the ledger, ``.tierdown``
  itself — plus ``refs/`` back-links and ``@base…`` references) writes
  through synchronously: these ARE the commit protocol, and the
  metadata-last durability ordering they implement is exactly what the
  tier must not perturb. The metadata write doubles as the runtime's
  commit signal for the root.

``ensure_durable`` passes through untouched: under the hot tier it
makes the *control plane* durable, while payload durability is the
tier-down contract (ack-at-k-replicas, ``.tierdown`` when storage holds
everything) — the documented relaxation this subsystem exists for.

``list_prefix`` deliberately enumerates the DURABLE tier only: sweeps
and reconcile reason about storage objects; hot-only buffers are
reconciled through :func:`~.runtime.reconcile_hot_tier`'s own
accounting, never by pretending RAM is storage.
"""

import asyncio
import time
from typing import Optional

from ..io_types import IOReq, StoragePlugin, io_payload, is_not_found_error
from .runtime import (
    HotTierRuntime,
    _METADATA_FNAME,
    is_payload_path,
)


class TieredPlugin(StoragePlugin):
    def __init__(
        self, inner: StoragePlugin, runtime: HotTierRuntime, root: str
    ) -> None:
        self._inner = inner
        self._runtime = runtime
        self._root = root.rstrip("/")
        self.max_write_concurrency = inner.max_write_concurrency
        self.max_read_concurrency = inner.max_read_concurrency

    async def write(self, io_req: IOReq) -> None:
        rt = self._runtime
        if not rt.active or not is_payload_path(io_req.path):
            await self._inner.write(io_req)
            if rt.active and io_req.path == _METADATA_FNAME:
                # The commit point just landed: from here the take is
                # visible, and once its pending objects drain the
                # .tierdown watermark follows.
                rt.on_commit(self._root)
            return
        payload = bytes(io_payload(io_req))
        # hot_put runs INLINE on the event loop, wire RPCs included —
        # deliberately: serializing the hottier.replicate boundaries is
        # what keeps faultline's crash-point op stream deterministic
        # (concurrent executor-thread puts would interleave op indices
        # across replays), and the span inherits the take's ambient
        # trace. The cost is bounded by the per-RPC deadline + retry
        # budget per peer, after which the down-cooldown makes every
        # later push to that peer fail fast.
        placed, tag = rt.hot_put(self._root, io_req.path, payload)
        # The ack moment: hot_put returned — from here the object's
        # durability-lag clock runs (ack → drained, per object), fed to
        # the runtime alongside the payload size so the sampler's
        # at-risk accounting needs no tier re-probe.
        ack_t = time.monotonic()
        if placed < rt.k:
            # The ack-at-k contract cannot be met from RAM (dead or
            # full peers, spare hosts included): degrade to a
            # synchronous durable write BEFORE acknowledging — slower,
            # never less durable. Whatever replicas did land still
            # serve hot reads and are immediately evictable. The drain
            # pipeline for this path is quiesced FIRST, so a drain of
            # superseded bytes cannot land after our durable write; a
            # FAILED write re-arms the drain for the placed replicas so
            # the obligation is never silently retired. The quiesce can
            # block on an in-flight drain's durable write — run it off
            # the event loop so concurrent scheduler IO keeps flowing.
            await asyncio.get_running_loop().run_in_executor(
                None, rt.begin_write_through, self._root, io_req.path
            )
            try:
                await self._inner.write(io_req)
            except BaseException:
                rt.abort_write_through(
                    self._root, io_req.path, tag, placed
                )
                raise
            rt.note_write_through(
                self._root, io_req.path, tag, placed, nbytes=len(payload)
            )
            return
        rt.note_replicated_ack(len(payload))
        rt.enqueue_drain(
            self._root,
            io_req.path,
            tag,
            nbytes=len(payload),
            ack_t=ack_t,
        )

    async def read(self, io_req: IOReq) -> None:
        rt = self._runtime
        if rt.active and is_payload_path(io_req.path):
            data, attempted = rt.hot_get(
                self._root, io_req.path, io_req.byte_range
            )
            if data is not None:
                io_req.data = data
                return
            await self._inner.read(io_req)
            if attempted:
                # The hot tier knew this object and every replica was
                # dead/missing/corrupt: a counted degraded fallback —
                # and direct evidence of under-replication, so nudge
                # the snapmend repair plane instead of waiting out its
                # full interval.
                rt.note_fallback_bytes(len(io_payload(io_req)))
                rt.request_repair_scan()
            return
        await self._inner.read(io_req)

    async def delete(self, path: str) -> None:
        rt = self._runtime
        dropped = False
        if rt.active and is_payload_path(path):
            # Drop replicas AND cancel the pending drain first: a drain
            # racing this delete must not resurrect the object into the
            # durable tier after we removed it. forget_object can block
            # waiting out an in-flight drain — keep it off the event
            # loop so gathered deletes keep flowing.
            dropped = await asyncio.get_running_loop().run_in_executor(
                None, rt.forget_object, self._root, path
            )
        try:
            await self._inner.delete(path)
        except Exception as e:
            if dropped and is_not_found_error(e):
                return  # the object lived only in the hot tier
            raise

    async def list_prefix(self, prefix: str):
        return await self._inner.list_prefix(prefix)

    async def object_age_s(self, path: str) -> Optional[float]:
        try:
            age = await self._inner.object_age_s(path)
        except Exception as e:
            if not is_not_found_error(e):
                raise
            age = None
        if age is None and self._runtime.active and is_payload_path(path):
            return self._runtime.object_age_s(self._root, path)
        return age

    async def object_size_bytes(self, path: str) -> Optional[int]:
        try:
            size = await self._inner.object_size_bytes(path)
        except Exception as e:
            if not is_not_found_error(e):
                raise
            size = None
        if size is None and self._runtime.active and is_payload_path(path):
            return self._runtime.object_size_bytes(self._root, path)
        return size

    def ensure_durable(self) -> None:
        self._inner.ensure_durable()

    def close(self) -> None:
        # The drainer holds its own (bypassed) plugins; closing this one
        # never blocks on tier-down — preemption tolerance means the
        # foreground is free the moment the replicas are placed.
        self._inner.close()
