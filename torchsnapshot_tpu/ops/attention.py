"""Fused (flash) attention Pallas kernel for the flagship transformer.

The transformer workload's hot op is attention; materializing the
[B, H, S, S] score matrix is O(S²) HBM traffic, which is what caps long
sequences. This kernel computes softmax(QKᵀ)·V with the online-softmax
recurrence, tiled so only [block_q, block_k] score tiles ever exist —
they live in VMEM, QKᵀ and P·V run on the MXU, and HBM traffic drops to
O(S·D). Causal masking skips fully-masked key blocks outright
(predicated off, not just masked), halving the work of autoregressive
attention.

Kernel structure (see /opt/skills/guides/pallas_guide.md):
- grid = (batch·heads, S/block_q, S/block_k); the last axis iterates
  sequentially on TPU, so the running max/denominator/accumulator live
  in VMEM scratch that persists across it;
- accumulation in float32 regardless of input dtype (bf16-safe);
- on CPU the kernel runs in interpreter mode, so the hermetic test suite
  exercises the same code path bit-for-bit.

Exposed through the transformer via ``TransformerConfig.flash_attention``
(off by default: the einsum path remains the numerical reference; the
kernel reassociates the softmax reduction so results match to float
tolerance, not bitwise).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: key block kj is entirely in the future of query block qi
    # iff its first key index exceeds the last query index.
    run = (
        (kj * block_k <= qi * block_q + (block_q - 1)) if causal else True
    )

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)  # [block_k, d]
        s = jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(kj == last_k)
    def _finish():
        # Fully-masked rows (can't happen with causal self-attention, but
        # keep the guard) would have l == 0; avoid 0/0.
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _reference_attention(q, k, v, causal):
    """Differentiable einsum attention — the kernel's numerical spec and
    the recompute target for the backward pass."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / (d**0.5)
    if causal:
        length = q.shape[2]
        mask = jnp.tril(jnp.ones((length, length), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, interpret, residuals, g):
    # Backward recomputes attention through the differentiable reference:
    # training keeps exact einsum gradients while the forward pass (and
    # anything under stop_gradient/inference) uses the fused kernel. The
    # backward therefore still materializes S² — the kernel's O(S·D)
    # memory win applies to forward/inference paths.
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """softmax(QKᵀ/√D)·V without materializing the S×S score matrix."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_forward(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,  # resolved by flash_attention(); never None here
) -> jax.Array:
    b, h, s, d = q.shape
    assert k.shape == v.shape == (b, h, s, d)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"sequence length {s} must be divisible by block sizes "
            f"({block_q}, {block_k})"
        )

    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)

    grid = (b * h, s // block_q, s // block_k)
    kernel = functools.partial(
        _flash_kernel,
        scale=1.0 / (d**0.5),
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)
