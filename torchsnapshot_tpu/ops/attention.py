"""Fused (flash) attention Pallas kernel for the flagship transformer.

The transformer workload's hot op is attention; materializing the
[B, H, S, S] score matrix is O(S²) HBM traffic, which is what caps long
sequences. This kernel computes softmax(QKᵀ)·V with the online-softmax
recurrence, tiled so only [block_q, block_k] score tiles ever exist —
they live in VMEM, QKᵀ and P·V run on the MXU, and HBM traffic drops to
O(S·D). Causal masking skips fully-masked key blocks outright
(predicated off, not just masked), halving the work of autoregressive
attention.

Kernel structure (see /opt/skills/guides/pallas_guide.md):
- grid = (batch·heads, S/block_q, S/block_k); the last axis iterates
  sequentially on TPU, so the running max/denominator/accumulator live
  in VMEM scratch that persists across it;
- accumulation in float32 regardless of input dtype (bf16-safe);
- on CPU the kernel runs in interpreter mode, so the hermetic test suite
  exercises the same code path bit-for-bit.

The backward pass is also tiled Pallas: the forward saves the per-row
log-sum-exp, and two kernels reconstruct p = exp(s - lse) per tile to
accumulate dq (over key blocks) and dk/dv (over query blocks) — the
score matrix never materializes in either direction, so the O(S·D)
memory bound holds for training too.

Exposed through the transformer via ``TransformerConfig.flash_attention``
(off by default: the einsum path remains the numerical reference; the
kernel reassociates the softmax reduction so results match to float
tolerance, not bitwise).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _causal_positions(qi, kj, block_q: int, block_k: int):
    """Global (q_pos, k_pos) grids for one (q-block, k-block) tile —
    the single source of the position math shared by the forward and
    backward kernels."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return q_pos, k_pos


def _block_visible(qi, kj, block_q: int, block_k: int):
    """Whether any key of block kj is visible (causally) to block qi."""
    return kj * block_k <= qi * block_q + (block_q - 1)


def resolve_flash_block(seq_len: int) -> int:
    """The tiling policy, shared by every flash call site: largest
    power-of-two divisor of the sequence length, capped at 1024.

    The cap is a VMEM-residency choice, not an MXU one: bigger tiles
    amortize the per-block online-softmax bookkeeping and k/v tile
    revisits. Measured on one v5e chip (S=4096, D=128, bf16, causal):
    128-wide tiles sustain ~10 TFLOP/s forward, 512 ~50, 1024 ~80 (and
    ~6× on forward+backward); 2048² tiles exceed VMEM and fail to
    compile. A 1024² f32 score tile is 4 MB — resident even on 16 MB
    VMEM generations. Lengths whose power-of-two factor is below the
    sublane minimum (8) are rejected — they would tile into sub-MXU
    scalar-sized blocks, worse than einsum.

    The numbers above are v5e; the backward pass holds several
    [block, block] f32 intermediates live per tile, so a generation
    with much smaller VMEM may need a smaller cap —
    ``TPUSNAPSHOT_FLASH_BLOCK_CAP`` overrides it without code changes."""
    import math

    from ..utils.env import env_int

    cap = env_int("TPUSNAPSHOT_FLASH_BLOCK_CAP", 1024)
    block = math.gcd(seq_len, cap)
    if block < 8:
        raise ValueError(
            f"flash attention needs a sequence length with a power-of-two "
            f"factor >= 8; {seq_len} tiles at {block} rows. Pad the "
            f"sequence or use the einsum path."
        )
    return block


def resolve_interpret() -> bool:
    """Run the kernel in interpreter mode off-TPU (hermetic CPU tests).

    Any non-TPU backend interprets: the kernels are written against the
    TPU Mosaic lowering, and compiling them on e.g. GPU would fail with
    an opaque Mosaic error rather than fall back."""
    return jax.default_backend() != "tpu"


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: key block kj is entirely in the future of query block qi
    # iff its first key index exceeds the last query index.
    run = _block_visible(qi, kj, block_q, block_k) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)  # [block_k, d]
        s = jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            q_pos, k_pos = _causal_positions(qi, kj, block_q, block_k)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(kj == last_k)
    def _finish():
        # Fully-masked rows (can't happen with causal self-attention, but
        # keep the guard) would have l == 0; avoid 0/0.
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # Log-sum-exp per row, consumed by the backward kernels to
        # reconstruct p = exp(s - lse) without storing the score matrix.
        lse_ref[0] = m_ref[:] + jnp.log(denom)


def _bwd_pieces(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *, scale,
                causal, qi, kj, block_q, block_k):
    """Recompute p and ds for one (q-block, k-block) pair — the shared
    core of both backward kernels. Returns (p, ds), both [block_q,
    block_k] float32."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    # All-masked rows (forward wrote lse = -1e30) must yield p = 0, not
    # exp(s + 1e30) = inf: clamp for the exp, then zero those rows.
    lse_raw = lse_ref[0]
    lse_safe = jnp.maximum(lse_raw, _NEG_INF / 2)
    p = jnp.where(lse_raw > _NEG_INF / 2, jnp.exp(s - lse_safe), 0.0)
    if causal:
        q_pos, k_pos = _causal_positions(qi, kj, block_q, block_k)
        p = jnp.where(k_pos <= q_pos, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, block_k]
    ds = p * (dp - delta_ref[0])
    return p, ds


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale, causal, block_q, block_k,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _block_visible(qi, kj, block_q, block_k) if causal else True

    @pl.when(run)
    def _step():
        _, ds = _bwd_pieces(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, causal=causal, qi=qi, kj=kj,
            block_q=block_q, block_k=block_k,
        )
        dq_acc[:] += scale * jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, scale, causal, block_q, block_k,
):
    # Grid: (bh, n_k, n_q) — the q-block axis iterates sequentially so
    # the dk/dv accumulators persist across it.
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # Causal: q block strictly before the k block contributes nothing.
    run = _block_visible(qi, kj, block_q, block_k) if causal else True

    @pl.when(run)
    def _step():
        p, ds = _bwd_pieces(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, causal=causal, qi=qi, kj=kj,
            block_q=block_q, block_k=block_k,
        )
        dv_acc[:] += jax.lax.dot_general(
            p, do_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # pᵀ·dO [block_k, d]
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # dsᵀ·q [block_k, d]

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _reference_attention(q, k, v, causal):
    """Differentiable einsum attention — the kernels' numerical spec
    (forward and backward match it to float tolerance, not bitwise: the
    tiled kernels reassociate the softmax reductions)."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / (d**0.5)
    if causal:
        length = q.shape[2]
        mask = jnp.tril(jnp.ones((length, length), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)[0]


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, residuals, g):
    # Tiled Pallas backward: p is reconstructed per tile from the saved
    # log-sum-exp, so the backward, like the forward, never materializes
    # the S×S score matrix (O(S·D) memory end to end). Two kernels: dq
    # accumulates over key blocks; dk/dv accumulate over query blocks.
    q, k, v, out, lse = residuals
    # delta_i = rowsum(dO_i · O_i) — the softmax-jacobian correction.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    dq, dk, dv = _flash_backward(
        q, k, v, g, lse, delta, causal, block_q, block_k, interpret
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_chunk_attention(q, k, v, causal, block_q, block_k, interpret):
    """Flash attention returning BOTH (out, lse) — the chunk primitive for
    ring attention (parallel/ring_attention.py), differentiable.

    The ring's online-softmax merge consumes the chunk's normalized output
    *and* its log-sum-exp, so cotangents arrive for both. The lse cotangent
    folds into the existing tiled backward kernels without new code:
    ds_ij = p_ij·(dout_i·v_j − delta_i) from the output plus
    ds_ij += dlse_i·p_ij from the lse (∂lse_i/∂s_ij = p_ij), i.e. the
    kernels run unchanged with delta' = delta − dlse. dv is lse-independent.
    """
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_chunk_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_chunk_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    dout, dlse = g
    dout = dout.astype(jnp.float32)
    delta = (
        jnp.sum(dout * out.astype(jnp.float32), axis=-1, keepdims=True)
        - dlse.astype(jnp.float32)
    )
    dq, dk, dv = _flash_backward(
        q, k, v, dout, lse, delta, causal, block_q, block_k, interpret
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_chunk_attention.defvjp(_flash_chunk_fwd, _flash_chunk_bwd)


def _resolve_blocks(s: int, block_q: int, block_k: int):
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"sequence length {s} must be divisible by block sizes "
            f"({block_q}, {block_k})"
        )
    return block_q, block_k


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_backward(q, k, v, g, lse, delta, causal, block_q, block_k, interpret):
    b, h, s, d = q.shape
    group = _gqa_group(q, k)
    hkv = h // group
    block_q, block_k = _resolve_blocks(s, block_q, block_k)
    scale = 1.0 / (d**0.5)
    bh = b * h
    flat = lambda x: x.reshape(-1, s, x.shape[-1])  # noqa: E731
    qf, kf, vf, gf = flat(q), flat(k), flat(v), flat(g)
    lsef, deltaf = lse.reshape(bh, s, 1), delta.reshape(bh, s, 1)

    # Two index maps cover both grids: "block index is grid axis 1" vs
    # "grid axis 2". dq's grid is (bh, q, k); dk/dv's is (bh, k, q) — the
    # q-indexed operands ride axis 1 in the first and axis 2 in the
    # second, and vice versa for k-indexed ones. Under GQA the k-indexed
    # operands additionally collapse the q-head to its kv-head.
    by_axis1 = lambda bh_, a, b_: (bh_, a, 0)  # noqa: E731
    by_axis2 = lambda bh_, a, b_: (bh_, b_, 0)  # noqa: E731
    kv1 = _kv_index_map(h, group)  # k-operand indexed by grid axis 2
    row_q = pl.BlockSpec((1, block_q, d), by_axis1)
    row_k = pl.BlockSpec((1, block_k, d), kv1)
    aux_q = pl.BlockSpec((1, block_q, 1), by_axis1)

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        grid=(bh, s // block_q, s // block_k),
        in_specs=[row_q, row_k, row_k, row_q, aux_q, aux_q],
        out_specs=row_q,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, deltaf)

    # dk/dv grid swaps the roles: k-block outer (axis 1), q-block inner.
    # Under GQA the kernel runs per Q-head (each contributes to its
    # kv-head's gradient); the per-q-head partials are group-summed after
    # the call — one transient [B,Hq,S,D] f32 pair, the same footprint as
    # the incoming cotangent, in exchange for unchanged kernel code.
    row_q2 = pl.BlockSpec((1, block_q, d), by_axis2)
    row_k2 = pl.BlockSpec((1, block_k, d), _kv_index_map(h, group, block_axis=1))
    out_k2 = pl.BlockSpec((1, block_k, d), by_axis1)
    aux_q2 = pl.BlockSpec((1, block_q, 1), by_axis2)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        ),
        grid=(bh, s // block_k, s // block_q),
        in_specs=[row_q2, row_k2, row_k2, row_q2, aux_q2, aux_q2],
        out_specs=(out_k2, out_k2),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, deltaf)

    dq = dq.reshape(b, h, s, d)
    if group == 1:
        return dq, dk.reshape(b, hkv, s, d), dv.reshape(b, hkv, s, d)
    dk = dk.reshape(b, hkv, group, s, d).sum(axis=2)
    dv = dv.reshape(b, hkv, group, s, d).sum(axis=2)
    return dq, dk, dv


def flash_attention(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """softmax(QKᵀ/√D)·V without materializing the S×S score matrix."""
    if interpret is None:
        interpret = resolve_interpret()
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)


def _gqa_group(q: jax.Array, k: jax.Array) -> int:
    """Query heads per key/value head. Dense attention is group 1;
    grouped-query attention (Hq = g·Hkv) maps q-head h to kv-head
    h // g — expressed in the kernels purely through BlockSpec index
    maps, so K/V are never materialized per q-head."""
    hq, hkv = q.shape[1], k.shape[1]
    if hq % hkv:
        raise ValueError(
            f"query heads ({hq}) must be a multiple of kv heads ({hkv})"
        )
    return hq // hkv


def _kv_index_map(h: int, group: int, block_axis: int = 2):
    """Flat q-head grid index -> flat kv-head row: bh = b·H + h_q maps to
    b·(H//group) + h_q//group. ``block_axis`` selects which grid axis
    carries the k-block index (2 for the forward/dq grids (bh, q, k),
    1 for the dk/dv grid (bh, k, q))."""
    hkv = h // group

    def index_map(bh, a, b_):
        return (
            (bh // h) * hkv + (bh % h) // group,
            a if block_axis == 1 else b_,
            0,
        )

    return index_map


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_forward(
    q: jax.Array,  # [B, Hq, S, D]
    k: jax.Array,  # [B, Hkv, S, D] — Hq % Hkv == 0 (GQA); dense if equal
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,  # resolved by flash_attention(); never None here
):
    """Returns (out [B,Hq,S,D], lse [B,Hq,S,1] float32)."""
    b, h, s, d = q.shape
    group = _gqa_group(q, k)
    assert k.shape == v.shape == (b, h // group, s, d)
    block_q, block_k = _resolve_blocks(s, block_q, block_k)

    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * (h // group), s, d)
    vf = v.reshape(b * (h // group), s, d)

    grid = (b * h, s // block_q, s // block_k)
    kernel = functools.partial(
        _flash_kernel,
        scale=1.0 / (d**0.5),
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    kv_map = _kv_index_map(h, group)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d), lse.reshape(b, h, s, 1)
