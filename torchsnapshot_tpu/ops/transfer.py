"""Device↔host transfer ops: chunked parallel gather + consistent-cut clone.

The two device-side primitives behind snapshot performance:

- :func:`parallel_device_get` — gather a large device array to host by
  slicing it on device along its largest dimension and transferring the
  slices over concurrent streams. A single device→host stream does not
  saturate the accelerator↔host link (PCIe on TPU VMs, or a network hop
  when the device is remote); measured here, 32 concurrent 8 MiB chunk
  streams sustain ~2× the single-stream bandwidth. Reference analog: the
  CUDA-stream staging thread pool (torchsnapshot io_preparer.py:199-210),
  re-thought for XLA's transfer model.
- :func:`device_clone` — on-device copies of a batch of arrays (sharding
  preserved). An HBM→HBM copy runs at memory bandwidth, which is what
  makes device-staged async snapshots' "stall = one on-device copy"
  possible.

Env knobs: ``TPUSNAPSHOT_TRANSFER_CHUNK_BYTES`` (default 8 MiB),
``TPUSNAPSHOT_TRANSFER_CONCURRENCY`` (default 32),
``TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER`` (test hook: chunk on CPU too).
"""

import math
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Sequence

import numpy as np

import jax

_DEFAULT_TRANSFER_CHUNK_BYTES = 8 * 1024 * 1024
_DEFAULT_TRANSFER_CONCURRENCY = 32

_transfer_pool: Optional[ThreadPoolExecutor] = None
_transfer_pool_lock = threading.Lock()


def transfer_chunk_bytes() -> int:
    return int(
        os.environ.get(
            "TPUSNAPSHOT_TRANSFER_CHUNK_BYTES", _DEFAULT_TRANSFER_CHUNK_BYTES
        )
    )


def _get_transfer_pool() -> ThreadPoolExecutor:
    global _transfer_pool
    with _transfer_pool_lock:
        if _transfer_pool is None:
            _transfer_pool = ThreadPoolExecutor(
                max_workers=int(
                    os.environ.get(
                        "TPUSNAPSHOT_TRANSFER_CONCURRENCY",
                        _DEFAULT_TRANSFER_CONCURRENCY,
                    )
                ),
                thread_name_prefix="tpusnapshot-d2h",
            )
        return _transfer_pool


def should_chunk_transfer(arr: Any) -> bool:
    """Whether ``arr`` is a device array large enough for chunked gather."""
    if not isinstance(arr, jax.Array):
        return False
    try:
        platform = next(iter(arr.devices())).platform
    # Placement probe (tracers hide .devices()); "don't chunk" is the
    # safe default and the plain path surfaces real failures.
    except Exception:  # pragma: no cover; snapcheck: disable=swallowed-exception -- placement probe
        return False
    if platform == "cpu" and not os.environ.get(
        "TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER"
    ):
        # Host-backed arrays gather via memcpy (often zero-copy); device
        # slicing would only add copies. Env override exists for tests.
        return False
    shape = tuple(arr.shape)
    if not shape or max(shape) <= 1:
        return False
    nbytes = np.dtype(arr.dtype).itemsize * math.prod(shape)
    return nbytes >= 2 * transfer_chunk_bytes()


def parallel_device_get(arr: jax.Array) -> np.ndarray:
    """Gather ``arr`` to host via parallel chunked transfers."""
    shape = tuple(arr.shape)
    dtype = np.dtype(arr.dtype)
    nbytes = dtype.itemsize * math.prod(shape)
    axis = max(range(len(shape)), key=lambda d: shape[d])
    n_chunks = min(shape[axis], max(1, -(-nbytes // transfer_chunk_bytes())))
    out = np.empty(shape, dtype=dtype)
    bounds = [round(i * shape[axis] / n_chunks) for i in range(n_chunks + 1)]

    def _fetch(lo: int, hi: int) -> None:
        piece = jax.lax.slice_in_dim(arr, lo, hi, axis=axis)
        sel = tuple(
            slice(lo, hi) if d == axis else slice(None)
            for d in range(len(shape))
        )
        out[sel] = np.asarray(piece)

    pool = _get_transfer_pool()
    futures = [
        pool.submit(_fetch, bounds[i], bounds[i + 1])
        for i in range(n_chunks)
        if bounds[i] < bounds[i + 1]
    ]
    errors = [f.exception() for f in futures]
    for err in errors:
        if err is not None:
            raise err
    return out


_DEFAULT_H2D_CHUNK_BYTES = 16 * 1024 * 1024


def h2d_chunk_bytes() -> int:
    from ..utils.env import env_int

    return env_int("TPUSNAPSHOT_H2D_CHUNK_BYTES", _DEFAULT_H2D_CHUNK_BYTES)


def should_chunk_h2d(arr: Any, device: Any) -> bool:
    """Whether a host buffer is worth pushing through the chunked path."""
    if getattr(device, "platform", None) == "cpu" and not os.environ.get(
        "TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER"
    ):
        return False
    return arr.nbytes >= 2 * h2d_chunk_bytes()


def chunked_device_put(arr: np.ndarray, device: Any) -> Any:
    """Push a large host buffer to one device as a batch of medium-size
    chunks and reassemble on device.

    A single host→device stream does not saturate this platform's link
    (measured here: one 200 MB ``device_put`` sustains ~0.015 GB/s, a
    batched put of 16–32 MB slices + on-device ``concatenate`` ~0.025
    GB/s — the runtime pipelines the per-chunk transfers where one large
    transfer serializes). The reassembly is a flat 1-D concatenate +
    reshape: both layout-preserving, so the device-side cost is one HBM
    copy. Transient HBM footprint is 2× the array (chunks + result),
    matching the take path's on-device clone.
    """
    import jax.numpy as jnp

    flat = np.ascontiguousarray(arr).reshape(-1)
    itemsize = flat.dtype.itemsize
    chunk_elems = max(1, h2d_chunk_bytes() // itemsize)
    pieces = [
        flat[i : i + chunk_elems] for i in range(0, flat.size, chunk_elems)
    ]
    parts = jax.device_put(pieces, [device] * len(pieces))
    return jnp.concatenate(parts).reshape(arr.shape)


# ------------------------------------------------------------- H2D probe
#
# One-shot hardware-bound measurement for the restore flight report
# (snapxray): consume GB/s only means something as a FRACTION of what
# the link could do, the same way bench pins take against the D2H
# probe. Memoized per process — the report wants an order-of-magnitude
# anchor, not a bracketing measurement (bench's restore section still
# brackets with fresh probes).

_H2D_PROBE_BYTES_ENV_VAR = "TPUSNAPSHOT_H2D_PROBE_BYTES"
_DEFAULT_H2D_PROBE_BYTES = 32 * 1024 * 1024

_h2d_probe_lock = threading.Lock()
_h2d_probe_memo: List[Optional[float]] = []


def probe_h2d_gbps(refresh: bool = False) -> Optional[float]:
    """Measured host→device bandwidth (GB/s) via the same chunked-put
    transfer the restore path uses, synced by a forced device reduction
    (``device_put`` returns before bytes cross the link). Best of two
    runs, each with a FRESH host buffer — re-putting the same array
    measures a cached staging path, not a restore. Memoized; ``refresh``
    re-measures. Returns None when disabled
    (``TPUSNAPSHOT_H2D_PROBE_BYTES=0``) or the probe fails (no device)."""
    from ..utils.env import env_int

    with _h2d_probe_lock:
        if _h2d_probe_memo and not refresh:
            return _h2d_probe_memo[0]
    nbytes = env_int(_H2D_PROBE_BYTES_ENV_VAR, _DEFAULT_H2D_PROBE_BYTES)
    result: Optional[float] = None
    if nbytes > 0:
        try:
            import time

            import jax.numpy as jnp

            device = jax.devices()[0]
            force = jax.jit(jnp.sum)
            rng = np.random.default_rng(11)
            n = max(1, nbytes // 4)
            best = 0.0
            for _ in range(2):
                host = rng.standard_normal(n, dtype=np.float32)
                begin = time.monotonic()
                arr = chunked_device_put(host, device)
                float(force(arr))
                elapsed = time.monotonic() - begin
                if elapsed > 0:
                    best = max(best, host.nbytes / 1024**3 / elapsed)
                arr.delete()
                del host
            result = best if best > 0 else None
        # Capability probe: a backend without a usable device (or one
        # that rejects delete()) yields "no probe", never a failed
        # restore report.
        except Exception:  # snapcheck: disable=swallowed-exception -- capability probe
            result = None
    with _h2d_probe_lock:
        if _h2d_probe_memo:
            _h2d_probe_memo[0] = result
        else:
            _h2d_probe_memo.append(result)
    return result


# ------------------------------------------------------- H2D overlap engine
#
# The streaming-restore fast path's transfer stream: a depth-limited
# worker pool that owns ALL host→device placement the restore pipeline
# wants off its consume executors. Consumers submit a host buffer the
# moment its decode+verify completes and go back to consuming; the
# engine runs the (chunked) put, FORCES the bytes across the link
# (block_until_ready — device_put alone returns before the transfer on
# this platform), accounts the wall into the restore's consume profile
# as ``h2d_overlap``, and fires the caller's done-callback. Depth 2
# (``TPUSNAPSHOT_H2D_DEPTH``) is classic double buffering: one chunk's
# bytes ride the link while the next chunk's decode/verify/submit
# proceeds — the H2D mirror of how take double-buffers D2H through the
# chunked transfer pool above.

_H2D_DEPTH_ENV_VAR = "TPUSNAPSHOT_H2D_DEPTH"
_DEFAULT_H2D_DEPTH = 2


def h2d_depth() -> int:
    from ..utils.env import env_int

    return max(1, env_int(_H2D_DEPTH_ENV_VAR, _DEFAULT_H2D_DEPTH))


class H2DPipeline:
    """Depth-limited asynchronous host→device transfer engine."""

    def __init__(self, depth: Optional[int] = None) -> None:
        self._pool = ThreadPoolExecutor(
            max_workers=depth if depth is not None else h2d_depth(),
            thread_name_prefix="tpusnapshot-h2d",
        )

    def submit(self, host: Any, device: Any, profile: Any = None):
        """Schedule ``host`` (a numpy buffer) onto ``device``; returns a
        ``concurrent.futures.Future`` resolving to the device array
        AFTER the bytes have crossed the link. Exceptions (including
        faultline's SimulatedCrash BaseException) resolve into the
        future — callers must surface them before publishing anything
        assembled from sibling transfers."""
        from ..telemetry import consume_profile as _cprof

        nbytes = int(getattr(host, "nbytes", len(host)))

        def _transfer() -> Any:
            from .. import telemetry
            from ..telemetry import metrics as _metric_names

            t0 = time.monotonic()
            # Union-time accounting (overlap_span): the profile's
            # h2d_overlap seconds advance once across concurrent
            # workers so bytes/seconds is delivered link throughput;
            # the process counter below keeps plain per-call walls.
            with _cprof.overlap_span(profile, nbytes):
                if should_chunk_h2d(host, device):
                    dev = chunked_device_put(host, device)
                else:
                    dev = jax.device_put(host, device)
                jax.block_until_ready(dev)
            elapsed = time.monotonic() - t0
            telemetry.counter(_metric_names.H2D_OVERLAP_SECONDS).inc(
                elapsed
            )
            telemetry.counter(_metric_names.H2D_OVERLAP_BYTES).inc(nbytes)
            return dev

        return self._pool.submit(_transfer)


_h2d_pipeline: Optional[H2DPipeline] = None
_h2d_pipeline_lock = threading.Lock()


def h2d_pipeline() -> H2DPipeline:
    global _h2d_pipeline
    with _h2d_pipeline_lock:
        if _h2d_pipeline is None:
            _h2d_pipeline = H2DPipeline()
        return _h2d_pipeline


def _reset_h2d_pipeline_for_tests() -> None:
    global _h2d_pipeline
    with _h2d_pipeline_lock:
        _h2d_pipeline = None


def is_oom_error(exc: BaseException) -> bool:
    if isinstance(exc, MemoryError):
        return True
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text


def device_clone(arrays: Sequence[jax.Array]) -> Optional[List[jax.Array]]:
    """On-device copies of ``arrays`` (shardings preserved). Returns
    None — with partial clones released — if the device ran out of
    memory and the synchronous OOM check is enabled.

    The batched ``block_until_ready`` exists ONLY for that OOM check:
    the fallback to host staging must be decided while the caller's
    original arrays are still valid (after ``async_take`` returns they
    may be donated away). It costs one host↔device round trip — the
    dominant part of the async-take stall on a tunneled device
    (measured: ~160 ms of a ~166 ms stall, vs microseconds for the HBM
    copy itself). Deployments with known HBM headroom can set
    ``TPUSNAPSHOT_CLONE_OOM_CHECK=0`` to skip it: a (now unhandled)
    clone OOM then surfaces when the background drain first stages from
    the poisoned clone — failing the take at ``wait()`` instead of
    falling back to host staging. Consistency does not depend on the
    wait either way: the runtime orders the clone before any later
    computation and keeps source buffers alive for pending consumers.
    """
    import jax.numpy as jnp

    check_oom = os.environ.get("TPUSNAPSHOT_CLONE_OOM_CHECK", "1") != "0"
    clones: List[jax.Array] = []
    try:
        for arr in arrays:
            clones.append(jnp.copy(arr))
        # One batched wait, not a per-array loop: each blocking call pays a
        # full host↔device round trip, which dominates the HBM copy itself
        # when the device is behind a network tunnel (measured here: 20
        # sequential waits ≈ 1.7 s vs one batched wait ≈ 0.1 s).
        if check_oom:
            jax.block_until_ready(clones)
    except Exception as e:
        if is_oom_error(e):
            for clone in clones:
                try:
                    clone.delete()
                # Freeing partially-materialized clones during OOM
                # unwind; the OOM itself is what the caller reports.
                except Exception:  # pragma: no cover; snapcheck: disable=swallowed-exception -- OOM unwind
                    pass
            return None
        raise
    return clones
