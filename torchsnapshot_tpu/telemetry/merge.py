"""Cross-rank Chrome-trace merge: one timeline, one clock, one verdict.

Usage::

    python -m torchsnapshot_tpu.telemetry.merge rank0.json rank1.json ... \
        -o merged.json [--json]

Each per-rank trace written by ``tracing.py`` is self-describing: its
``metadata`` carries ``clock_epoch_s`` (the wall-clock epoch of trace
ts 0), ``rank``, and ``host``. The merge

1. maps every event's monotonic ts onto the wall clock,
2. **corrects clock skew** using coord barrier instants
   (``barrier_exit`` events: every rank passes a given barrier
   generation at approximately one global moment, so per-rank deviation
   from the cross-rank median at shared generations IS that rank's
   clock skew),
3. emits a single Perfetto-loadable trace — each rank rendered as its
   own process (``pid = rank``, named ``rank N (host)``), span ids
   namespaced per rank so cross-rank id collisions cannot pair a begin
   on one rank with an end on another, all timestamps rebased to one
   monotonic non-negative clock,
4. computes the **cross-rank critical path**: which rank's pipeline
   activity ended last (gating the commit every other rank then waited
   for), that rank's dominant phase, and each rank's slack.

``telemetry.summarize`` recognizes a merged trace and appends the
critical-path section to its per-phase table.

Exit codes: 0 = merged; 1 = no events in any input; 2 = usage error.
"""

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# The pipelined ops whose completion can gate a commit (take or restore
# direction); instants and orchestration wrappers don't gate by
# themselves.
_PIPELINE_OPS = ("stage", "write", "read", "consume")

_BARRIER_INSTANT = "barrier_exit"
_COMMIT_INSTANTS = ("metadata_committed", "step_marker_committed")


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare-array Chrome trace variant
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a Chrome trace")
    return doc


def trace_meta(doc: Dict[str, Any], fallback_rank: int) -> Dict[str, Any]:
    """The trace's identity metadata, tolerating traces from before the
    stamp existed (they merge as rank ``fallback_rank`` on an
    uncorrected clock)."""
    meta = doc.get("metadata") or {}
    return {
        "clock_epoch_s": float(meta.get("clock_epoch_s") or 0.0),
        "rank": int(meta["rank"]) if meta.get("rank") is not None else fallback_rank,
        "host": str(meta.get("host") or "?"),
    }


def _barrier_walls(
    doc: Dict[str, Any], epoch: float
) -> Dict[Any, float]:
    """``{barrier generation: wall time}`` for this trace's
    barrier-exit instants (first occurrence per generation)."""
    out: Dict[Any, float] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "i" and ev.get("name") == _BARRIER_INSTANT:
            gen = (ev.get("args") or {}).get("gen")
            if gen is not None and gen not in out:
                out[gen] = epoch + ev.get("ts", 0.0) / 1e6
    return out


def compute_skews(
    docs: List[Dict[str, Any]], metas: List[Dict[str, Any]]
) -> Dict[int, float]:
    """Per-rank clock-skew estimate (seconds to SUBTRACT from that
    rank's wall times). Anchored on barrier generations present in every
    trace: at each shared generation, a rank's deviation from the
    cross-rank median is skew plus barrier-exit jitter; averaging over
    generations keeps the jitter small. Ranks without shared anchors
    get skew 0 (wall clocks trusted as-is)."""
    walls = [
        _barrier_walls(doc, meta["clock_epoch_s"])
        for doc, meta in zip(docs, metas)
    ]
    shared = set(walls[0]) if walls else set()
    for w in walls[1:]:
        shared &= set(w)
    skews: Dict[int, List[float]] = {}
    for gen in shared:
        at = sorted(w[gen] for w in walls)
        median = at[len(at) // 2]
        for meta, w in zip(metas, walls):
            skews.setdefault(meta["rank"], []).append(w[gen] - median)
    return {
        meta["rank"]: (
            sum(skews[meta["rank"]]) / len(skews[meta["rank"]])
            if skews.get(meta["rank"])
            else 0.0
        )
        for meta in metas
    }


def merge_traces(
    docs: List[Dict[str, Any]], skew_correct: bool = True
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Merge per-rank traces onto one corrected clock.

    Returns ``(merged trace doc, info)`` where info carries the skew
    table and the critical-path verdict.
    """
    metas = [trace_meta(doc, i) for i, doc in enumerate(docs)]
    ranks = [m["rank"] for m in metas]
    if len(set(ranks)) != len(ranks):
        raise ValueError(
            f"duplicate rank(s) across input traces: {sorted(ranks)} — "
            f"each input must be a distinct rank's trace"
        )
    skews = (
        compute_skews(docs, metas)
        if skew_correct
        else {r: 0.0 for r in ranks}
    )

    # Corrected wall time of every event; the merged clock starts at the
    # earliest event (ts >= 0, monotonic by construction: one shared
    # wall clock after skew subtraction).
    t_base: Optional[float] = None
    per_doc_events: List[List[Tuple[float, Dict[str, Any]]]] = []
    for doc, meta in zip(docs, metas):
        epoch = meta["clock_epoch_s"] - skews[meta["rank"]]
        rows = []
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue  # per-process metadata is re-emitted below
            wall = epoch + ev.get("ts", 0.0) / 1e6
            rows.append((wall, ev))
            t_base = wall if t_base is None else min(t_base, wall)
        per_doc_events.append(rows)
    if t_base is None:
        raise ValueError("no events in any input trace")

    merged_events: List[Dict[str, Any]] = []
    for meta, rows in zip(metas, per_doc_events):
        rank = meta["rank"]
        merged_events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": rank,
                "tid": 0,
                "args": {"name": f"rank {rank} ({meta['host']})"},
            }
        )
        for wall, ev in rows:
            out = dict(ev)
            out["ts"] = (wall - t_base) * 1e6
            out["pid"] = rank
            if "id" in out:
                # Namespace span ids per rank: every trace counts ids
                # from 1, and a cross-rank collision would let a begin
                # on rank A pair with an end on rank B.
                out["id"] = f"r{rank}:{out['id']}"
            merged_events.append(out)
    merged_events.sort(key=lambda e: e.get("ts", 0.0))

    info = {
        "ranks": sorted(ranks),
        "skew_s": {str(r): round(skews[r], 6) for r in sorted(skews)},
        "t_base_epoch_s": t_base,
        "critical_path": critical_path(merged_events),
    }
    merged = {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged": True,
            "ranks": sorted(ranks),
            "skew_s": info["skew_s"],
            "clock_epoch_s": t_base,
            "tracer": "torchsnapshot_tpu",
        },
    }
    return merged, info


def critical_path(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Which rank/phase gated the commit.

    Per rank, find the end time of its last pipeline-op span (the work
    the commit's completion barrier waits for). The **gating rank** is
    the one whose pipeline ended last; every other rank's slack is how
    long it sat finished while the gater worked. The commit instant
    (when present) confirms the ordering: it can only land after the
    gating rank's last write.
    """
    begins: Dict[Any, Dict[str, Any]] = {}
    last_end: Dict[int, float] = {}
    last_phase: Dict[int, str] = {}
    commit_ts: Optional[float] = None
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "")
        if ph == "i" and name in _COMMIT_INSTANTS:
            ts = ev.get("ts", 0.0)
            commit_ts = ts if commit_ts is None else max(commit_ts, ts)
            continue
        if name not in _PIPELINE_OPS:
            continue
        if ph == "b":
            begins[(ev.get("pid"), ev.get("id"), name)] = ev
        elif ph == "e":
            b = begins.pop((ev.get("pid"), ev.get("id"), name), None)
            if b is None:
                continue
            rank = int(ev.get("pid", 0))
            end = ev.get("ts", 0.0)
            if end >= last_end.get(rank, -1.0):
                last_end[rank] = end
                last_phase[rank] = name
        elif ph == "X":
            rank = int(ev.get("pid", 0))
            end = ev.get("ts", 0.0) + ev.get("dur", 0)
            if end >= last_end.get(rank, -1.0):
                last_end[rank] = end
                last_phase[rank] = name
    if not last_end:
        return None
    gating_rank = max(last_end, key=lambda r: last_end[r])
    gate_end = last_end[gating_rank]
    return {
        "gating_rank": gating_rank,
        "gating_phase": last_phase[gating_rank],
        "gate_end_s": round(gate_end / 1e6, 6),
        "commit_at_s": (
            round(commit_ts / 1e6, 6) if commit_ts is not None else None
        ),
        "per_rank": [
            {
                "rank": r,
                "last_phase": last_phase[r],
                "last_end_s": round(last_end[r] / 1e6, 6),
                "slack_s": round((gate_end - last_end[r]) / 1e6, 6),
            }
            for r in sorted(last_end)
        ],
    }


def render_info(info: Dict[str, Any]) -> str:
    lines: List[str] = []
    lines.append(
        f"merged {len(info['ranks'])} rank trace(s): "
        f"ranks {', '.join(str(r) for r in info['ranks'])}"
    )
    skews = info.get("skew_s") or {}
    if any(abs(v) > 0 for v in skews.values()):
        lines.append("per-rank clock skew (s, corrected):")
        for r in sorted(skews, key=int):
            lines.append(f"  rank {r}: {skews[r]:+.6f}")
    else:
        lines.append("per-rank clock skew: none detected (or no shared "
                     "barrier anchors)")
    cp = info.get("critical_path")
    if cp:
        lines.append(
            f"critical path: rank {cp['gating_rank']} gated the commit "
            f"(last {cp['gating_phase']} ended at "
            f"{cp['gate_end_s']:.3f}s)"
        )
        for row in cp["per_rank"]:
            lines.append(
                f"  rank {row['rank']}: last {row['last_phase']} ended "
                f"{row['last_end_s']:.3f}s, slack {row['slack_s']:.3f}s"
            )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry.merge",
        description="Merge per-rank snapshot traces onto one "
        "skew-corrected clock.",
    )
    parser.add_argument("traces", nargs="+", help="per-rank trace JSONs")
    parser.add_argument(
        "-o",
        "--output",
        required=True,
        help="path for the merged Perfetto-loadable trace",
    )
    parser.add_argument(
        "--no-skew-correct",
        action="store_true",
        help="trust wall clocks as-is (skip barrier-anchor alignment)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the skew table + critical path as JSON on stdout",
    )
    args = parser.parse_args(argv)
    try:
        docs = [load_trace(p) for p in args.traces]
        merged, info = merge_traces(
            docs, skew_correct=not args.no_skew_correct
        )
    except (OSError, ValueError, json.JSONDecodeError) as e:
        if isinstance(e, ValueError) and "no events" in str(e):
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(f"error: {e}", file=sys.stderr)
        return 2
    with open(args.output, "w") as f:
        json.dump(merged, f)
    if args.json:
        print(json.dumps(info, indent=2, sort_keys=True))
    else:
        print(render_info(info))
        print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
