"""Cross-process Chrome-trace merge: one timeline, one clock, one verdict.

Usage::

    python -m torchsnapshot_tpu.telemetry.merge rank0.json rank1.json \
        server.json ... -o merged.json [--json]

Each per-process trace written by ``tracing.py`` is self-describing:
its ``metadata`` carries ``clock_epoch_s`` (the wall-clock epoch of
trace ts 0), ``rank``, ``host``, ``pid``, and (for non-rank processes
like a snapserve server) ``role``. The merge

1. maps every event's monotonic ts onto the wall clock,
2. **corrects clock skew** — rank processes align on coord barrier
   instants (``barrier_exit``: every rank passes a given barrier
   generation at approximately one global moment, so per-rank deviation
   from the cross-rank median at shared generations IS that rank's
   clock skew); processes with no barriers (a snapserve server) align
   on **paired flow events**: a client's ``s``/``f`` pair brackets the
   server's ``t`` for the same flow id, so the NTP-style midpoint
   offset estimates the server's skew with the network latency
   cancelled,
3. emits a single Perfetto-loadable trace — each process rendered as
   its own track (rank processes keep ``pid = rank``, named
   ``rank N (host)``; role processes get ``<role> pid P (host)``),
   span ids namespaced per process so cross-process id collisions
   cannot pair a begin in one process with an end in another, all
   timestamps rebased to one monotonic non-negative clock. Flow events
   (``ph: s/t/f``) survive the merge with their shared ids intact —
   Perfetto draws the client→server→client arrows,
4. computes the **cross-process critical path**: which process's
   pipeline activity ended last (gating the operation every other
   process then waited for), that process's dominant phase, and each
   process's slack.

``telemetry.summarize`` recognizes a merged trace and appends the
critical-path section to its per-phase table.

Exit codes: 0 = merged; 1 = no events in any input; 2 = usage error.
"""

import argparse
import json
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

# The pipelined ops whose completion can gate a commit/restore — client
# pipeline ops plus the read plane's serving ops (a server process's
# whole pipeline activity IS serving); instants and orchestration
# wrappers don't gate by themselves. hottier spans are deliberately
# absent: replication runs inside write spans, and the BACKGROUND
# drain completes after the commit by design — counting it would name
# the drain the "gater" of a commit that never waited for it.
_PIPELINE_OPS = (
    "stage",
    "write",
    "read",
    "consume",
    "snapserve.request",
    "snapserve.backend_fetch",
)

_BARRIER_INSTANT = "barrier_exit"
_COMMIT_INSTANTS = ("metadata_committed", "step_marker_committed")
_FLOW_PHASES = ("s", "t", "f")


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare-array Chrome trace variant
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a Chrome trace")
    return doc


def trace_meta(doc: Dict[str, Any], fallback_rank: int) -> Dict[str, Any]:
    """The trace's identity metadata, tolerating traces from before the
    stamp existed (they merge as rank ``fallback_rank`` on an
    uncorrected clock)."""
    meta = doc.get("metadata") or {}
    return {
        "clock_epoch_s": float(meta.get("clock_epoch_s") or 0.0),
        "rank": int(meta["rank"]) if meta.get("rank") is not None else fallback_rank,
        "host": str(meta.get("host") or "?"),
        "pid": int(meta["pid"]) if meta.get("pid") is not None else 0,
        "role": str(meta["role"]) if meta.get("role") else None,
    }


def _process_label(meta: Dict[str, Any]) -> str:
    if meta["role"]:
        return f"{meta['role']} pid {meta['pid']} ({meta['host']})"
    return f"rank {meta['rank']} ({meta['host']})"


def _skew_key(meta: Dict[str, Any]) -> str:
    """The per-process key in the ``skew_s`` table. Rank processes keep
    the bare-rank key (backward compatible); role processes key as
    ``<role>:<pid>``."""
    if meta["role"]:
        return f"{meta['role']}:{meta['pid']}"
    return str(meta["rank"])


def _barrier_walls(
    doc: Dict[str, Any], epoch: float
) -> Dict[Any, float]:
    """``{barrier generation: wall time}`` for this trace's
    barrier-exit instants (first occurrence per generation)."""
    out: Dict[Any, float] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "i" and ev.get("name") == _BARRIER_INSTANT:
            gen = (ev.get("args") or {}).get("gen")
            if gen is not None and gen not in out:
                out[gen] = epoch + ev.get("ts", 0.0) / 1e6
    return out


def _flow_walls(
    doc: Dict[str, Any], epoch: float
) -> Dict[str, Dict[str, float]]:
    """``{flow id: {phase: wall}}`` for this trace's flow events (first
    occurrence per phase per id)."""
    out: Dict[str, Dict[str, float]] = {}
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        if ph not in _FLOW_PHASES:
            continue
        fid = ev.get("id")
        if fid is None:
            continue
        entry = out.setdefault(str(fid), {})
        if ph not in entry:
            entry[ph] = epoch + ev.get("ts", 0.0) / 1e6
    return out


def _median(values: List[float]) -> float:
    # statistics.median averages even counts — the right call for NTP
    # offset estimates (two samples should not arbitrarily pick one).
    return float(statistics.median(values)) if values else 0.0


def compute_skews(
    docs: List[Dict[str, Any]], metas: List[Dict[str, Any]]
) -> List[float]:
    """Per-INPUT clock-skew estimate (seconds to SUBTRACT from that
    trace's wall times).

    Two anchor families, applied in order:

    - **barriers** — at each barrier generation shared by every
      barrier-bearing trace, a trace's deviation from the cross-trace
      median is skew plus barrier-exit jitter; averaged over
      generations.
    - **paired flows** — a trace with no barrier skew (a snapserve
      server) is aligned against already-corrected traces through
      matching flow ids: the client's ``s`` (request out) and ``f``
      (response in) bracket the server's ``t`` (handling), so
      ``t - (s + f)/2`` is the server's offset with the request/response
      latency cancelled (one-way flows fall back to ``t - s``). The
      median over all pairs is the skew.

    Traces with neither anchor get skew 0 (wall clock trusted as-is).
    """
    walls = [
        _barrier_walls(doc, meta["clock_epoch_s"])
        for doc, meta in zip(docs, metas)
    ]
    anchored = [i for i, w in enumerate(walls) if w]
    skews = [0.0] * len(docs)
    have_skew = [False] * len(docs)
    if anchored:
        shared = set(walls[anchored[0]])
        for i in anchored[1:]:
            shared &= set(walls[i])
        samples: Dict[int, List[float]] = {}
        for gen in shared:
            at = [walls[i][gen] for i in anchored]
            median = _median(at)
            for i in anchored:
                samples.setdefault(i, []).append(walls[i][gen] - median)
        for i, vals in samples.items():
            skews[i] = sum(vals) / len(vals)
            have_skew[i] = True

    # Rank processes are the reference frame for the flow pass: with no
    # barrier anchors at all, flow-aligning the CLIENT against an
    # uncorrected server would shift the wrong clock (the estimate is
    # order-dependent without a reference). Rank docs keep their
    # barrier skew (or 0); only role processes are flow-aligned.
    for i, meta in enumerate(metas):
        if meta["role"] is None:
            have_skew[i] = True

    flows = [
        _flow_walls(doc, meta["clock_epoch_s"])
        for doc, meta in zip(docs, metas)
    ]
    for i in range(len(docs)):
        if have_skew[i]:
            continue
        offsets: List[float] = []
        for j in range(len(docs)):
            if i == j or not have_skew[j]:
                continue
            for fid, mine in flows[i].items():
                theirs = flows[j].get(fid)
                if not theirs:
                    continue
                their_skew = skews[j]
                if "t" in mine and "s" in theirs:
                    # I handled a flow they initiated: their s/f
                    # bracket my t.
                    s = theirs["s"] - their_skew
                    f = theirs.get("f")
                    anchor = (s + (f - their_skew)) / 2 if f is not None else s
                    offsets.append(mine["t"] - anchor)
                elif "s" in mine and "t" in theirs:
                    # I initiated a flow they handled.
                    s = mine["s"]
                    f = mine.get("f")
                    anchor = (s + f) / 2 if f is not None else s
                    offsets.append(anchor - (theirs["t"] - their_skew))
        if offsets:
            skews[i] = _median(offsets)
            have_skew[i] = True
    return skews


def _assign_process_ids(
    metas: List[Dict[str, Any]]
) -> List[int]:
    """Output pid per input: rank processes keep ``pid = rank`` (the
    established convention summarize/tests rely on); role processes
    (and a second process claiming an already-taken rank — e.g. a
    forked child's re-suffixed trace) get distinct pids above the rank
    range."""
    taken: set = set()
    out: List[int] = []
    extra = None
    for meta in metas:
        if meta["role"] is None and meta["rank"] not in taken:
            taken.add(meta["rank"])
            out.append(meta["rank"])
        else:
            out.append(-1)  # assigned below, above the rank range
    base = max(taken, default=-1) + 1
    extra = base + 10000
    for i, pid in enumerate(out):
        if pid < 0:
            out[i] = extra
            extra += 1
    return out


def merge_traces(
    docs: List[Dict[str, Any]], skew_correct: bool = True
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Merge per-process traces onto one corrected clock.

    Returns ``(merged trace doc, info)`` where info carries the skew
    table, the cross-process flow count, and the critical-path verdict.
    """
    metas = [trace_meta(doc, i) for i, doc in enumerate(docs)]
    seen: Dict[Tuple, int] = {}
    for i, meta in enumerate(metas):
        ident = (meta["role"], meta["rank"], meta["pid"])
        if ident in seen:
            raise ValueError(
                f"duplicate process identity across input traces: "
                f"{_process_label(meta)} (inputs {seen[ident]} and {i}) "
                f"— each input must be a distinct process's trace"
            )
        seen[ident] = i
    skews = (
        compute_skews(docs, metas)
        if skew_correct
        else [0.0] * len(docs)
    )
    out_pids = _assign_process_ids(metas)
    # Per-process skew-table keys: first claimant of a rank keeps the
    # bare-rank key (backward compatible); a duplicate-rank process (a
    # forked child's re-suffixed trace) disambiguates by os pid so its
    # skew cannot silently overwrite the parent's.
    skew_keys: List[str] = []
    used_keys: set = set()
    for m in metas:
        key = _skew_key(m)
        if key in used_keys:
            key = f"{key}:{m['pid']}"
        used_keys.add(key)
        skew_keys.append(key)

    # Corrected wall time of every event; the merged clock starts at the
    # earliest event (ts >= 0, monotonic by construction: one shared
    # wall clock after skew subtraction).
    t_base: Optional[float] = None
    per_doc_events: List[List[Tuple[float, Dict[str, Any]]]] = []
    for doc, meta, skew in zip(docs, metas, skews):
        epoch = meta["clock_epoch_s"] - skew
        rows = []
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue  # per-process metadata is re-emitted below
            wall = epoch + ev.get("ts", 0.0) / 1e6
            rows.append((wall, ev))
            t_base = wall if t_base is None else min(t_base, wall)
        per_doc_events.append(rows)
    if t_base is None:
        raise ValueError("no events in any input trace")

    # Cross-process flows: a flow id appearing in >= 2 inputs is a drawn
    # arrow (the acceptance telemetry for the snapxray CI smoke).
    flow_owners: Dict[str, set] = {}
    for i, doc in enumerate(docs):
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") in _FLOW_PHASES and ev.get("id") is not None:
                flow_owners.setdefault(str(ev["id"]), set()).add(i)
    cross_flows = sum(1 for owners in flow_owners.values() if len(owners) > 1)

    labels = {
        out_pids[i]: _process_label(meta) for i, meta in enumerate(metas)
    }
    merged_events: List[Dict[str, Any]] = []
    for i, (meta, rows) in enumerate(zip(metas, per_doc_events)):
        pid = out_pids[i]
        merged_events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": labels[pid]},
            }
        )
        ns = f"r{pid}" if meta["role"] is None else f"p{pid}"
        for wall, ev in rows:
            out = dict(ev)
            out["ts"] = (wall - t_base) * 1e6
            out["pid"] = pid
            if "id" in out and ev.get("ph") not in _FLOW_PHASES:
                # Namespace span ids per process: every trace counts ids
                # from 1, and a cross-process collision would let a
                # begin in process A pair with an end in process B.
                # Flow ids are NOT namespaced — their whole point is to
                # match across processes.
                out["id"] = f"{ns}:{out['id']}"
            merged_events.append(out)
    merged_events.sort(key=lambda e: e.get("ts", 0.0))

    info = {
        "ranks": sorted(m["rank"] for m in metas if m["role"] is None),
        "processes": [
            {
                "pid": out_pids[i],
                "label": labels[out_pids[i]],
                "rank": m["rank"] if m["role"] is None else None,
                "role": m["role"],
                # The process's key in the skew_s table (role processes
                # key by their ORIGINAL os pid, not the merged pid) —
                # what lets summarize join the two per merged pid.
                "skew_key": skew_keys[i],
            }
            for i, m in enumerate(metas)
        ],
        "skew_s": {
            skew_keys[i]: round(skews[i], 6) for i in range(len(metas))
        },
        "t_base_epoch_s": t_base,
        "cross_process_flows": cross_flows,
        "critical_path": critical_path(merged_events, labels=labels),
    }
    merged = {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged": True,
            "ranks": info["ranks"],
            "processes": info["processes"],
            "skew_s": info["skew_s"],
            "cross_process_flows": cross_flows,
            "clock_epoch_s": t_base,
            "tracer": "torchsnapshot_tpu",
        },
    }
    return merged, info


def critical_path(
    events: List[Dict[str, Any]],
    labels: Optional[Dict[int, str]] = None,
) -> Optional[Dict[str, Any]]:
    """Which process/phase gated the operation.

    Per process (merged pid), find the end time of its last pipeline-op
    span (the work completion waits for). The **gating process** is the
    one whose pipeline ended last; every other process's slack is how
    long it sat finished while the gater worked. The commit instant
    (when present) confirms the ordering: it can only land after the
    gating process's last write.

    ``gating_rank`` / per-row ``rank`` keep the merged pid for backward
    compatibility (rank processes merge with ``pid = rank``);
    ``gating_process`` / per-row ``process`` carry the human label when
    the merge supplied one.
    """
    labels = labels or {}
    begins: Dict[Any, Dict[str, Any]] = {}
    last_end: Dict[int, float] = {}
    last_phase: Dict[int, str] = {}
    commit_ts: Optional[float] = None
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "")
        if ph == "i" and name in _COMMIT_INSTANTS:
            ts = ev.get("ts", 0.0)
            commit_ts = ts if commit_ts is None else max(commit_ts, ts)
            continue
        if name not in _PIPELINE_OPS:
            continue
        if ph == "b":
            begins[(ev.get("pid"), ev.get("id"), name)] = ev
        elif ph == "e":
            b = begins.pop((ev.get("pid"), ev.get("id"), name), None)
            if b is None:
                continue
            rank = int(ev.get("pid", 0))
            end = ev.get("ts", 0.0)
            if end >= last_end.get(rank, -1.0):
                last_end[rank] = end
                last_phase[rank] = name
        elif ph == "X":
            rank = int(ev.get("pid", 0))
            end = ev.get("ts", 0.0) + ev.get("dur", 0)
            if end >= last_end.get(rank, -1.0):
                last_end[rank] = end
                last_phase[rank] = name
    if not last_end:
        return None
    gating = max(last_end, key=lambda r: last_end[r])
    gate_end = last_end[gating]
    return {
        "gating_rank": gating,
        "gating_process": labels.get(gating, f"rank {gating}"),
        "gating_phase": last_phase[gating],
        "gate_end_s": round(gate_end / 1e6, 6),
        "commit_at_s": (
            round(commit_ts / 1e6, 6) if commit_ts is not None else None
        ),
        "per_rank": [
            {
                "rank": r,
                "process": labels.get(r, f"rank {r}"),
                "last_phase": last_phase[r],
                "last_end_s": round(last_end[r] / 1e6, 6),
                "slack_s": round((gate_end - last_end[r]) / 1e6, 6),
            }
            for r in sorted(last_end)
        ],
    }


def render_info(info: Dict[str, Any]) -> str:
    lines: List[str] = []
    processes = info.get("processes") or []
    if any(p.get("role") for p in processes):
        lines.append(
            f"merged {len(processes)} process trace(s): "
            + ", ".join(p["label"] for p in processes)
        )
    else:
        lines.append(
            f"merged {len(info['ranks'])} rank trace(s): "
            f"ranks {', '.join(str(r) for r in info['ranks'])}"
        )
    flows = info.get("cross_process_flows") or 0
    if flows:
        lines.append(f"cross-process flow arrows: {flows}")
    skews = info.get("skew_s") or {}
    if any(abs(v) > 0 for v in skews.values()):
        lines.append("per-process clock skew (s, corrected):")
        # Numeric keys (ranks) in numeric order, then role keys.
        for r in sorted(
            skews, key=lambda k: (0, int(k), "") if k.isdigit() else (1, 0, k)
        ):
            lines.append(f"  {r}: {skews[r]:+.6f}")
    else:
        lines.append("per-process clock skew: none detected (or no "
                     "shared anchors)")
    cp = info.get("critical_path")
    if cp:
        lines.append(
            f"critical path: {cp.get('gating_process') or 'rank ' + str(cp['gating_rank'])} "
            f"gated the operation (last {cp['gating_phase']} ended at "
            f"{cp['gate_end_s']:.3f}s)"
        )
        for row in cp["per_rank"]:
            lines.append(
                f"  {row.get('process') or 'rank ' + str(row['rank'])}: "
                f"last {row['last_phase']} ended "
                f"{row['last_end_s']:.3f}s, slack {row['slack_s']:.3f}s"
            )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry.merge",
        description="Merge per-process snapshot traces (ranks + read-"
        "plane servers) onto one skew-corrected clock.",
    )
    parser.add_argument("traces", nargs="+", help="per-process trace JSONs")
    parser.add_argument(
        "-o",
        "--output",
        required=True,
        help="path for the merged Perfetto-loadable trace",
    )
    parser.add_argument(
        "--no-skew-correct",
        action="store_true",
        help="trust wall clocks as-is (skip barrier/flow alignment)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the skew table + critical path as JSON on stdout",
    )
    args = parser.parse_args(argv)
    try:
        docs = [load_trace(p) for p in args.traces]
        merged, info = merge_traces(
            docs, skew_correct=not args.no_skew_correct
        )
    except (OSError, ValueError, json.JSONDecodeError) as e:
        if isinstance(e, ValueError) and "no events" in str(e):
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(f"error: {e}", file=sys.stderr)
        return 2
    with open(args.output, "w") as f:
        json.dump(merged, f)
    if args.json:
        print(json.dumps(info, indent=2, sort_keys=True))
    else:
        print(render_info(info))
        print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
