"""Consume micro-profiler: sub-step attribution inside the restore path.

The flight recorder and the ``consume-dominated-restore`` doctor rule
can say a restore spent 176s in ``consume`` against 0.76s of ``read``
(BENCH_r05) — but not WHERE inside consume the time went, which is the
number the streaming-restore rewrite (ROADMAP item 1) must be planned
from and certified against. This module is that number: an always-on,
contextvar-scoped accumulator the restore root opens and every buffer
consumer notes into, at per-leaf/per-chunk granularity:

==================  ====================================================
sub-step            what it times
==================  ====================================================
read_wait           a completed read's payload sitting in the scheduler
                    queue before its consume dispatched (budget / device-
                    budget / executor pressure — NOT part of consume wall)
deserialize         pickled-object loads (``bytes_to_object``) and raw
                    byte→array reinterpretation
decode              codec work: ``decompress_payload`` and chunk-store
                    codec decode (zlib/zstd/int8)
verify              integrity: checksum verification, streaming crc
                    folds, content-fingerprint checks
reassemble          host memcpy: scattering chunk views into region
                    buffers, splicing ranged sub-reads into assembly
                    buffers
device_put          H2D transfers: streamed chunk puts and the
                    finalize-time batched/chunked device placement
staging_release     freeing assembly/staging buffers and re-crediting
                    scheduler budget reservations
other               consume wall the sub-steps above did not account
                    for (event-loop/executor scheduling, GIL waits) —
                    computed at collect time so the breakdown SUMS to
                    the consume wall exactly
==================  ====================================================

Scoping matches the snapserve read-plane attribution: the profile is a
contextvar set in the restoring thread; consumers CAPTURE it (and the
ambient trace id) at plan-build time — which happens in that thread —
so notes from executor threads land in the right restore even with two
restores in flight. Cost when nothing special is happening: one
``time.monotonic()`` pair per noted sub-step per chunk, well under the
<2% restore-wall budget bench's restore section enforces; sub-step
tracing spans are emitted only while tracing is enabled.
"""

import contextvars
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from .. import tracing

# Sub-steps that run INSIDE consume_buffer (their seconds reconcile
# against the scheduler's consume op seconds); read_wait happens between
# read completion and consume dispatch and is reported beside them.
IN_CONSUME_SUBSTEPS = (
    "deserialize",
    "decode",
    "verify",
    "reassemble",
    "device_put",
    "staging_release",
)
SUBSTEPS = ("read_wait",) + IN_CONSUME_SUBSTEPS


class ConsumeProfile:
    """Thread-safe sub-step accumulator for ONE restore."""

    __slots__ = ("_lock", "_agg", "trace_id")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # substep -> [count, seconds, bytes]
        self._agg: Dict[str, list] = {}
        # Captured at begin() so executor-thread sub-step spans can
        # stamp the restore's trace id without a contextvar handoff.
        self.trace_id = tracing.current_trace_id()

    def note(self, substep: str, seconds: float, nbytes: int = 0) -> None:
        with self._lock:
            entry = self._agg.get(substep)
            if entry is None:
                entry = self._agg[substep] = [0, 0.0, 0]
            entry[0] += 1
            entry[1] += seconds
            entry[2] += nbytes

    def summary(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                substep: {
                    "count": entry[0],
                    "seconds": round(entry[1], 6),
                    "bytes": entry[2],
                }
                for substep, entry in sorted(self._agg.items())
            }


_SCOPE: "contextvars.ContextVar[Optional[ConsumeProfile]]" = (
    contextvars.ContextVar("tpusnapshot_consume_profile", default=None)
)


def begin() -> Tuple[ConsumeProfile, Any]:
    """Open a per-restore profiling scope in the restoring thread."""
    profile = ConsumeProfile()
    return profile, _SCOPE.set(profile)


def current() -> Optional[ConsumeProfile]:
    """The active profile — captured by consumers at plan-build time."""
    return _SCOPE.get()


def collect(
    token: Any, consume_s: Optional[float] = None
) -> Optional[Dict[str, Any]]:
    """Close the scope and build the flight-report block. ``consume_s``
    (the scheduler's consume op seconds for this restore) yields the
    ``other`` bucket, so the in-consume sub-steps plus ``other`` sum to
    the consume wall exactly. None when nothing was noted (a restore of
    primitives only)."""
    if token is None:
        return None
    profile, var_token = token
    try:
        _SCOPE.reset(var_token)
    except ValueError:
        pass  # reset from a different context: scope still collected
    substeps = profile.summary()
    if not substeps and not consume_s:
        return None
    block: Dict[str, Any] = {"substeps": substeps}
    accounted = sum(
        substeps.get(s, {}).get("seconds", 0.0) for s in IN_CONSUME_SUBSTEPS
    )
    block["accounted_s"] = round(accounted, 6)
    if consume_s is not None:
        block["consume_s"] = round(consume_s, 6)
        other = max(0.0, consume_s - accounted)
        block["substeps"]["other"] = {
            "count": 0,
            "seconds": round(other, 6),
            "bytes": 0,
        }
    return block


@contextmanager
def substep(
    profile: Optional[ConsumeProfile], name: str, nbytes: int = 0
):
    """Time one sub-step into ``profile``. A plain passthrough when no
    restore scope is active (``profile`` None) — verify()/read_object
    paths reuse the instrumented consumers, and emitting
    ``consume.<name>`` spans for them would hand summarize a bogus
    consume-breakdown section for an operation that never restored.
    While tracing is enabled, a span is emitted alongside the note,
    stamped with the restore's trace id even from executor threads."""
    if profile is None:
        yield
        return
    if tracing.enabled():
        span_args: Dict[str, Any] = {"bytes": nbytes}
        if profile.trace_id is not None:
            span_args["trace"] = profile.trace_id
        with tracing.span(f"consume.{name}", **span_args):
            t0 = time.monotonic()
            try:
                yield
            finally:
                profile.note(name, time.monotonic() - t0, nbytes)
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        profile.note(name, time.monotonic() - t0, nbytes)
