"""Consume micro-profiler: sub-step attribution inside the restore path.

The flight recorder and the ``consume-dominated-restore`` doctor rule
can say a restore spent 176s in ``consume`` against 0.76s of ``read``
(BENCH_r05) — but not WHERE inside consume the time went, which is the
number the streaming-restore rewrite (ROADMAP item 1) must be planned
from and certified against. This module is that number: an always-on,
contextvar-scoped accumulator the restore root opens and every buffer
consumer notes into, at per-leaf/per-chunk granularity:

==================  ====================================================
sub-step            what it times
==================  ====================================================
read_wait           a completed read's payload sitting in the scheduler
                    queue before its consume dispatched (budget / device-
                    budget / executor pressure — NOT part of consume wall)
deserialize         pickled-object loads (``bytes_to_object``) and raw
                    byte→array reinterpretation
decode              codec work: ``decompress_payload`` and chunk-store
                    codec decode (zlib/zstd/int8)
verify              integrity: checksum verification, streaming crc
                    folds, content-fingerprint checks
reassemble          host memcpy: scattering chunk views into region
                    buffers, splicing ranged sub-reads into assembly
                    buffers
device_put          H2D transfers issued from INSIDE consume executors
                    (small-region batched puts at a consume-triggered
                    finalize)
staging_release     freeing assembly/staging buffers and re-crediting
                    scheduler budget reservations
pool_wait           waiting for a staging-pool buffer at pool capacity
                    (staging_pool.py — budget pressure made visible)
h2d_overlap         the overlap engine's H2D transfer wall
                    (ops/transfer.py H2DPipeline) — UNION time across
                    concurrent workers so bytes/seconds is delivered
                    link GB/s; concurrent with reads/consumes, NOT
                    part of consume wall
overlap_other       in-consume-named work that ran outside any consume
                    executor (engine-triggered finalize placement,
                    donation waits) — beside the wall, kept separate
                    so h2d_overlap's GB/s certificate stays pure
other               consume wall the sub-steps above did not account
                    for (event-loop/executor scheduling, GIL waits) —
                    computed at collect time so the breakdown SUMS to
                    the consume wall exactly
==================  ====================================================

Scoping matches the snapserve read-plane attribution: the profile is a
contextvar set in the restoring thread; consumers CAPTURE it (and the
ambient trace id) at plan-build time — which happens in that thread —
so notes from executor threads land in the right restore even with two
restores in flight. Cost when nothing special is happening: one
``time.monotonic()`` pair per noted sub-step per chunk, well under the
<2% restore-wall budget bench's restore section enforces; sub-step
tracing spans are emitted only while tracing is enabled.
"""

import contextvars
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from .. import tracing

# Sub-steps that run INSIDE consume_buffer (their seconds reconcile
# against the scheduler's consume op seconds); the OVERLAP sub-steps
# happen outside the consume wall and are reported beside them:
# read_wait between read completion and consume dispatch, h2d_overlap
# on the H2D overlap engine's transfer threads (ops/transfer.py
# H2DPipeline) — device placement and buffer donation the streaming
# fast path moved OFF the consume executors so it rides concurrently
# with reads and decodes still in flight.
IN_CONSUME_SUBSTEPS = (
    "deserialize",
    "decode",
    "verify",
    "reassemble",
    "device_put",
    "staging_release",
    "pool_wait",
)
# Beside-the-wall buckets: read_wait (scheduler queueing), h2d_overlap
# (the overlap engine's transfers — union time, see overlap_span),
# overlap_other (in-consume-named work that ran OUTSIDE a consume
# section, e.g. an engine-triggered finalize's device placement and
# buffer donation — kept separate from h2d_overlap so the engine's
# delivered-GB/s certificate is never polluted by finalize bytes).
OVERLAP_SUBSTEPS = ("read_wait", "h2d_overlap", "overlap_other")
SUBSTEPS = OVERLAP_SUBSTEPS + IN_CONSUME_SUBSTEPS


class ConsumeProfile:
    """Thread-safe sub-step accumulator for ONE restore."""

    __slots__ = ("_lock", "_agg", "trace_id", "_ov_active", "_ov_start")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # substep -> [count, seconds, bytes]
        self._agg: Dict[str, list] = {}
        # Captured at begin() so executor-thread sub-step spans can
        # stamp the restore's trace id without a contextvar handoff.
        self.trace_id = tracing.current_trace_id()
        # Union-time clock for the overlap engine: h2d_overlap seconds
        # count wall during which >= 1 transfer was in flight for THIS
        # restore — summing per-call walls across depth-N concurrent
        # workers would overstate seconds by up to the depth factor and
        # understate the delivered GB/s the certificate is built from.
        self._ov_active = 0
        self._ov_start = 0.0

    def note(self, substep: str, seconds: float, nbytes: int = 0) -> None:
        with self._lock:
            entry = self._agg.get(substep)
            if entry is None:
                entry = self._agg[substep] = [0, 0.0, 0]
            entry[0] += 1
            entry[1] += seconds
            entry[2] += nbytes

    def _overlap_enter(self) -> None:
        with self._lock:
            if self._ov_active == 0:
                self._ov_start = time.monotonic()
            self._ov_active += 1

    def _overlap_exit(self, nbytes: int) -> None:
        with self._lock:
            self._ov_active -= 1
            entry = self._agg.get("h2d_overlap")
            if entry is None:
                entry = self._agg["h2d_overlap"] = [0, 0.0, 0]
            entry[0] += 1
            entry[2] += nbytes
            if self._ov_active == 0:
                entry[1] += time.monotonic() - self._ov_start

    def summary(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                substep: {
                    "count": entry[0],
                    "seconds": round(entry[1], 6),
                    "bytes": entry[2],
                }
                for substep, entry in sorted(self._agg.items())
            }


_SCOPE: "contextvars.ContextVar[Optional[ConsumeProfile]]" = (
    contextvars.ContextVar("tpusnapshot_consume_profile", default=None)
)

# Consume-section marker (thread-local): consumer executor bodies wrap
# their work in consume_section() so sub-step notes can tell "inside a
# scheduler consume span" from "on the overlap side". The same code
# (e.g. ArrayRestorePlan.finalize) runs on either side depending on
# which completion fired last; an in-consume-named note recorded
# OUTSIDE a consume section is pipeline work that overlapped the
# consume wall, so it folds into ``overlap_other`` (NOT h2d_overlap —
# that bucket is reserved for the engine's own transfer clock) —
# keeping the in-consume sub-steps summing exactly to the consume wall.
_SECTION = threading.local()


@contextmanager
def consume_section():
    prev = getattr(_SECTION, "active", False)
    _SECTION.active = True
    try:
        yield
    finally:
        _SECTION.active = prev


def in_consume_section() -> bool:
    return getattr(_SECTION, "active", False)


def _route(name: str) -> str:
    if name in IN_CONSUME_SUBSTEPS and not in_consume_section():
        return "overlap_other"
    return name


def begin() -> Tuple[ConsumeProfile, Any]:
    """Open a per-restore profiling scope in the restoring thread."""
    profile = ConsumeProfile()
    return profile, _SCOPE.set(profile)


def current() -> Optional[ConsumeProfile]:
    """The active profile — captured by consumers at plan-build time."""
    return _SCOPE.get()


def collect(
    token: Any, consume_s: Optional[float] = None
) -> Optional[Dict[str, Any]]:
    """Close the scope and build the flight-report block. ``consume_s``
    (the scheduler's consume op seconds for this restore) yields the
    ``other`` bucket, so the in-consume sub-steps plus ``other`` sum to
    the consume wall exactly. None when nothing was noted (a restore of
    primitives only)."""
    if token is None:
        return None
    profile, var_token = token
    try:
        _SCOPE.reset(var_token)
    except ValueError:
        pass  # reset from a different context: scope still collected
    substeps = profile.summary()
    if not substeps and not consume_s:
        return None
    block: Dict[str, Any] = {"substeps": substeps}
    accounted = sum(
        substeps.get(s, {}).get("seconds", 0.0) for s in IN_CONSUME_SUBSTEPS
    )
    block["accounted_s"] = round(accounted, 6)
    if consume_s is not None:
        block["consume_s"] = round(consume_s, 6)
        other = max(0.0, consume_s - accounted)
        block["substeps"]["other"] = {
            "count": 0,
            "seconds": round(other, 6),
            "bytes": 0,
        }
    return block


@contextmanager
def overlap_span(profile: Optional[ConsumeProfile], nbytes: int = 0):
    """Time one overlap-engine transfer into ``h2d_overlap`` with
    UNION-time semantics: concurrent transfers for one restore advance
    the clock once, so bytes/seconds is the engine's delivered link
    throughput at any depth. Emits a ``consume.h2d_overlap`` span per
    transfer while tracing is on (spans may overlap — that is the
    point)."""
    if profile is None:
        yield
        return
    if tracing.enabled():
        span_args: Dict[str, Any] = {"bytes": nbytes}
        if profile.trace_id is not None:
            span_args["trace"] = profile.trace_id
        with tracing.span("consume.h2d_overlap", **span_args):
            profile._overlap_enter()
            try:
                yield
            finally:
                profile._overlap_exit(nbytes)
        return
    profile._overlap_enter()
    try:
        yield
    finally:
        profile._overlap_exit(nbytes)


@contextmanager
def substep(
    profile: Optional[ConsumeProfile], name: str, nbytes: int = 0
):
    """Time one sub-step into ``profile``. A plain passthrough when no
    restore scope is active (``profile`` None) — verify()/read_object
    paths reuse the instrumented consumers, and emitting
    ``consume.<name>`` spans for them would hand summarize a bogus
    consume-breakdown section for an operation that never restored.
    While tracing is enabled, a span is emitted alongside the note,
    stamped with the restore's trace id even from executor threads."""
    if profile is None:
        yield
        return
    name = _route(name)
    if tracing.enabled():
        span_args: Dict[str, Any] = {"bytes": nbytes}
        if profile.trace_id is not None:
            span_args["trace"] = profile.trace_id
        with tracing.span(f"consume.{name}", **span_args):
            t0 = time.monotonic()
            try:
                yield
            finally:
                profile.note(name, time.monotonic() - t0, nbytes)
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        profile.note(name, time.monotonic() - t0, nbytes)
