"""snapscope's publishing half: the live runtime sampler.

Everything else in the telemetry subsystem is either post-hoc (flight
reports, the ledger) or event-driven (progress records pulse when the
pipeline completes work). Neither can answer the questions that matter
while a background tier-down is the only thing between an acked
checkpoint and data loss: *how deep is the drain queue right now? how
old is its oldest item? how many committed bytes exist in RAM only? is
the scheduler stalled on its memory budget?* The sampler answers them
by periodically snapshotting runtime state — no hooks in the operation
paths, so it can never slow or fail them:

- **hot-tier drain pipeline** (``hottier.runtime.introspect()``): queue
  depth, oldest pending-object age, at-risk (committed-but-undrained)
  bytes per root, stranded-drain count, per-host replica occupancy vs
  capacity, drain heartbeat age ("event-loop lag");
- **scheduler budget**: live occupancy and stalled-right-now state (the
  gauges the pipelines maintain), plus the stall-seconds counters and
  high-water marks;
- **goodput**: the accountant's current attribution, when it has data.

Samples land in three sinks, all best-effort:

- a bounded in-memory **ring buffer** (``samples()``), what in-process
  consumers (the ops view, the SLO engine's live rules, tests) read;
- a local **JSONL statusfile** ``<dir>/rank<N>.scope.jsonl``
  (``TPUSNAPSHOT_PROGRESS_DIR`` — the same live-ops directory the
  progress statusfiles use), size-rotated so it stays bounded;
- optionally a **storage object** ``.scope/rank<N>`` in a snapshot
  prefix (latest sample only, atomically replaced), so
  ``python -m torchsnapshot_tpu.telemetry.ops <url>`` can render the
  drain state from any machine that can read the snapshot's storage.
  Scope objects are operational debris like progress records:
  ``Snapshot.delete`` removes them and ``reconcile()`` sweeps aged
  orphans (they must never survive a deleted snapshot or a detected
  crash).

Crash isolation is the load-bearing contract: the sampler thread is a
daemon, every sampling pass is wrapped, an exception is counted
(``tpusnapshot_sampler_errors_total``) and logged once per distinct
error — it never propagates, and nothing on the take/restore path ever
waits on the sampler.
"""

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from . import metrics as _m
from .metrics import REGISTRY

logger = logging.getLogger(__name__)

SAMPLE_FORMAT_VERSION = 1

# Storage-object prefix for published scope records (one per rank),
# mirroring the .progress/ lifecycle: swept by Snapshot.delete and by
# reconcile's age-guarded debris pass.
SCOPE_PREFIX = ".scope"


def scope_path(rank: int) -> str:
    return f"{SCOPE_PREFIX}/rank{rank}"


def statusfile_name(rank: int) -> str:
    return f"rank{rank}.scope.jsonl"


_INTERVAL_ENV_VAR = "TPUSNAPSHOT_SAMPLER_INTERVAL_S"
_DEFAULT_INTERVAL_S = 2.0
_DIR_ENV_VAR = "TPUSNAPSHOT_PROGRESS_DIR"  # shared live-ops directory
_RING_ENV_VAR = "TPUSNAPSHOT_SAMPLER_RING"
_DEFAULT_RING = 512
# Statusfile rotation cap: past this, the JSONL is rewritten from the
# ring (bounded by construction) instead of appended forever.
_STATUSFILE_CAP_BYTES = 1 << 20


def _scalar(name: str, **labels: str) -> float:
    return REGISTRY.gauge(name, **labels).value


class RuntimeSampler:
    """One process's background runtime sampler (see module docstring).

    ``storage_url`` (optional) enables the ``.scope/rank<N>`` storage
    sink; the plugin is resolved lazily on the sampler thread so even a
    hanging backend cannot block the caller that started the sampler.
    """

    def __init__(
        self,
        rank: int = 0,
        interval_s: Optional[float] = None,
        ring: Optional[int] = None,
        statusfile_dir: Optional[str] = None,
        storage_url: Optional[str] = None,
    ) -> None:
        self.rank = rank
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get(_INTERVAL_ENV_VAR, _DEFAULT_INTERVAL_S)
                )
            except ValueError:
                interval_s = _DEFAULT_INTERVAL_S
        self.interval_s = max(0.05, interval_s)
        if ring is None:
            try:
                ring = int(os.environ.get(_RING_ENV_VAR, _DEFAULT_RING))
            except ValueError:
                ring = _DEFAULT_RING
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=max(1, ring))
        self._dir = (
            statusfile_dir
            if statusfile_dir is not None
            else os.environ.get(_DIR_ENV_VAR)
        )
        self.storage_url = storage_url
        self._storage: Any = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._last_error: Optional[str] = None
        self.error_count = 0

    # ------------------------------------------------------------ sampling

    def build_sample(self) -> Dict[str, Any]:
        """One sample of the live runtime state (may raise — callers go
        through :meth:`sample_once`, which is the crash-isolated path)."""
        from .. import hottier
        from . import goodput as _goodput

        with self._lock:
            self._seq += 1
            seq = self._seq
        sample: Dict[str, Any] = {
            "format_version": SAMPLE_FORMAT_VERSION,
            "ts_epoch_s": round(time.time(), 3),
            "seq": seq,
            "rank": self.rank,
            "pid": os.getpid(),
            "hot_tier": hottier.introspect(),
            "scheduler": {
                pipeline: {
                    "budget_in_use_bytes": int(
                        _scalar(_m.SCHED_BUDGET_IN_USE, pipeline=pipeline)
                    ),
                    "stalled": bool(
                        _scalar(_m.SCHED_BUDGET_STALLED, pipeline=pipeline)
                    ),
                    "stall_s_total": round(
                        REGISTRY.counter(
                            _m.SCHED_STALL_SECONDS, pipeline=pipeline
                        ).value,
                        6,
                    ),
                    "high_water_bytes": int(
                        _scalar(_m.SCHED_BUDGET_HWM, pipeline=pipeline)
                    ),
                }
                for pipeline in ("write", "read")
            },
            "goodput": (
                _goodput.snapshot() if _goodput.has_data() else None
            ),
        }
        # Wire observability (wiretap/snapflight): cumulative per-op
        # view — the slo live rule diffs consecutive samples for
        # fresh deadline misses; absent when nothing crossed a wire.
        from .. import wiretap

        wire = wiretap.sample_block()
        if wire.get("ops"):
            sample["wire"] = wire
        # Host memory plane (memwatch/snapmem): the cross-domain
        # occupancy table + headroom headline — the slo live rule
        # tracks residual drift and overcommit across samples; absent
        # when no domain is registered. Includes the staging pool's
        # retained/leased/high-water split via its domain entry.
        from . import memwatch

        mem = memwatch.sample_block()
        if mem.get("domains"):
            sample["memory"] = mem
        return sample

    def sample_once(self) -> Optional[Dict[str, Any]]:
        """Take one sample and publish it to every sink; returns the
        sample, or None when the pass failed (counted, never raised) —
        the crash-isolation boundary the tests pin."""
        try:
            sample = self.build_sample()
        except Exception as e:
            self._note_error(e, where="build")
            return None
        self._ring.append(sample)
        REGISTRY.counter(_m.SAMPLER_SAMPLES).inc()
        try:
            self._emit_file(sample)
        except Exception as e:
            self._note_error(e, where="statusfile")
        try:
            self._emit_storage(sample)
        except Exception as e:
            self._note_error(e, where="storage")
        return sample

    def _note_error(self, e: BaseException, where: str) -> None:
        self.error_count += 1
        REGISTRY.counter(_m.SAMPLER_ERRORS).inc()
        msg = f"{where}: {e!r}"
        if msg != self._last_error:
            # Log each distinct failure once, not once per tick — a
            # persistently broken sink must not flood the log at 0.5 Hz.
            self._last_error = msg
            logger.warning("runtime sampler %s failed: %r", where, e)

    # -------------------------------------------------------------- sinks

    def _emit_file(self, sample: Dict[str, Any]) -> None:
        if self._dir is None:
            return
        os.makedirs(self._dir, exist_ok=True)
        target = os.path.join(self._dir, statusfile_name(self.rank))
        line = json.dumps(sample, sort_keys=True) + "\n"
        try:
            size = os.path.getsize(target)
        except OSError:
            size = 0
        if size + len(line) > _STATUSFILE_CAP_BYTES:
            # Rotate by rewriting from the ring: bounded on disk, and
            # the tail a reader wants (recent samples) survives.
            tmp = f"{target}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                for s in list(self._ring):
                    f.write(json.dumps(s, sort_keys=True) + "\n")
            # snapcheck: disable=durability-order -- ephemeral live state; a sample lost to a crash is re-sampled next tick
            os.replace(tmp, target)
        else:
            with open(target, "a") as f:
                # snapcheck: disable=durability-order -- ephemeral live state; a sample lost to a crash is re-sampled next tick
                f.write(line)

    def _emit_storage(self, sample: Dict[str, Any]) -> None:
        if self.storage_url is None:
            return
        if self._storage is None:
            from ..storage_plugin import url_to_storage_plugin

            self._storage = url_to_storage_plugin(self.storage_url)
        import asyncio

        from ..io_types import IOReq

        asyncio.run(
            self._storage.write(
                IOReq(
                    path=scope_path(self.rank),
                    data=json.dumps(sample, sort_keys=True).encode("utf-8"),
                )
            )
        )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "RuntimeSampler":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name="tpusnapshot-scope-sampler",
                daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.sample_once()
            self._stop.wait(self.interval_s)

    def stop(self, final_sample: bool = True) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        if final_sample:
            self.sample_once()
        storage, self._storage = self._storage, None
        if storage is not None:
            try:
                storage.close()
            except Exception as e:
                logger.debug("sampler storage close failed: %r", e)

    def samples(self) -> List[Dict[str, Any]]:
        return list(self._ring)

    def latest(self) -> Optional[Dict[str, Any]]:
        return self._ring[-1] if self._ring else None


# ------------------------------------------------------- module-level API

_SAMPLER: Optional[RuntimeSampler] = None
_SAMPLER_LOCK = threading.Lock()


def start(
    storage_url: Optional[str] = None, **kwargs: Any
) -> RuntimeSampler:
    """Start (or return) the process-wide sampler. ``storage_url``
    additionally publishes ``.scope/rank<N>`` into that snapshot
    prefix."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            _SAMPLER = RuntimeSampler(storage_url=storage_url, **kwargs)
            _SAMPLER.start()
        return _SAMPLER


def stop(final_sample: bool = True) -> None:
    global _SAMPLER
    with _SAMPLER_LOCK:
        sampler, _SAMPLER = _SAMPLER, None
    if sampler is not None:
        sampler.stop(final_sample=final_sample)


def current() -> Optional[RuntimeSampler]:
    return _SAMPLER


# ---------------------------------------------------------------- reading


def parse_statusfile(path: str) -> List[Dict[str, Any]]:
    """All parseable samples from one ``rank<N>.scope.jsonl`` (torn tail
    lines are skipped — a concurrent writer is expected)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return out
    for line in raw.splitlines():
        if not line.strip():
            continue
        try:
            doc = json.loads(line.decode("utf-8"))
        # Torn/garbage line IS the expected answer mid-append.
        except Exception:  # snapcheck: disable=swallowed-exception -- torn-line probe
            continue
        if isinstance(doc, dict) and "format_version" in doc:
            out.append(doc)
    return out


def collect_statusfiles(directory: str) -> Dict[int, List[Dict[str, Any]]]:
    """``{rank: samples}`` from every scope statusfile under
    ``directory``."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("rank") and name.endswith(".scope.jsonl")):
            continue
        samples = parse_statusfile(os.path.join(directory, name))
        if samples:
            out[int(samples[-1].get("rank", 0))] = samples
    return out


async def acollect_storage_records(
    storage: Any,
) -> Dict[int, List[Dict[str, Any]]]:
    """Latest published sample per rank from ``.scope/rank<N>`` objects
    (each holds one sample; returned as a one-element list so dir and
    storage modes share a shape)."""
    import re

    from ..io_types import IOReq, io_payload

    out: Dict[int, List[Dict[str, Any]]] = {}
    pat = re.compile(r"^\.scope/rank(\d+)$")
    for path in await storage.list_prefix(SCOPE_PREFIX + "/") or []:
        m = pat.match(path)
        if not m:
            continue
        try:
            io_req = IOReq(path=path)
            await storage.read(io_req)
            doc = json.loads(bytes(io_payload(io_req)).decode("utf-8"))
        # Deleted/torn between list and read: the writer (or a delete)
        # raced the reader — expected for live state.
        except Exception:  # snapcheck: disable=swallowed-exception -- live-state read races
            continue
        if isinstance(doc, dict):
            out[int(m.group(1))] = [doc]
    return out
