"""Live in-flight progress records (snapwatch's publishing half).

Everything the flight recorder (:mod:`.report`) ships is post-hoc: the
``.report.json`` exists only once a take commits, so a 30-minute
multi-rank take that hangs, straggles, or crawls is a black box until it
finishes or times out. This module closes that gap: the take/restore
paths publish small rank-local **progress records** on a cadence —

- to a **local statusfile** (``TPUSNAPSHOT_PROGRESS_DIR``, one
  atomically-replaced JSON per rank), readable by anything on the host;
- on the **async/storage commit route** (where the take_id nonce exists
  before the writes drain), to ``.progress/<take_id>/<rank>`` objects in
  the snapshot prefix itself — so ``python -m
  torchsnapshot_tpu.telemetry.watch <path>`` can render per-rank
  phase/throughput/ETA for an in-flight operation from any machine that
  can read the snapshot's storage, and flag ranks whose heartbeat went
  stale (straggler / hang detection).

Progress is observability, not protocol: every publish is best-effort,
rate-limited (``TPUSNAPSHOT_PROGRESS_INTERVAL_S``, default 2s), and may
never fail or slow the operation it describes. Storage progress objects
are cleaned at commit: each rank publishes a terminal ``done`` record
BEFORE its completion marker (never deleting its own), and rank 0 —
the only deleter — sweeps every rank's object after the metadata
lands, so the sweep cannot race a republish.
``CheckpointManager.reconcile`` reclaims debris left by crashed takes —
a progress object must never survive a commit or a detected crash.

Record schema (``format_version`` 1)::

    {
      "format_version": 1,
      "kind": "take" | "async_take" | "restore",
      "path": "<snapshot url>",
      "take_id": "<nonce or null>",
      "rank": r, "world_size": N,
      "phase": "capture" | "prestage" | "write" | "commit" | ... | "done",
      "bytes_done": B, "bytes_total": T | null,
      "ops": {"stage": n, "write": n, ...},      # pipelined op counts
      "retries": n,                              # storage retry delta
      "seq": monotonically increasing per publish,
      "host": hostname, "pid": pid,
      "started_at": wall epoch s, "heartbeat_at": wall epoch s
    }

``heartbeat_at`` is the load-bearing field: the publisher refreshes it
at every pipeline completion and phase change, so a rank whose record
stops aging forward is stuck inside one storage op, one collective, or
one device transfer — exactly the straggler signature ``watch`` flags.
"""

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Dict, Optional

from . import metrics as _m
from .metrics import REGISTRY, diff_snapshots, sum_samples

logger = logging.getLogger(__name__)

PROGRESS_FORMAT_VERSION = 1
# Listing prefix covering every progress object a snapshot can hold.
PROGRESS_PREFIX = ".progress"
# Per-rank in-flight records on the storage route.
RANK_PROGRESS_PREFIX = ".progress/"

_INTERVAL_ENV_VAR = "TPUSNAPSHOT_PROGRESS_INTERVAL_S"
_DEFAULT_INTERVAL_S = 2.0
_DIR_ENV_VAR = "TPUSNAPSHOT_PROGRESS_DIR"

# Phase a finished operation publishes; watch renders it as complete and
# never flags its heartbeat as stale.
DONE_PHASE = "done"


def progress_path(take_id: str, rank: int) -> str:
    return f"{RANK_PROGRESS_PREFIX}{take_id}/{rank}"


def statusfile_name(rank: int) -> str:
    return f"rank{rank}.progress.json"


def _interval_s() -> float:
    raw = os.environ.get(_INTERVAL_ENV_VAR)
    if raw is None:
        return _DEFAULT_INTERVAL_S
    try:
        return max(0.0, float(raw))
    except ValueError:
        logger.warning(
            "Malformed %s=%r; using %gs",
            _INTERVAL_ENV_VAR,
            raw,
            _DEFAULT_INTERVAL_S,
        )
        return _DEFAULT_INTERVAL_S


class ProgressPublisher:
    """One rank's live progress record for one snapshot operation.

    Thread-safe: an async take updates from the background drain thread
    while the foreground may still be mutating phase state, and the
    statusfile write may race a reader (atomic tmp+rename, same
    crash-safe discipline as ``tracing.flush``).

    The storage sink is attached only once a take_id exists (async
    takes broadcast the nonce before the drain starts; sync takes draw
    it at commit time, when writes are already done — so sync takes and
    restores publish statusfiles only). Storage publication happens via
    :meth:`async_tick` from inside the pipeline's event loop, so it
    needs no extra thread and stops exactly when the pipeline stops —
    which is the point: a stuck pipeline's record goes stale.
    """

    def __init__(
        self,
        kind: str,
        path: str,
        rank: int,
        world_size: int = 1,
        take_id: Optional[str] = None,
        statusfile_dir: Optional[str] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        self.kind = kind
        self.path = path
        self.rank = rank
        self.world_size = world_size
        self.take_id = take_id
        self._dir = (
            statusfile_dir
            if statusfile_dir is not None
            else os.environ.get(_DIR_ENV_VAR)
        )
        self._interval_s = (
            interval_s if interval_s is not None else _interval_s()
        )
        self._storage: Optional[Any] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._phase = "starting"
        self._bytes_done = 0
        self._bytes_total: Optional[int] = None
        self._ops: Dict[str, int] = {}
        self._heartbeat_at = time.time()
        self._started_at = self._heartbeat_at
        self._baseline = REGISTRY.snapshot()
        self._last_file_emit = 0.0
        self._last_storage_emit = 0.0
        self._finished = False

    # ------------------------------------------------------------- mutation

    def attach_storage(self, storage: Any, take_id: str) -> None:
        """Enable the ``.progress/<take_id>/<rank>`` storage sink (the
        async/storage route, where the nonce exists before writes)."""
        with self._lock:
            self._storage = storage
            self.take_id = take_id

    def set_phase(self, phase: str) -> None:
        with self._lock:
            self._phase = phase
            self._heartbeat_at = time.time()
        self._emit_file(force=True)

    def add_bytes_total(self, total: int) -> None:
        """Accumulate expected bytes: an operation may run several
        pipeline legs (restore runs one per stateful), each announcing
        its own total as it starts."""
        with self._lock:
            self._bytes_total = (self._bytes_total or 0) + int(total)

    def pipeline_update(self, op: str, done_bytes: int = 0) -> None:
        """One pipelined op (stage/write/read/consume) completed. Called
        from the scheduler's event-loop thread per completion — the
        heartbeat's pulse. ``done_bytes`` is the op's share of
        ``bytes_total`` IN THE SAME UNITS the totals were announced in
        (the scheduler credits pre-compression costs, so done/total stay
        commensurable when compression shrinks the stored payloads);
        ops that re-describe already-counted payloads pass 0."""
        with self._lock:
            self._ops[op] = self._ops.get(op, 0) + 1
            self._bytes_done += int(done_bytes)
            self._heartbeat_at = time.time()
        self._emit_file()

    def heartbeat(self) -> None:
        """Refresh liveness without other state changes (long phases
        with no pipeline completions, e.g. marker polling)."""
        with self._lock:
            self._heartbeat_at = time.time()
        self._emit_file()

    def finish(self) -> None:
        """Publish the terminal record (phase ``done``) to the
        statusfile; storage objects are deleted at commit instead."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self._phase = DONE_PHASE
            self._heartbeat_at = time.time()
        self._emit_file(force=True)

    # ------------------------------------------------------------ rendering

    def record(self) -> Dict[str, Any]:
        retries = sum_samples(
            diff_snapshots(self._baseline, REGISTRY.snapshot()),
            _m.STORAGE_RETRIES,
        )
        with self._lock:
            self._seq += 1
            return {
                "format_version": PROGRESS_FORMAT_VERSION,
                "kind": self.kind,
                "path": self.path,
                "take_id": self.take_id,
                "rank": self.rank,
                "world_size": self.world_size,
                "phase": self._phase,
                "bytes_done": self._bytes_done,
                "bytes_total": self._bytes_total,
                "ops": dict(self._ops),
                "retries": int(retries),
                "seq": self._seq,
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "started_at": round(self._started_at, 3),
                "heartbeat_at": round(self._heartbeat_at, 3),
            }

    # -------------------------------------------------------------- sinks

    def _emit_file(self, force: bool = False) -> None:
        if self._dir is None:
            return
        now = time.monotonic()
        with self._lock:
            # Cadence check-and-set under the lock: the foreground and
            # the drain thread emit concurrently by contract.
            if not force and now - self._last_file_emit < self._interval_s:
                return
            self._last_file_emit = now
        try:
            os.makedirs(self._dir, exist_ok=True)
            target = os.path.join(self._dir, statusfile_name(self.rank))
            # Thread id in the tmp name: two threads sharing one tmp
            # could rename a half-written sibling into place; distinct
            # tmps make each replace atomic and complete (last wins).
            tmp = (
                f"{target}.tmp{os.getpid()}."
                f"{threading.get_ident() & 0xFFFFFFFF}"
            )
            with open(tmp, "w") as f:
                json.dump(self.record(), f)
            os.replace(tmp, target)
        except Exception as e:
            # Best-effort by contract; one debug line, never a failure.
            logger.debug("progress statusfile write failed: %r", e)

    async def async_tick(self, force: bool = False) -> None:
        """Publish to the attached storage sink if the cadence elapsed.
        Awaited from the pipeline's event loop (and the drain's phase
        boundaries); best-effort, and rate-limited so a fast pipeline
        does not turn progress into measurable IO load."""
        self._emit_file(force=force)
        storage, take_id = self._storage, self.take_id
        if storage is None or take_id is None:
            return
        now = time.monotonic()
        if not force and now - self._last_storage_emit < self._interval_s:
            return
        self._last_storage_emit = now
        try:
            from ..io_types import IOReq

            io_req = IOReq(
                path=progress_path(take_id, self.rank),
                data=json.dumps(self.record(), sort_keys=True).encode(
                    "utf-8"
                ),
            )
            await storage.write(io_req)
        except Exception as e:
            logger.debug("progress object write failed: %r", e)


async def acleanup_progress_objects(
    storage: Any, take_id: str, world_size: int
) -> None:
    """Best-effort sweep of every rank's ``.progress/<take_id>/*`` object
    — called by rank 0 after the metadata commit, so a committed
    snapshot never retains in-flight progress debris. Deletes fan out
    under the backend's write cap: at pod scale, world_size sequential
    round-trips would measurably stretch the commit tail."""
    import asyncio

    sem = asyncio.Semaphore(
        max(1, getattr(storage, "max_write_concurrency", 1))
    )

    async def _one(r: int) -> None:
        async with sem:
            try:
                await storage.delete(progress_path(take_id, r))
            except Exception:
                # Absent (the rank never published) or transiently
                # unreadable — both fine; reconcile() sweeps survivors.
                logger.debug(
                    "progress cleanup of %s skipped",
                    progress_path(take_id, r),
                    exc_info=True,
                )

    await asyncio.gather(*(_one(r) for r in range(world_size)))


# ---------------------------------------------------------------- collection


def parse_record(data: bytes) -> Optional[Dict[str, Any]]:
    """A progress record from raw bytes; None when torn/garbage (a
    concurrent writer on a non-atomic backend is expected, not an
    error)."""
    try:
        doc = json.loads(data.decode("utf-8"))
    # Torn/garbage record IS the expected answer on a non-atomic
    # backend racing the writer; "no record" keeps the watcher going.
    except Exception:  # snapcheck: disable=swallowed-exception -- torn-record probe
        return None
    if not isinstance(doc, dict) or "rank" not in doc:
        return None
    return doc


def collect_statusfiles(directory: str) -> Dict[int, Dict[str, Any]]:
    """Read every ``rank<N>.progress.json`` under ``directory``."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("rank") and name.endswith(".progress.json")):
            continue
        try:
            with open(os.path.join(directory, name), "rb") as f:
                doc = parse_record(f.read())
        except OSError:
            continue
        if doc is not None:
            out[int(doc["rank"])] = doc
    return out


async def acollect_storage_records(
    storage: Any,
) -> Dict[str, Dict[int, Dict[str, Any]]]:
    """All in-flight progress records in a snapshot prefix, grouped by
    take_id: ``{take_id: {rank: record}}``."""
    out: Dict[str, Dict[int, Dict[str, Any]]] = {}
    from ..io_types import IOReq, io_payload

    paths = await storage.list_prefix(RANK_PROGRESS_PREFIX)
    for path in paths or []:
        tail = path[len(RANK_PROGRESS_PREFIX):]
        take_id, _, rank_s = tail.partition("/")
        if not take_id or not rank_s.isdigit():
            continue
        try:
            io_req = IOReq(path=path)
            await storage.read(io_req)
        # A record deleted between listing and read is the commit's
        # cleanup racing the watcher — expected, not an error.
        except Exception:  # snapcheck: disable=swallowed-exception -- commit races watch
            continue
        doc = parse_record(bytes(io_payload(io_req)))
        if doc is not None:
            out.setdefault(take_id, {})[int(rank_s)] = doc
    return out
