"""snapmem: the process-wide host-memory plane.

The pipeline enforces byte caps in at least seven independent places —
the scheduler's write/read budget cells, the restore staging pool, the
hot tier's ``HostRamStore`` instances and their remote-shadow ledger,
the snapserve ``ByteLRU``, per-client flow control, tenant admission
quotas, and the wiretap ring — each with private accounting and, until
now, no process-wide view. An overcommit across domains (every budget
individually honored, their SUM past what the host can give) or a slow
leak in any one of them was invisible until the OS killed the process.
This module is the registry those budgets reconcile through:

- every byte-capped subsystem registers a :class:`MemDomain` handle
  (name, cap, used, pinned-vs-evictable split) and pushes its
  occupancy as it changes, or registers a **provider** callable that
  is polled at snapshot time (for stores whose mutation points are
  too many to instrument: hot-tier host stores, the wiretap ring);
- :func:`snapshot` produces one consistent cross-domain view under a
  single lock: per-domain occupancy/high-water, aggregate committed
  bytes, and headroom against ``TPUSNAPSHOT_HOST_MEM_BUDGET`` (or the
  detected cgroup limit / host RAM) minus the process RSS;
- :func:`window_begin`/:func:`window_collect` bracket one operation
  (a take, a restore, a bench section) and return the phase-windowed
  memory block flight reports embed — per-domain high-waters inside
  the window, ending occupancy, counter deltas, and any pressure
  forecasts that fired;
- :func:`forecast` is the pre-storm check: before a take/restore's
  allocation burst, compare the plan's byte demand against live
  headroom and emit a warning + counter + trace instant instead of
  letting the burst become an OOM (the doctor's
  ``host-memory-overcommit`` rule reads the recorded event from the
  report's memory block);
- :func:`leak_findings` is the leak/drift sentinel: over a ledger
  series it watches each domain's steady-state residual bytes across
  N completed takes/restores and names the drifting domain
  (``memory-leak-suspected``); the module CLI exposes it with the
  standard exit contract (0 healthy, 1 findings, 2 usage).

Domain semantics:

- ``pinned`` bytes cannot be released by the subsystem on demand
  (leased staging buffers, undrained hot-tier objects, in-flight
  response bytes); ``evictable`` = used - pinned (cache entries, free
  pooled buffers) could be dropped under pressure.
- ``transient`` domains must return to ~zero occupancy between
  operations (scheduler budget cells, flow control); a residual there
  is a leak signal by itself.
- ``watch_residual`` selects what the leak heuristics track for the
  domain: ``"used"`` (transient domains), ``"pinned"`` (pools whose
  free buffers are retained by design but whose leases must come
  back), or ``None`` (caches and stores whose retention is the
  point — excluded from leak detection).
- ``external=True`` marks accounting of bytes that live OUTSIDE this
  process (the hot tier's remote-shadow ledger of replicas parked on
  peers): reported in the domains table for visibility, EXCLUDED from
  ``committed_bytes`` and the headroom math so fleet-wide views do
  not double-count what the owning process already registers.

faultline's ``mem_pressure(domain, cap_bytes)`` schedule rule calls
:func:`force_cap` at a deterministic op boundary: the override shrinks
the REPORTED cap (the subsystem's real budget is untouched), so the
domain's high-water lands above its cap and the doctor/slo memory
rules trip deterministically in tests.

Like every telemetry surface here, the plane is observability, not
protocol: registration and updates are cheap dict/int mutations under
one lock, snapshots never raise into the pipeline (provider errors
drop the provider's domain from that snapshot), and nothing in this
module may fail the operation it measures.
"""

import argparse
import json
import logging
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.env import env_int
from . import metrics as _m
from .metrics import REGISTRY

logger = logging.getLogger(__name__)

MEMORY_FORMAT_VERSION = 1

# The operator-declared host budget every domain reconciles against.
# Unset: fall back to the cgroup limit (v2 memory.max, then v1
# memory.limit_in_bytes), then total host RAM.
HOST_MEM_BUDGET_ENV_VAR = "TPUSNAPSHOT_HOST_MEM_BUDGET"
# Leak sentinel: how many consecutive same-kind ledger records a
# domain's residual must be non-decreasing across, and the minimum
# total growth (bytes) before the drift is named.
LEAK_RECORDS_ENV_VAR = "TPUSNAPSHOT_MEM_LEAK_RECORDS"
LEAK_MIN_BYTES_ENV_VAR = "TPUSNAPSHOT_MEM_LEAK_MIN_BYTES"
_DEFAULT_LEAK_RECORDS = 5
_DEFAULT_LEAK_MIN_BYTES = 1 << 20

# A window that is never collected (a crashed take) must not leak
# registry state: oldest windows are dropped past this many open.
_MAX_OPEN_WINDOWS = 64

_LOCK = threading.RLock()
_DOMAINS: Dict[str, List["MemDomain"]] = {}
_PROVIDERS: Dict[str, "_Provider"] = {}
_CAP_OVERRIDES: Dict[str, int] = {}
_WINDOWS: Dict[int, "_Window"] = {}
_NEXT_WINDOW_ID = 1
# Lifetime (since reset) high-water of the committed total, and the
# running committed/pinned totals maintained incrementally by domain
# updates (providers fold in at snapshot time only).
_TOTAL_USED = 0
_TOTAL_HWM = 0


class MemDomain:
    """One byte-capped subsystem's handle into the registry.

    Thread-safe through the registry lock. Multiple instances may share
    a name (one per hot-tier host store, one ``ByteLRU`` per server in
    a multi-server test process); snapshots aggregate by name so the
    label cardinality stays bounded.
    """

    __slots__ = (
        "name",
        "transient",
        "watch_residual",
        "external",
        "_cap",
        "_used",
        "_pinned",
        "_hwm",
        "_counters",
        "_alive",
    )

    def __init__(
        self,
        name: str,
        cap_bytes: Optional[int],
        transient: bool,
        watch_residual: Optional[str],
        external: bool,
    ) -> None:
        self.name = name
        self.transient = transient
        self.watch_residual = watch_residual
        self.external = external
        self._cap = cap_bytes
        self._used = 0
        self._pinned = 0
        self._hwm = 0
        self._counters: Dict[str, int] = {}
        self._alive = True

    # ------------------------------------------------------------ updates

    def set_cap(self, cap_bytes: Optional[int]) -> None:
        with _LOCK:
            self._cap = cap_bytes
        _set_domain_gauges(self.name)

    def set_used(
        self, used_bytes: int, pinned_bytes: Optional[int] = None
    ) -> None:
        """Publish the subsystem's current occupancy (absolute, not a
        delta). ``pinned_bytes`` defaults to sticky: unchanged if set
        before, else 0."""
        global _TOTAL_USED, _TOTAL_HWM
        used = max(0, int(used_bytes))
        with _LOCK:
            if not self._alive:
                return
            delta = used - self._used
            self._used = used
            if pinned_bytes is not None:
                self._pinned = max(0, min(used, int(pinned_bytes)))
            else:
                self._pinned = min(self._pinned, used)
            self._hwm = max(self._hwm, used)
            if not self.external:
                _TOTAL_USED += delta
                _TOTAL_HWM = max(_TOTAL_HWM, _TOTAL_USED)
            _window_observe_locked(self.name)
        _set_domain_gauges(self.name)

    def charge(self, nbytes: int, pinned: bool = False) -> None:
        with _LOCK:
            self.set_used(
                self._used + int(nbytes),
                self._pinned + int(nbytes) if pinned else None,
            )

    def release(self, nbytes: int, pinned: bool = False) -> None:
        with _LOCK:
            self.set_used(
                self._used - int(nbytes),
                self._pinned - int(nbytes) if pinned else None,
            )

    def counter(self, key: str, inc: int = 1) -> None:
        """Monotonic per-domain event counters (pool hits/misses/waits,
        cache hits/evictions); windows report their deltas, which is
        what the thrash/misfit doctor rules read."""
        with _LOCK:
            self._counters[key] = self._counters.get(key, 0) + int(inc)

    def close(self) -> None:
        """Unregister (idempotent). The domain's bytes leave the
        committed total — a closed pool/cache no longer holds them."""
        global _TOTAL_USED
        with _LOCK:
            if not self._alive:
                return
            self._alive = False
            if not self.external:
                _TOTAL_USED -= self._used
            insts = _DOMAINS.get(self.name)
            if insts is not None:
                insts = [d for d in insts if d is not self]
                if insts:
                    _DOMAINS[self.name] = insts
                else:
                    _DOMAINS.pop(self.name, None)
            _window_observe_locked(self.name)
        _set_domain_gauges(self.name)

    # ---------------------------------------------------------- inspection

    @property
    def used_bytes(self) -> int:
        with _LOCK:
            return self._used

    @property
    def cap_bytes(self) -> Optional[int]:
        with _LOCK:
            return _CAP_OVERRIDES.get(self.name, self._cap)

    @property
    def high_water_bytes(self) -> int:
        with _LOCK:
            return self._hwm


class _Provider:
    """A polled domain: ``fn() -> (used, pinned, cap)`` sampled at
    snapshot/window boundaries instead of pushed per mutation."""

    __slots__ = (
        "name", "fn", "transient", "watch_residual", "external", "_hwm"
    )

    def __init__(
        self,
        name: str,
        fn: Callable[[], Tuple[int, int, Optional[int]]],
        transient: bool,
        watch_residual: Optional[str],
        external: bool,
    ) -> None:
        self.name = name
        self.fn = fn
        self.transient = transient
        self.watch_residual = watch_residual
        self.external = external
        self._hwm = 0


class _Window:
    __slots__ = (
        "domain_hwm",
        "domain_cap",
        "domain_ext",
        "total_hwm",
        "counters0",
        "forecasts",
    )

    def __init__(self) -> None:
        self.domain_hwm: Dict[str, int] = {}
        # Caps/externality remembered per-domain so a transient domain
        # that closes before collection (a scheduler budget cell dying
        # with its pipeline run) still reports against its cap.
        self.domain_cap: Dict[str, Optional[int]] = {}
        self.domain_ext: Dict[str, bool] = {}
        self.total_hwm = 0
        self.counters0: Dict[str, Dict[str, int]] = {}
        self.forecasts: List[Dict[str, Any]] = []


# ------------------------------------------------------------ registration


def register(
    name: str,
    cap_bytes: Optional[int] = None,
    transient: bool = False,
    watch_residual: Optional[str] = None,
    external: bool = False,
) -> MemDomain:
    """Register one byte-capped subsystem instance. Call
    :meth:`MemDomain.close` when the instance goes away (pool reset,
    server stop); a ``weakref.finalize`` on the owning object is the
    idiomatic safety net."""
    d = MemDomain(name, cap_bytes, transient, watch_residual, external)
    with _LOCK:
        _DOMAINS.setdefault(name, []).append(d)
        # Stamp cap/externality into already-open windows so a domain
        # registered mid-window that never updates (an idle budget
        # cell) still reports its identity at collect time.
        _window_observe_locked(name)
    _set_domain_gauges(name)
    return d


def register_provider(
    name: str,
    fn: Callable[[], Tuple[int, int, Optional[int]]],
    transient: bool = False,
    watch_residual: Optional[str] = None,
    external: bool = False,
) -> None:
    """Register a polled domain (replaces any previous provider of the
    same name). ``fn`` runs under the registry lock at snapshot time
    and must be cheap and non-reentrant; an error drops the domain
    from that snapshot, never raises."""
    with _LOCK:
        _PROVIDERS[name] = _Provider(
            name, fn, transient, watch_residual, external
        )


def unregister_provider(name: str) -> None:
    with _LOCK:
        _PROVIDERS.pop(name, None)


def force_cap(name: str, cap_bytes: int) -> None:
    """faultline's ``mem_pressure`` lever: override the REPORTED cap of
    every current and future instance of ``name`` (the subsystem's
    real budget is untouched) so occupancy lands above cap and the
    memory rules trip deterministically. Cleared by
    :func:`clear_cap_overrides` / :func:`reset`."""
    with _LOCK:
        _CAP_OVERRIDES[name] = int(cap_bytes)
    _set_domain_gauges(name)


def clear_cap_overrides() -> None:
    with _LOCK:
        _CAP_OVERRIDES.clear()


def reset() -> None:
    """Tests only: drop every domain, provider, window, and override."""
    global _TOTAL_USED, _TOTAL_HWM
    with _LOCK:
        _DOMAINS.clear()
        _PROVIDERS.clear()
        _CAP_OVERRIDES.clear()
        _WINDOWS.clear()
        _TOTAL_USED = 0
        _TOTAL_HWM = 0


# ---------------------------------------------------------------- internals


def _agg_locked(name: str) -> Optional[Dict[str, Any]]:
    """Aggregate one name's live instances (lock held). None when the
    name has no live pushed instances."""
    insts = _DOMAINS.get(name)
    if not insts:
        return None
    used = sum(d._used for d in insts)
    pinned = sum(d._pinned for d in insts)
    hwm = sum(d._hwm for d in insts)
    caps = [d._cap for d in insts]
    cap: Optional[int] = (
        sum(c for c in caps if c is not None)
        if any(c is not None for c in caps)
        else None
    )
    if name in _CAP_OVERRIDES:
        cap = _CAP_OVERRIDES[name]
    counters: Dict[str, int] = {}
    for d in insts:
        for k, v in d._counters.items():
            counters[k] = counters.get(k, 0) + v
    first = insts[0]
    return {
        "used_bytes": used,
        "pinned_bytes": pinned,
        "evictable_bytes": used - pinned,
        "cap_bytes": cap,
        "high_water_bytes": hwm,
        "instances": len(insts),
        "transient": first.transient,
        "external": first.external,
        "watch_residual": first.watch_residual,
        "counters": counters,
    }


def _provider_agg_locked(p: _Provider) -> Optional[Dict[str, Any]]:
    try:
        used, pinned, cap = p.fn()
    except Exception:
        logger.debug(
            "memwatch provider %s failed; domain skipped this snapshot",
            p.name,
            exc_info=True,
        )
        return None
    used = max(0, int(used))
    pinned = max(0, min(used, int(pinned)))
    p._hwm = max(p._hwm, used)
    if p.name in _CAP_OVERRIDES:
        cap = _CAP_OVERRIDES[p.name]
    return {
        "used_bytes": used,
        "pinned_bytes": pinned,
        "evictable_bytes": used - pinned,
        "cap_bytes": int(cap) if cap is not None else None,
        "high_water_bytes": p._hwm,
        "instances": 1,
        "transient": p.transient,
        "external": p.external,
        "watch_residual": p.watch_residual,
        "counters": {},
    }


def _domains_locked(poll: bool = True) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for name in sorted(_DOMAINS):
        agg = _agg_locked(name)
        if agg is not None:
            out[name] = agg
    if poll:
        for name, p in sorted(_PROVIDERS.items()):
            if name in out:
                continue  # a pushed registration shadows the provider
            agg = _provider_agg_locked(p)
            if agg is not None:
                out[name] = agg
    return out


def _residual_of(entry: Dict[str, Any]) -> Optional[int]:
    watch = entry.get("watch_residual")
    if watch == "used":
        return int(entry.get("used_bytes") or 0)
    if watch == "pinned":
        return int(entry.get("pinned_bytes") or 0)
    return None


def _window_observe_locked(name: str) -> None:
    """Raise every open window's high-waters after a domain update
    (lock held). Providers are not observed here — they are polled at
    window boundaries only."""
    if not _WINDOWS:
        return
    agg = _agg_locked(name)
    used = int(agg["used_bytes"]) if agg else 0
    for w in _WINDOWS.values():
        w.domain_hwm[name] = max(w.domain_hwm.get(name, 0), used)
        if agg is not None:
            w.domain_cap[name] = agg["cap_bytes"]
            w.domain_ext[name] = bool(agg["external"])
        w.total_hwm = max(w.total_hwm, _TOTAL_USED)


def _set_domain_gauges(name: str) -> None:
    """Mirror one domain's aggregate into the always-on gauges. Label
    cardinality is bounded by the registered domain names."""
    try:
        with _LOCK:
            agg = _agg_locked(name)
        if agg is None:
            REGISTRY.gauge(_m.MEM_DOMAIN_USED, domain=name).set(0)
            return
        REGISTRY.gauge(_m.MEM_DOMAIN_USED, domain=name).set(
            agg["used_bytes"]
        )
        REGISTRY.gauge(_m.MEM_DOMAIN_HWM, domain=name).set(
            agg["high_water_bytes"]
        )
        if agg["cap_bytes"] is not None:
            REGISTRY.gauge(_m.MEM_DOMAIN_CAP, domain=name).set(
                agg["cap_bytes"]
            )
    except Exception:  # pragma: no cover - observability never raises
        logger.debug("memwatch gauge update failed", exc_info=True)


# ------------------------------------------------------------- host budget


def host_budget_bytes() -> Tuple[Optional[int], str]:
    """``(budget, source)``: the operator knob, else the cgroup limit,
    else total host RAM, else ``(None, "unknown")``."""
    raw = env_int(HOST_MEM_BUDGET_ENV_VAR, 0)
    if raw > 0:
        return raw, "env"
    for path, source in (
        ("/sys/fs/cgroup/memory.max", "cgroup"),
        ("/sys/fs/cgroup/memory/memory.limit_in_bytes", "cgroup"),
    ):
        try:
            with open(path, "r", encoding="ascii") as f:
                text = f.read().strip()
            if text and text != "max":
                limit = int(text)
                # v1 reports an effectively-unlimited sentinel near
                # 2^63; treat anything over 1 PiB as no limit.
                if 0 < limit < (1 << 50):
                    return limit, source
        except (OSError, ValueError):
            continue
    try:
        import psutil

        return int(psutil.virtual_memory().total), "host"
    except (ImportError, OSError, RuntimeError):
        return None, "unknown"


def process_rss_bytes() -> Optional[int]:
    try:
        import psutil

        return int(psutil.Process().memory_info().rss)
    except (ImportError, OSError, RuntimeError):
        return None


def _headroom_fields() -> Dict[str, Any]:
    budget, source = host_budget_bytes()
    rss = process_rss_bytes()
    out: Dict[str, Any] = {
        "budget_bytes": budget,
        "budget_source": source,
        "rss_bytes": rss,
    }
    out["headroom_bytes"] = (
        budget - rss if budget is not None and rss is not None else None
    )
    return out


# --------------------------------------------------------------- snapshots


def snapshot() -> Dict[str, Any]:
    """One consistent cross-domain view: every domain's occupancy and
    lifetime high-water, the committed total (external domains
    excluded), and headroom against the host budget."""
    with _LOCK:
        domains = _domains_locked()
        total_hwm = _TOTAL_HWM
    committed = sum(
        d["used_bytes"] for d in domains.values() if not d["external"]
    )
    pinned = sum(
        d["pinned_bytes"] for d in domains.values() if not d["external"]
    )
    doc: Dict[str, Any] = {
        "format_version": MEMORY_FORMAT_VERSION,
        "domains": domains,
        "committed_bytes": committed,
        "pinned_bytes": pinned,
        "high_water_bytes": max(total_hwm, committed),
    }
    doc.update(_headroom_fields())
    try:
        REGISTRY.gauge(_m.MEM_COMMITTED).set(committed)
        if doc["headroom_bytes"] is not None:
            REGISTRY.gauge(_m.MEM_HEADROOM).set(doc["headroom_bytes"])
    except Exception:  # pragma: no cover - observability never raises
        logger.debug("memwatch headline gauges failed", exc_info=True)
    return doc


def sample_block() -> Dict[str, Any]:
    """Compact block for the runtime sampler and the stats RPCs: the
    per-domain occupancy table plus the headline headroom numbers the
    slo/ops consumers sort by. Empty ``domains`` when nothing is
    registered (callers omit the block then)."""
    snap = snapshot()
    domains = {
        name: {
            k: v
            for k, v in entry.items()
            if k
            in (
                "used_bytes",
                "pinned_bytes",
                "cap_bytes",
                "high_water_bytes",
                "external",
                "watch_residual",
            )
        }
        for name, entry in snap["domains"].items()
    }
    return {
        "domains": domains,
        "committed_bytes": snap["committed_bytes"],
        "high_water_bytes": snap["high_water_bytes"],
        "budget_bytes": snap["budget_bytes"],
        "budget_source": snap["budget_source"],
        "rss_bytes": snap["rss_bytes"],
        "headroom_bytes": snap["headroom_bytes"],
    }


# ----------------------------------------------------------------- windows


def window_begin() -> int:
    """Open a phase window (one per take/restore/bench section).
    Returns an opaque token for :func:`window_collect`. Windows are
    seeded with current occupancy so a domain that never moves inside
    the window still reports its standing bytes as the window
    high-water."""
    global _NEXT_WINDOW_ID
    with _LOCK:
        w = _Window()
        domains = _domains_locked()
        for name, entry in domains.items():
            w.domain_hwm[name] = int(entry["used_bytes"])
            w.domain_cap[name] = entry["cap_bytes"]
            w.domain_ext[name] = bool(entry["external"])
            w.counters0[name] = dict(entry.get("counters") or {})
        w.total_hwm = sum(
            d["used_bytes"] for d in domains.values() if not d["external"]
        )
        token = _NEXT_WINDOW_ID
        _NEXT_WINDOW_ID += 1
        _WINDOWS[token] = w
        while len(_WINDOWS) > _MAX_OPEN_WINDOWS:
            _WINDOWS.pop(min(_WINDOWS))
        return token


def window_collect(token: int) -> Dict[str, Any]:
    """Close a window and return the flight-report memory block:
    per-domain window high-waters + ending occupancy + counter deltas,
    the aggregate window high-water, headroom at close, and any
    pressure forecasts recorded inside the window. ``{}`` when no
    domain was ever registered (the caller omits the block)."""
    with _LOCK:
        w = _WINDOWS.pop(token, None)
        domains = _domains_locked()
        if w is not None:
            # Final poll: provider domains and push domains alike get
            # their closing occupancy folded into the window HWM.
            for name, entry in domains.items():
                w.domain_hwm[name] = max(
                    w.domain_hwm.get(name, 0), int(entry["used_bytes"])
                )
            w.total_hwm = max(
                w.total_hwm,
                sum(
                    d["used_bytes"]
                    for d in domains.values()
                    if not d["external"]
                ),
            )
    if w is None or (not w.domain_hwm and not w.forecasts):
        return {}
    out_domains: Dict[str, Any] = {}
    for name in sorted(w.domain_hwm):
        entry = domains.get(name)
        block: Dict[str, Any] = {
            "high_water_bytes": int(w.domain_hwm[name]),
            "end_used_bytes": int(entry["used_bytes"]) if entry else 0,
            "pinned_bytes": int(entry["pinned_bytes"]) if entry else 0,
            "cap_bytes": (
                entry["cap_bytes"]
                if entry
                else w.domain_cap.get(name)
            ),
        }
        if (entry and entry["external"]) or (
            entry is None and w.domain_ext.get(name)
        ):
            block["external"] = True
        residual = _residual_of(entry) if entry else None
        if residual is not None:
            block["residual_bytes"] = residual
        deltas = {}
        now_counters = (entry or {}).get("counters") or {}
        base = w.counters0.get(name) or {}
        for k in sorted(now_counters):
            d = int(now_counters[k]) - int(base.get(k, 0))
            if d:
                deltas[k] = d
        if deltas:
            block["counters"] = deltas
        out_domains[name] = block
    committed = sum(
        d["used_bytes"] for d in domains.values() if not d["external"]
    )
    block = {
        "format_version": MEMORY_FORMAT_VERSION,
        "domains": out_domains,
        "committed_bytes": committed,
        "high_water_bytes": int(w.total_hwm),
    }
    block.update(_headroom_fields())
    if w.forecasts:
        block["forecasts"] = list(w.forecasts)
    return block


# -------------------------------------------------------------- forecasting


def forecast(
    demand_bytes: int, kind: str = "take"
) -> Optional[Dict[str, Any]]:
    """Pre-storm pressure check: will ``demand_bytes`` of imminent
    allocations fit in live headroom? On predicted overcommit, records
    the event (returned, counted, traced, logged, and folded into
    every open window so the flight report's memory block carries it
    for the ``host-memory-overcommit`` doctor rule) — the deliberate
    alternative to discovering the answer as an OOM kill. Never
    raises; returns None when headroom is unknown or sufficient."""
    try:
        fields = _headroom_fields()
        headroom = fields.get("headroom_bytes")
        demand = max(0, int(demand_bytes))
        if headroom is None:
            return None
        if demand <= headroom:
            REGISTRY.counter(_m.MEM_FORECASTS, verdict="ok").inc()
            return None
        event = {
            "kind": kind,
            "demand_bytes": demand,
            "headroom_bytes": int(headroom),
            "budget_bytes": fields.get("budget_bytes"),
            "rss_bytes": fields.get("rss_bytes"),
            "overcommit": True,
        }
        REGISTRY.counter(_m.MEM_FORECASTS, verdict="overcommit").inc()
        from .. import tracing

        tracing.instant(
            "mem_pressure_forecast",
            kind=kind,
            demand_bytes=demand,
            headroom_bytes=int(headroom),
        )
        logger.warning(
            "memwatch: %s plans %d bytes against %d bytes of host "
            "headroom (budget %s, rss %s) — expect allocation pressure; "
            "lower the per-rank budget or raise %s",
            kind,
            demand,
            int(headroom),
            fields.get("budget_bytes"),
            fields.get("rss_bytes"),
            HOST_MEM_BUDGET_ENV_VAR,
        )
        with _LOCK:
            for w in _WINDOWS.values():
                w.forecasts.append(dict(event))
        return event
    except Exception:  # pragma: no cover - observability never raises
        logger.debug("memwatch forecast failed", exc_info=True)
        return None


# ----------------------------------------------------------- reconciliation


def reconcile(block: Dict[str, Any]) -> List[str]:
    """Violations of the memory block's internal contract (empty list
    = consistent): every non-external domain's window high-water must
    fit its cap (overridden caps excepted — that is the injected
    fault's point), and the aggregate high-water cannot exceed the sum
    of per-domain high-waters (each term is itself a max, so the sum
    bounds any instantaneous total)."""
    problems: List[str] = []
    domains = block.get("domains") or {}
    hwm_sum = 0
    for name, d in sorted(domains.items()):
        if not isinstance(d, dict):
            continue
        hwm = int(d.get("high_water_bytes") or 0)
        if not d.get("external"):
            hwm_sum += hwm
        cap = d.get("cap_bytes")
        with _LOCK:
            overridden = name in _CAP_OVERRIDES
        if cap is not None and not overridden and hwm > int(cap):
            problems.append(
                f"domain {name}: high water {hwm} exceeds cap {cap}"
            )
    agg = int(block.get("high_water_bytes") or 0)
    if agg > hwm_sum:
        problems.append(
            f"aggregate high water {agg} exceeds the sum of per-domain "
            f"high waters {hwm_sum}"
        )
    return problems


# ------------------------------------------------------------ leak sentinel


def leak_findings(
    records: List[Dict[str, Any]],
    min_records: Optional[int] = None,
    min_growth_bytes: Optional[int] = None,
) -> List[Any]:
    """The leak/drift sentinel over a ledger series: for every domain
    with residual tracking, fold the ``memory`` blocks of completed
    take/restore records and name any domain whose residual bytes were
    non-decreasing across the last N records while growing by at least
    the threshold — steady-state bytes that completed operations keep
    not giving back. Returns doctor ``Finding`` objects
    (``memory-leak-suspected``)."""
    from .doctor import Finding

    n = min_records or env_int(LEAK_RECORDS_ENV_VAR, _DEFAULT_LEAK_RECORDS)
    floor = (
        min_growth_bytes
        if min_growth_bytes is not None
        else env_int(LEAK_MIN_BYTES_ENV_VAR, _DEFAULT_LEAK_MIN_BYTES)
    )
    series: Dict[str, List[int]] = {}
    for r in records:
        if r.get("kind") not in ("take", "async_take", "restore"):
            continue
        mem = r.get("memory")
        if not isinstance(mem, dict):
            continue
        for name, d in (mem.get("domains") or {}).items():
            if not isinstance(d, dict):
                continue
            residual = d.get("residual_bytes")
            if residual is None:
                continue
            series.setdefault(name, []).append(int(residual))
    findings: List[Any] = []
    for name in sorted(series):
        vals = series[name]
        if len(vals) < max(2, n):
            continue
        tail = vals[-max(2, n):]
        growth = tail[-1] - tail[0]
        monotonic = all(b >= a for a, b in zip(tail, tail[1:]))
        if monotonic and growth >= max(1, floor) and tail[-1] > 0:
            findings.append(
                Finding(
                    rule="memory-leak-suspected",
                    severity="warn",
                    title=(
                        f"domain {name} retained {tail[-1]} bytes after "
                        f"the last completed operation, up {growth} "
                        f"bytes across {len(tail)} operations"
                    ),
                    evidence={
                        "domain": name,
                        "residual_bytes": tail[-1],
                        "growth_bytes": growth,
                        "records": len(tail),
                        "series_tail": tail,
                    },
                    remediation=(
                        "steady-state residual bytes are growing across "
                        "completed takes/restores — the named domain is "
                        "not releasing what it acquires. Inspect its "
                        "lease/charge call sites; compare the flight "
                        "reports' memory blocks (end_used_bytes per "
                        "domain) for the first operation that stopped "
                        "returning to baseline."
                    ),
                )
            )
    return findings


# ---------------------------------------------------------------- self-test


def _self_test() -> int:
    """Hermetic fixture check of the registry, windows, reconciliation,
    forecasting, cap overrides, and the leak sentinel — what CI smokes
    with no snapshot run."""
    reset()
    try:
        d = register(
            "t.pool", cap_bytes=1000, watch_residual="pinned"
        )
        d.set_used(0, pinned_bytes=0)
        token = window_begin()
        d.charge(600, pinned=True)
        d.release(400, pinned=True)
        d.counter("hits", 3)
        s = snapshot()
        assert s["domains"]["t.pool"]["used_bytes"] == 200, s
        assert s["domains"]["t.pool"]["high_water_bytes"] == 600, s
        assert s["committed_bytes"] == 200, s
        block = window_collect(token)
        assert block["domains"]["t.pool"]["high_water_bytes"] == 600, block
        assert block["domains"]["t.pool"]["end_used_bytes"] == 200, block
        assert block["domains"]["t.pool"]["residual_bytes"] == 200, block
        assert block["domains"]["t.pool"]["counters"] == {"hits": 3}, block
        assert block["high_water_bytes"] == 600, block
        assert reconcile(block) == [], reconcile(block)

        # Provider domains fold in at snapshot time; external domains
        # stay out of the committed total.
        register_provider("t.ring", lambda: (128, 0, 256))
        register_provider(
            "t.shadow", lambda: (4096, 4096, None), external=True
        )
        s = snapshot()
        assert s["domains"]["t.ring"]["used_bytes"] == 128, s
        assert s["domains"]["t.shadow"]["external"], s
        assert s["committed_bytes"] == 200 + 128, s

        # Cap override (the mem_pressure fault): reported cap shrinks,
        # occupancy exceeds it, reconcile still passes (the override
        # is the injected fault, not an accounting bug).
        force_cap("t.pool", 100)
        s = snapshot()
        assert s["domains"]["t.pool"]["cap_bytes"] == 100, s
        assert s["domains"]["t.pool"]["used_bytes"] > 100, s
        tok2 = window_begin()
        over = window_collect(tok2)
        assert reconcile(over) == [], reconcile(over)
        clear_cap_overrides()

        # A genuine over-cap high-water IS a reconciliation failure.
        bad = {
            "domains": {
                "x": {"high_water_bytes": 200, "cap_bytes": 100}
            },
            "high_water_bytes": 200,
        }
        assert any("exceeds cap" in p for p in reconcile(bad)), bad

        # close() retires the bytes.
        d.close()
        assert snapshot()["committed_bytes"] == 128, snapshot()

        # Forecast: an impossible demand records an overcommit event
        # into open windows (budget detection may legitimately be
        # unavailable in exotic sandboxes — then forecast is None by
        # contract and the window block simply has no forecasts).
        tok3 = window_begin()
        ev = forecast(1 << 62, kind="take")
        fblock = window_collect(tok3)
        if ev is not None:
            assert ev["overcommit"] and ev["demand_bytes"] == 1 << 62, ev
            assert fblock.get("forecasts"), fblock

        # Leak sentinel: the injected never-releasing domain is named;
        # a healthy domain that returns to baseline is not.
        def rec(leaky, healthy):
            return {
                "kind": "take",
                "memory": {
                    "domains": {
                        "leaky.domain": {"residual_bytes": leaky},
                        "healthy.pool": {"residual_bytes": healthy},
                    }
                },
            }

        records = [
            rec(1 << 20, 0),
            rec(3 << 20, 1 << 10),
            rec(5 << 20, 0),
            rec(7 << 20, 2 << 10),
            rec(9 << 20, 0),
        ]
        found = leak_findings(records, min_records=5)
        assert len(found) == 1, found
        assert found[0].rule == "memory-leak-suspected", found
        assert found[0].evidence["domain"] == "leaky.domain", found
        flat = leak_findings([rec(1 << 20, 0)] * 8, min_records=5)
        assert not flat, flat  # standing bytes without growth: no leak
        print("memwatch self-test OK")
        return 0
    finally:
        reset()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry.memwatch",
        description="Host-memory plane: leak/drift sentinel over a "
        "telemetry ledger series, or a live snapshot of this process's "
        "registered memory domains.",
    )
    parser.add_argument(
        "path",
        nargs="?",
        help="ledger root URL, a ledger .jsonl file, or a snapshot path "
        "to run the leak sentinel over",
    )
    parser.add_argument(
        "--min-records",
        type=int,
        default=None,
        metavar="N",
        help=f"consecutive records a residual must be non-decreasing "
        f"across (default {_DEFAULT_LEAK_RECORDS}, env "
        f"{LEAK_RECORDS_ENV_VAR})",
    )
    parser.add_argument(
        "--min-growth-bytes",
        type=int,
        default=None,
        metavar="B",
        help=f"minimum residual growth before a domain is named "
        f"(default {_DEFAULT_LEAK_MIN_BYTES}, env "
        f"{LEAK_MIN_BYTES_ENV_VAR})",
    )
    parser.add_argument("--json", action="store_true", help="emit JSON")
    parser.add_argument(
        "--self-test",
        action="store_true",
        help="run the built-in fixture checks and exit",
    )
    args = parser.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not args.path:
        parser.error("a ledger path is required (or --self-test)")
    from . import ledger as _ledger
    from .doctor import render_findings

    try:
        records, _skipped = _ledger.read_records(args.path)
    except Exception as e:
        print(f"error reading ledger at {args.path}: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"no ledger records at {args.path}", file=sys.stderr)
        return 2
    findings = leak_findings(
        records,
        min_records=args.min_records,
        min_growth_bytes=args.min_growth_bytes,
    )
    if args.json:
        print(
            json.dumps(
                {"findings": [f.as_dict() for f in findings]},
                indent=2,
                sort_keys=True,
            )
        )
    else:
        print(render_findings(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
