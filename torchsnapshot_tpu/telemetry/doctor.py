"""Anomaly-diagnosing doctor: structured findings from a rule table.

Usage::

    python -m torchsnapshot_tpu.telemetry.doctor <snapshot-path> [--json]
    python -m torchsnapshot_tpu.telemetry.doctor report.json [--json]
    python -m torchsnapshot_tpu.inspect <snapshot-path> --doctor

The doctor consumes a flight report (the ``.report.json`` /
``.report.restore.rank<N>.json`` documents the recorder commits beside
the manifest — or any JSON file of that schema) plus, optionally, a
trace summary and a metric snapshot, and emits findings from the rule
catalog below. Each finding names its rule id, the evidence that
triggered it, and a remediation hint — the difference between "this
restore was slow" and "this restore spent 176s deserializing against
0.8s of reads; storage is innocent" (the BENCH_r05 pathology that
motivated the whole telemetry subsystem).

Rule catalog (docs/OBSERVABILITY.md carries the narrative version):

========================  =============================================
id                        trigger
========================  =============================================
consume-dominated-restore consume phase >= 3x the read phase; when the
                          report carries the snapxray consume sub-phase
                          breakdown, evidence names the dominant
                          sub-step (decode/verify/reassemble/
                          device_put/…) and the remediation is
                          sub-step-specific
read-dominated-restore    read phase >= 3x the consume phase
stage-dominated-take      stage busy >= 3x write busy (scheduler ops)
budget-stall-dominated    budget stall >= 25% of a rank's wall time
retry-storm               storage retries >= 10 across the operation
straggler-rank            a rank's wall >= 1.5x the rank median (>2s)
imbalanced-stripe         max rank bytes >= 2x the rank median
checkpoint-overhead-      goodput attribution shows checkpointing over
above-budget              TPUSNAPSHOT_CKPT_BUDGET_PCT (default 5%)
missing-rank-summary      a rank's summary never arrived (null)
hot-tier-degraded         a restore fell back to the durable tier for
                          >0 objects (critical when >50% of bytes)
replication-degraded      a take's snapwire replication missed a
                          per-RPC deadline or failed a push (warn);
                          critical when those wire failures pushed
                          >50% of the acked bytes onto the synchronous
                          write-through path — acks stay honest but
                          pay storage latency. Capacity-caused
                          write-throughs without wire failures do not
                          fire it
read-plane-degraded       a restore routed via snapserve fell back to
                          direct backend reads for >0 objects
                          (critical when >50% of bytes) — the read
                          service was unreachable; bit-exactness held
fleet-degraded            a fleet-routed restore left the ring owner:
                          failovers / owner misses (warn), or the
                          whole fleet exhausted into direct fallback
                          (critical); bit-exactness held either way
durability-lag-above-     the take's ack→.tierdown window (stamped into
budget                    the report by the hot tier's drain) exceeded
                          TPUSNAPSHOT_SLO_DURABILITY_LAG_S (default
                          120s; critical at 2x). The SLO engine
                          (telemetry/slo.py) fires the same rule id
                          LIVE from sampler state, before the
                          watermark exists to prove it post-hoc.
deadline-margin-          an op's wiretap window shows p99 latency
collapsing                consuming >= TPUSNAPSHOT_WIRE_MARGIN_WARN
                          (default 0.70) of its per-RPC deadline —
                          the hand-tuned deadline knob is nearly
                          collapsed onto real latency (warn); critical
                          when the window recorded outright deadline
                          misses. The SLO engine fires the same rule
                          id LIVE from sampler wire blocks
dedup-ineffective         a chunked take's chunk-level dedup saved no
                          more bytes than leaf-level dedup would have
                          (every hit byte sat inside a fully-clean
                          leaf) over >= TPUSNAPSHOT_DEDUP_MIN_BYTES of
                          chunked payload — chunk-grid overhead
                          without sub-leaf savings (chunkstore.py)
replication-under-        LIVE-ONLY (telemetry/slo.py, like the live
replicated                arm of durability-lag-above-budget):
                          snapmend found committed undrained objects
                          below k live replicas past one repair
                          interval (warn), or the repair stalled past
                          TPUSNAPSHOT_REPAIR_DEADLINE_S with the
                          write-through escalation firing (critical).
                          Flight reports carry no membership state, so
                          this rule has no report-based arm here — the
                          ops/slo CLIs surface it with the same
                          exit-code contract
========================  =============================================

Findings are observability, not judgment: every rule errs toward
silence on thin evidence (tiny operations trip no ratios).

Exit codes: 0 = healthy (no findings); 1 = findings emitted;
2 = usage / no report found.
"""

import argparse
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.env import env_float, env_int

# Ratio thresholds, shared with summarize's dominance verdict where the
# same question is asked of a trace instead of a report.
_DOMINANCE_RATIO = 3.0
_STALL_FRACTION = 0.25
_RETRY_STORM_COUNT = 10
_STRAGGLER_RATIO = 1.5
_STRAGGLER_MIN_WALL_S = 2.0
_STRIPE_RATIO = 2.0
# Checkpoint-overhead budget: the goodput accountant's attribution must
# cover at least this much wall time before the budget verdict means
# anything (two steps of a toy loop prove nothing).
_CKPT_BUDGET_ENV_VAR = "TPUSNAPSHOT_CKPT_BUDGET_PCT"
_DEFAULT_CKPT_BUDGET_PCT = 5.0
_MIN_GOODPUT_WINDOW_S = 10.0
# Deadline-margin pressure threshold (wiretap): an op whose p99 latency
# consumes this fraction of its per-RPC deadline is one latency wobble
# from missing it — warn before the misses start.
_WIRE_MARGIN_WARN_ENV_VAR = "TPUSNAPSHOT_WIRE_MARGIN_WARN"
_DEFAULT_WIRE_MARGIN_WARN = 0.70
# Phases must clear this floor before a ratio means anything: a 0.05s
# consume "dominating" a 0.006s read is scheduler jitter on a tiny
# operation, not a pathology worth a remediation hint — the findings
# this doctor exists for are seconds-to-minutes (BENCH_r05: 176s).
_MIN_PHASE_S = 1.0


@dataclass
class Finding:
    rule: str
    severity: str  # "warn" | "critical"
    title: str
    evidence: Dict[str, Any] = field(default_factory=dict)
    remediation: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "title": self.title,
            "evidence": self.evidence,
            "remediation": self.remediation,
        }


def _ranks(report: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [s for s in (report.get("ranks") or []) if s]


def _phase_s(summary: Dict[str, Any], phase: str) -> float:
    return float((summary.get("phases") or {}).get(f"{phase}_s", 0.0))


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    return ordered[len(ordered) // 2] if ordered else 0.0


# ----------------------------------------------------------------- the rules
#
# Each rule: (report) -> Optional[Finding]. Rules see the whole merged
# report so cross-rank rules (straggler, stripe) need no special casing.


# Per-sub-step remediation for the consume-dominated verdict (snapxray
# micro-profiler, telemetry/consume_profile.py): the generic "consume is
# slow" advice becomes an actionable name once the breakdown says WHICH
# sub-step dominates.
_CONSUME_SUBSTEP_REMEDIATION = {
    "decode": (
        "codec decode dominates: zlib inflate is single-threaded per "
        "buffer — switch to zstd (TPUSNAPSHOT_CODEC) or drop "
        "compression for restore-latency-critical snapshots; chunk-"
        "store decodes already overlap reads, so more chunks ≠ faster "
        "decode."
    ),
    "deserialize": (
        "object deserialization dominates: large pickled objects "
        "(optimizer states saved as raw Python objects) restore "
        "single-threaded — convert them to arrays so they take the "
        "zero-copy array path."
    ),
    "verify": (
        "integrity verification dominates: checksums/fingerprints are "
        "CPU-bound per buffer. Keep verification on (it is the "
        "corruption net) but check for double verification "
        "(TPUSNAPSHOT_STRICT_INTEGRITY forces whole-object reads + "
        "full checksums) and prefer the chunk store's on-device "
        "fingerprints for large arrays."
    ),
    "reassemble": (
        "host memcpy dominates: bytes are being copied into assembly "
        "buffers before device placement. Larger contiguous chunks "
        "(raise TPUSNAPSHOT_CHUNK_BYTES) and the streaming read path "
        "(uncompressed, chunk-aligned payloads) skip host reassembly "
        "entirely."
    ),
    "device_put": (
        "H2D transfers are running INSIDE consume executors instead of "
        "on the overlap engine — the streaming fast path is not "
        "engaging (regions too small, compressed payloads, or a "
        "resharded template). Check restore_consume_vs_h2d in the "
        "bench artifact / h2d_overlap_vs_probe in this report, raise "
        "the H2D depth (TPUSNAPSHOT_H2D_DEPTH) and the device restore "
        "budget (TPUSNAPSHOT_DEVICE_BUDGET_BYTES) so more regions "
        "stream concurrently."
    ),
    "pool_wait": (
        "consumes are blocking on staging-pool capacity: concurrent "
        "restores (or very large plans) exhausted the pooled staging "
        "bytes. Raise TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES (0 "
        "disables pooling outright) or lower restore concurrency."
    ),
    "staging_release": (
        "buffer release/accounting dominates — pathological; likely "
        "lock contention between consume executors. Report this with "
        "the trace."
    ),
    "other": (
        "unaccounted consume time dominates (event-loop/executor "
        "scheduling, GIL waits): the pipeline is overhead-bound, not "
        "work-bound. Fewer, larger objects (raise chunk sizes) cut "
        "per-request overhead."
    ),
}


def _consume_profiles(report: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [
        s.get("consume_profile")
        for s in _ranks(report)
        if s.get("consume_profile")
    ]


def _rule_consume_dominated(report: Dict[str, Any]) -> Optional[Finding]:
    if report.get("kind") != "restore":
        return None
    consume = sum(_phase_s(s, "consume") for s in _ranks(report))
    read = sum(_phase_s(s, "read") for s in _ranks(report))
    if consume < _MIN_PHASE_S or consume < _DOMINANCE_RATIO * max(
        read, 1e-9
    ):
        return None
    evidence = {
        "consume_s": round(consume, 3),
        "read_s": round(read, 3),
        "ratio": round(consume / max(read, 1e-9), 1),
    }
    title = (
        f"restore spent {consume:.2f}s deserializing / placing "
        f"against {read:.2f}s of storage reads"
    )
    remediation = (
        "storage is innocent — the bottleneck is host-side "
        "deserialization / host->device placement. The streaming "
        "fast path should keep consume off the critical path: check "
        "compression settings (zlib inflate is single-threaded per "
        "buffer), confirm the overlap engine is engaging "
        "(h2d_overlap in the sub-step breakdown; tune "
        "TPUSNAPSHOT_H2D_DEPTH), give concurrent restores pool "
        "headroom (TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES), raise "
        "the device restore budget "
        "(TPUSNAPSHOT_DEVICE_BUDGET_BYTES), and confirm consumes "
        "overlap reads in the trace (summarize's overlap column)."
    )
    # Micro-profiler upgrade (snapxray): when rank summaries carry the
    # consume sub-phase breakdown, the finding names the dominant
    # sub-step and swaps in its specific remediation.
    profiles = _consume_profiles(report)
    if profiles:
        substeps: Dict[str, float] = {}
        overlap_s = 0.0
        for p in profiles:
            for name, entry in (p.get("substeps") or {}).items():
                # Beside-the-wall sub-steps: read_wait (scheduler
                # queueing), h2d_overlap (the streaming pipeline's
                # engine transfers), and overlap_other (engine-side
                # finalize work) overlap the consume wall — they must
                # not be named "the dominant consume sub-step".
                if name in ("read_wait", "overlap_other"):
                    continue
                if name == "h2d_overlap":
                    overlap_s += float(entry.get("seconds") or 0.0)
                    continue
                substeps[name] = substeps.get(name, 0.0) + float(
                    entry.get("seconds") or 0.0
                )
        if substeps:
            dominant = max(substeps, key=lambda s: substeps[s])
            evidence["dominant_substep"] = dominant
            evidence["dominant_substep_s"] = round(substeps[dominant], 3)
            evidence["substeps_s"] = {
                k: round(v, 3) for k, v in sorted(substeps.items())
            }
            fractions = [
                p.get("h2d_fraction")
                for p in profiles
                if p.get("h2d_fraction") is not None
            ]
            if fractions:
                evidence["consume_h2d_fraction"] = round(
                    min(fractions), 4
                )
            # Streaming-pipeline evidence: how hard the overlap engine
            # ran, and its delivered H2D vs the probe — named
            # restore_vs_h2d_ceiling to MATCH the bench key gating the
            # same quantity (consume_h2d_fraction above is the bench's
            # restore_consume_vs_h2d analog). A firing rule WITH
            # healthy overlap numbers points at host-side work
            # (decode/deserialize); without them the fast path never
            # engaged.
            if overlap_s:
                evidence["h2d_overlap_s"] = round(overlap_s, 3)
            overlap_fractions = [
                p.get("h2d_overlap_vs_probe")
                for p in profiles
                if p.get("h2d_overlap_vs_probe") is not None
            ]
            if overlap_fractions:
                evidence["restore_vs_h2d_ceiling"] = round(
                    min(overlap_fractions), 4
                )
            title += (
                f"; dominant sub-step: {dominant} "
                f"({substeps[dominant]:.2f}s)"
            )
            remediation = _CONSUME_SUBSTEP_REMEDIATION.get(
                dominant, remediation
            )
    return Finding(
        rule="consume-dominated-restore",
        severity="critical",
        title=title,
        evidence=evidence,
        remediation=remediation,
    )


def _rule_read_dominated(report: Dict[str, Any]) -> Optional[Finding]:
    if report.get("kind") != "restore":
        return None
    consume = sum(_phase_s(s, "consume") for s in _ranks(report))
    read = sum(_phase_s(s, "read") for s in _ranks(report))
    if read < _MIN_PHASE_S or read < _DOMINANCE_RATIO * max(consume, 1e-9):
        return None
    return Finding(
        rule="read-dominated-restore",
        severity="warn",
        title=(
            f"restore spent {read:.2f}s in storage reads against "
            f"{consume:.2f}s of consumes"
        ),
        evidence={
            "read_s": round(read, 3),
            "consume_s": round(consume, 3),
            "ratio": round(read / max(consume, 1e-9), 1),
        },
        remediation=(
            "storage read bandwidth is the bottleneck: check the "
            "backend's read concurrency cap, object sizes (many tiny "
            "objects pay per-request latency), and network egress "
            "limits."
        ),
    )


def _rule_stage_dominated(report: Dict[str, Any]) -> Optional[Finding]:
    if report.get("kind") not in ("take", "async_take"):
        return None
    stage = sum(
        float((s.get("scheduler_ops") or {}).get("stage", {}).get("seconds", 0.0))
        for s in _ranks(report)
    )
    write = sum(
        float((s.get("scheduler_ops") or {}).get("write", {}).get("seconds", 0.0))
        for s in _ranks(report)
    )
    if stage < _MIN_PHASE_S or stage < _DOMINANCE_RATIO * max(write, 1e-9):
        return None
    return Finding(
        rule="stage-dominated-take",
        severity="warn",
        title=(
            f"take spent {stage:.2f}s staging (device->host + "
            f"serialize) against {write:.2f}s of storage writes"
        ),
        evidence={
            "stage_s": round(stage, 3),
            "write_s": round(write, 3),
            "ratio": round(stage / max(write, 1e-9), 1),
        },
        remediation=(
            "device->host transfer / serialization is the bottleneck, "
            "not storage. Check compression cost, host CPU "
            "contention with the training step, and whether "
            "incremental takes (base=) could skip unchanged arrays."
        ),
    )


def _rule_budget_stall(report: Dict[str, Any]) -> Optional[Finding]:
    worst: Optional[Dict[str, Any]] = None
    for s in _ranks(report):
        wall = float(s.get("wall_s") or 0.0)
        stall = float((s.get("budget") or {}).get("stall_s", 0.0))
        if wall < 1.0 or stall < _STALL_FRACTION * wall:
            continue
        if worst is None or stall > worst["stall_s"]:
            worst = {
                "rank": s.get("rank"),
                "stall_s": round(stall, 3),
                "wall_s": round(wall, 3),
                "fraction": round(stall / wall, 2),
                "high_water_bytes": (s.get("budget") or {}).get(
                    "high_water_bytes", 0
                ),
            }
    if worst is None:
        return None
    return Finding(
        rule="budget-stall-dominated",
        severity="warn",
        title=(
            f"rank {worst['rank']} spent {worst['stall_s']:.2f}s "
            f"({100 * worst['fraction']:.0f}% of its wall time) stalled "
            f"on the memory budget"
        ),
        evidence=worst,
        remediation=(
            "the pipeline was ready to move bytes but the per-process "
            "memory budget said no. Raise "
            "TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES if host RAM "
            "allows, or reduce per-object sizes (chunked writes) so "
            "admission granularity is finer."
        ),
    )


def _rule_retry_storm(report: Dict[str, Any]) -> Optional[Finding]:
    totals = report.get("totals") or {}
    retries = float(totals.get("retries") or 0)
    if retries < _RETRY_STORM_COUNT:
        return None
    by_rank = {
        str(s.get("rank")): (s.get("retries") or {}).get("total", 0)
        for s in _ranks(report)
        if (s.get("retries") or {}).get("total", 0)
    }
    return Finding(
        rule="retry-storm",
        severity="critical",
        title=(
            f"{retries:g} storage retries across the operation — the "
            f"backend is throttling or flapping"
        ),
        evidence={"retries": retries, "by_rank": by_rank},
        remediation=(
            "check the storage backend's health/quota (429s = request "
            "rate or bandwidth quota; 503s = service brownout). The "
            "retry budget (TPUSNAPSHOT_STORAGE_RETRY_BUDGET_S) bounds "
            "how long each op keeps trying; fewer, larger objects "
            "reduce request-rate pressure."
        ),
    )


def _rule_straggler(report: Dict[str, Any]) -> Optional[Finding]:
    ranks = _ranks(report)
    if len(ranks) < 2:
        return None
    walls = [float(s.get("wall_s") or 0.0) for s in ranks]
    median = _median(walls)
    if median <= 0:
        return None
    worst = max(ranks, key=lambda s: float(s.get("wall_s") or 0.0))
    wall = float(worst.get("wall_s") or 0.0)
    if wall < _STRAGGLER_MIN_WALL_S or wall < _STRAGGLER_RATIO * median:
        return None
    return Finding(
        rule="straggler-rank",
        severity="warn",
        title=(
            f"rank {worst.get('rank')} took {wall:.2f}s against a "
            f"rank-median of {median:.2f}s"
        ),
        evidence={
            "rank": worst.get("rank"),
            "wall_s": round(wall, 3),
            "median_wall_s": round(median, 3),
            "ratio": round(wall / median, 2),
            "phases": worst.get("phases"),
        },
        remediation=(
            "one rank gated the whole operation. Compare its phase "
            "breakdown against the others (inspect --report): slow "
            "storage from one host, an imbalanced stripe, or host CPU "
            "contention. Cross-check with telemetry.merge's critical "
            "path on per-rank traces."
        ),
    )


def _rule_imbalanced_stripe(report: Dict[str, Any]) -> Optional[Finding]:
    ranks = _ranks(report)
    if len(ranks) < 2:
        return None
    sizes = [float(s.get("bytes") or 0) for s in ranks]
    median = _median(sizes)
    biggest = max(ranks, key=lambda s: float(s.get("bytes") or 0))
    top = float(biggest.get("bytes") or 0)
    if median <= 0 or top < _STRIPE_RATIO * median or top < 1 << 20:
        return None
    return Finding(
        rule="imbalanced-stripe",
        severity="warn",
        title=(
            f"rank {biggest.get('rank')} moved {top:.0f} bytes against "
            f"a rank-median of {median:.0f}"
        ),
        evidence={
            "rank": biggest.get("rank"),
            "bytes": int(top),
            "median_bytes": int(median),
            "ratio": round(top / median, 2),
        },
        remediation=(
            "byte load is skewed across ranks. For replicated values "
            "the striper balances by size estimates — non-array values "
            "estimate as 0 and spread by count, so one giant pickled "
            "object can skew a rank. Shard large values, or mark them "
            "replicated so the LPT striper can balance them."
        ),
    )


def _rule_checkpoint_overhead(report: Dict[str, Any]) -> Optional[Finding]:
    """Goodput verdict: checkpointing ate more than its wall-time budget
    (``TPUSNAPSHOT_CKPT_BUDGET_PCT``, default 5%). Needs a rank summary
    carrying the goodput accountant's attribution — i.e. a train loop
    that calls ``telemetry.goodput.step()``."""
    if report.get("kind") not in ("take", "async_take"):
        return None
    budget_pct = env_float(_CKPT_BUDGET_ENV_VAR, _DEFAULT_CKPT_BUDGET_PCT)
    worst: Optional[Dict[str, Any]] = None
    for s in _ranks(report):
        gp = s.get("goodput") or {}
        pct = gp.get("checkpoint_overhead_pct")
        window_s = (gp.get("train_s") or 0.0) + (gp.get("checkpoint_s") or 0.0)
        if pct is None or window_s < _MIN_GOODPUT_WINDOW_S:
            continue
        if pct > budget_pct and (worst is None or pct > worst["overhead_pct"]):
            worst = {
                "rank": s.get("rank"),
                "overhead_pct": pct,
                "budget_pct": budget_pct,
                "train_s": gp.get("train_s"),
                "checkpoint_s": gp.get("checkpoint_s"),
                "by_mode": gp.get("by_mode"),
            }
    if worst is None:
        return None
    return Finding(
        rule="checkpoint-overhead-above-budget",
        severity=(
            "critical" if worst["overhead_pct"] >= 2 * budget_pct else "warn"
        ),
        title=(
            f"checkpointing consumed {worst['overhead_pct']:.1f}% of wall "
            f"time against a {budget_pct:g}% budget"
        ),
        evidence=worst,
        remediation=(
            "checkpoint overhead exceeds the budget "
            f"({_CKPT_BUDGET_ENV_VAR}). by_mode names the spender: "
            "sync_take -> switch to async_save; async_stall -> stage="
            '"device" or shrink the cut; drain_wait -> the drain is '
            "slower than the save interval (raise the interval, use "
            "incremental takes, or check the storage backend); also see "
            "timeline's goodput trend for when the overhead started."
        ),
    )


def _rule_durability_lag(report: Dict[str, Any]) -> Optional[Finding]:
    """The hot tier's drain back-fills ``durability_lag_s`` (take ack →
    ``.tierdown``) into the committed report once the root fully tiers
    down; a window past the RPO budget means acked checkpoints rested
    on RAM replicas longer than the stated objective allows."""
    if report.get("kind") not in ("take", "async_take"):
        return None
    lag = report.get("durability_lag_s")
    if not isinstance(lag, (int, float)):
        return None
    from .slo import DURABILITY_LAG_ENV_VAR, durability_lag_budget_s

    budget_s = durability_lag_budget_s()
    if budget_s <= 0 or lag <= budget_s:
        return None
    return Finding(
        rule="durability-lag-above-budget",
        severity="critical" if lag >= 2 * budget_s else "warn",
        title=(
            f"take stayed undrained for {lag:.1f}s after its ack "
            f"(durability-lag budget {budget_s:g}s)"
        ),
        evidence={
            "durability_lag_s": round(float(lag), 3),
            "budget_s": budget_s,
            "take_id": report.get("take_id"),
        },
        remediation=(
            "the ack→.tierdown exposure window exceeded the RPO "
            "budget: a correlated host loss in that window would have "
            "cost an acked checkpoint. Tier-down bandwidth is below "
            "the take cadence — lower the save frequency, use "
            "incremental takes, check durable-backend health, or "
            f"re-state the budget ({DURABILITY_LAG_ENV_VAR})."
        ),
    )


def _rule_missing_summary(report: Dict[str, Any]) -> Optional[Finding]:
    ranks = report.get("ranks") or []
    missing = [i for i, s in enumerate(ranks) if not s]
    if not missing or report.get("kind") == "restore":
        # Restore reports are rank-local by design; their ranks list
        # holds one summary regardless of world size.
        return None
    return Finding(
        rule="missing-rank-summary",
        severity="warn",
        title=f"rank(s) {missing} contributed no flight summary",
        evidence={"missing_ranks": missing},
        remediation=(
            "the operation committed but those ranks' summaries never "
            "arrived — a crashed-and-restarted process, or a summary "
            "write that lost its race with the commit. If it recurs, "
            "check those hosts' logs."
        ),
    )


def _rule_hot_tier_degraded(report: Dict[str, Any]) -> Optional[Finding]:
    """A restore that should have been served from peer RAM leaked reads
    to the durable tier: >0 per-object fallbacks fire a warning, and a
    majority of the BYTES falling back (the hot tier effectively absent —
    preempted peers, corrupt replicas, an undersized
    TPUSNAPSHOT_HOT_TIER_BYTES) is critical. Evidence names the degraded
    peer hosts range-compressed, the same rendering as coord timeouts."""
    from ..coord import format_rank_list

    if report.get("kind") != "restore":
        return None
    tiers = [
        s.get("tier") for s in _ranks(report) if s.get("tier")
    ]
    if not tiers:
        return None
    fallback_objects = sum(int(t.get("fallback_objects") or 0) for t in tiers)
    if fallback_objects <= 0:
        return None
    fallback_bytes = sum(int(t.get("fallback_bytes") or 0) for t in tiers)
    hot_bytes = sum(int(t.get("hot_bytes") or 0) for t in tiers)
    total_bytes = hot_bytes + fallback_bytes
    fraction = fallback_bytes / total_bytes if total_bytes > 0 else 1.0
    peers = sorted(
        {int(p) for t in tiers for p in (t.get("degraded_peers") or [])}
    )
    reasons: Dict[str, int] = {}
    for t in tiers:
        for r, c in (t.get("fallback_reasons") or {}).items():
            reasons[r] = reasons.get(r, 0) + int(c)
    return Finding(
        rule="hot-tier-degraded",
        severity="critical" if fraction > 0.5 else "warn",
        title=(
            f"restore fell back to the durable tier for "
            f"{fallback_objects} object(s) "
            f"({100 * fraction:.0f}% of bytes); degraded "
            f"{format_rank_list(peers, noun='peer host')}"
        ),
        evidence={
            "fallback_objects": fallback_objects,
            "fallback_bytes": fallback_bytes,
            "hot_bytes": hot_bytes,
            "fallback_byte_fraction": round(fraction, 3),
            "degraded_peers": format_rank_list(peers, noun="peer host"),
            "reasons": reasons,
        },
        remediation=(
            "the hot tier could not serve these objects: 'dead' peers "
            "mean preempted/lost hosts (raise TPUSNAPSHOT_HOT_TIER_K if "
            "losses exceed k-1), 'missing' means replicas were evicted "
            "or never placed (raise TPUSNAPSHOT_HOT_TIER_BYTES), "
            "'corrupt' means a replica failed its fingerprint check "
            "(the fallback kept the restore correct; investigate the "
            "host's RAM). Durable-tier restores are storage-speed — "
            "expect minutes, not seconds, until the tier is healthy."
        ),
    )


def _rule_replication_degraded(report: Dict[str, Any]) -> Optional[Finding]:
    """A take whose k-replication rode the snapwire transport showed
    wire distress: any deadline-missed or failed push warns
    (replication is limping — acks still honest, but each failure
    burned a deadline/retry episode), and wire failures combined with a
    MAJORITY of the acked bytes having ridden the synchronous
    write-through path is critical — the transport is effectively
    absent and every "RAM-speed" ack is paying storage latency before
    it returns. Write-throughs WITHOUT wire failures (healthy pushes,
    full peers) are a capacity problem, not a transport one, and stay
    out of this rule."""
    if report.get("kind") != "take":
        return None
    reps = [
        (s.get("tier") or {}).get("replication")
        for s in _ranks(report)
        if (s.get("tier") or {}).get("replication")
    ]
    if not reps:
        return None
    deadline_misses = sum(
        int(r.get("deadline_misses") or 0) for r in reps
    )
    retries = sum(int(r.get("retries") or 0) for r in reps)
    push_failures = sum(int(r.get("push_failures") or 0) for r in reps)
    wt_bytes = sum(int(r.get("write_through_bytes") or 0) for r in reps)
    replicated_bytes = sum(
        int(r.get("replicated_ack_bytes") or 0) for r in reps
    )
    acked = wt_bytes + replicated_bytes
    fraction = wt_bytes / acked if acked > 0 else 0.0
    # The critical arm requires actual WIRE distress behind the
    # write-through bytes: a capacity-degraded take with a healthy
    # transport (every push acked, peers simply full) is a hot-tier
    # sizing problem, not a network one — misdiagnosing it critical
    # would send the operator chasing a phantom transport failure.
    wire_failed = deadline_misses > 0 or push_failures > 0
    if not wire_failed:
        return None
    severity = "critical" if fraction > 0.5 else "warn"
    pushes = sum(int(r.get("pushes") or 0) for r in reps)
    return Finding(
        rule="replication-degraded",
        severity=severity,
        title=(
            f"hot-tier replication degraded: {deadline_misses} deadline "
            f"miss(es), {100 * fraction:.0f}% of acked bytes rode the "
            f"synchronous write-through path"
        ),
        evidence={
            "deadline_misses": deadline_misses,
            "retries": retries,
            "pushes": pushes,
            "push_failures": push_failures,
            "write_through_bytes": wt_bytes,
            "replicated_ack_bytes": replicated_bytes,
            "write_through_byte_fraction": round(fraction, 3),
        },
        remediation=(
            "peer pushes are missing TPUSNAPSHOT_REPLICATION_DEADLINE_S "
            "or exhausting TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S: check "
            "peer-process health (hottier.peer logs), the address book "
            "(TPUSNAPSHOT_HOT_TIER_ADDRS), and network latency between "
            "hosts. Acks stay honest either way — degraded puts write "
            "through to the durable tier BEFORE acking — but every "
            "write-through ack pays storage latency instead of RAM "
            "latency, eroding the tier's whole point."
        ),
    )


def _rule_read_plane_degraded(report: Dict[str, Any]) -> Optional[Finding]:
    """A restore routed through the snapserve read plane leaked reads
    to direct backend access: >0 fallbacks fire a warning (the restore
    stayed bit-exact — that is the fallback's contract — but every
    fallback re-pays the backend read the service exists to
    deduplicate), and a majority of the BYTES falling back (the server
    effectively absent) is critical. Reasons: 'unreachable' = a dial or
    transport failure on that very read; 'down' = inside the
    post-failure cooldown window (the server was seen dead moments
    before)."""
    if report.get("kind") != "restore":
        return None
    planes = [
        s.get("read_plane") for s in _ranks(report) if s.get("read_plane")
    ]
    if not planes:
        return None
    fallback_objects = sum(
        int(p.get("fallback_objects") or 0) for p in planes
    )
    if fallback_objects <= 0:
        return None
    fallback_bytes = sum(int(p.get("fallback_bytes") or 0) for p in planes)
    remote_bytes = sum(int(p.get("remote_bytes") or 0) for p in planes)
    total_bytes = remote_bytes + fallback_bytes
    fraction = fallback_bytes / total_bytes if total_bytes > 0 else 1.0
    reasons: Dict[str, int] = {}
    for p in planes:
        for r, c in (p.get("fallback_reasons") or {}).items():
            reasons[r] = reasons.get(r, 0) + int(c)
    return Finding(
        rule="read-plane-degraded",
        severity="critical" if fraction > 0.5 else "warn",
        title=(
            f"restore fell back to direct backend reads for "
            f"{fallback_objects} object(s) "
            f"({100 * fraction:.0f}% of bytes) — the snapserve read "
            f"plane was unreachable"
        ),
        evidence={
            "fallback_objects": fallback_objects,
            "fallback_bytes": fallback_bytes,
            "remote_bytes": remote_bytes,
            "fallback_byte_fraction": round(fraction, 3),
            "reasons": reasons,
        },
        remediation=(
            "the restore stayed bit-exact (direct fallback is the "
            "degraded-mode contract), but each falling-back client "
            "re-pays backend reads the service would have "
            "deduplicated — at fleet fan-out that multiplies "
            "object-store egress. Check the snapserve server process "
            "and TPUSNAPSHOT_SNAPSERVE_ADDR routing; restart the "
            "server and clients reattach automatically on their next "
            "read (after the cooldown window)."
        ),
    )


def _rule_fleet_degraded(report: Dict[str, Any]) -> Optional[Finding]:
    """A fleet-routed restore did not get every object from its ring
    owner: failovers (a member failed mid-read and a replica served),
    owner misses (the owner was down-latched), or full fleet
    exhaustion (reason 'fleet-exhausted' direct fallbacks). Bytes
    stayed bit-exact — that is the ladder's contract — but every
    non-owner read lands on a member whose cache does NOT shard that
    key, duplicating cache footprint and backend egress fleet-wide.
    Critical when the fleet was exhausted (some reads went direct);
    warn otherwise."""
    if report.get("kind") != "restore":
        return None
    planes = [
        s.get("read_plane") for s in _ranks(report) if s.get("read_plane")
    ]
    if not planes:
        return None
    owner_misses = sum(int(p.get("owner_misses") or 0) for p in planes)
    failover = sum(int(p.get("failover_objects") or 0) for p in planes)
    exhausted = sum(
        int((p.get("fallback_reasons") or {}).get("fleet-exhausted") or 0)
        for p in planes
    )
    if owner_misses <= 0 and failover <= 0 and exhausted <= 0:
        return None
    servers: Dict[str, Dict[str, int]] = {}
    for p in planes:
        for addr, entry in (p.get("servers") or {}).items():
            agg = servers.setdefault(addr, {"objects": 0, "bytes": 0})
            agg["objects"] += int(entry.get("objects") or 0)
            agg["bytes"] += int(entry.get("bytes") or 0)
    return Finding(
        rule="fleet-degraded",
        severity="critical" if exhausted > 0 else "warn",
        title=(
            f"fleet-routed restore left the ring owner for "
            f"{owner_misses + failover + exhausted} object(s) "
            f"({failover} failover, {owner_misses} owner-miss, "
            f"{exhausted} fleet-exhausted direct fallback)"
        ),
        evidence={
            "owner_misses": owner_misses,
            "failover_objects": failover,
            "fleet_exhausted_fallbacks": exhausted,
            "servers": servers,
        },
        remediation=(
            "bytes stayed bit-exact (replica failover and direct "
            "fallback are the degraded-mode contract), but non-owner "
            "reads defeat the ring's cache sharding: each displaced "
            "key is now cached on (and fetched by) a member that "
            "doesn't own it. Check which members died or hung "
            "(tpusnapshot_snapserve_fleet_probes_total{result}), "
            "restart them — a respawn re-registers one generation up "
            "and reclaims its ring segment automatically — and verify "
            "TPUSNAPSHOT_SNAPSERVE_FLEET_ADDRS lists the same members "
            "on every client."
        ),
    )


# Chunking must have covered at least this much logical payload before
# the dedup-ineffective verdict means anything (a 2 MiB toy take proves
# nothing about chunk-grid fit).
_DEDUP_MIN_LOGICAL_BYTES = 32 << 20


def _rule_dedup_ineffective(report: Dict[str, Any]) -> Optional[Finding]:
    """Chunk-granular dedup (chunkstore.py) is pure overhead when every
    saved byte would have been saved by LEAF-granular dedup anyway:
    chunk hits ≤ bytes of fully-clean leaves means sub-leaf
    content-addressing bought nothing this take — the chunk grid does
    not match the workload's dirty pattern (or the model is fully
    clean/fully dirty)."""
    notes = [
        s.get("churn")
        for s in _ranks(report)
        if s.get("churn") and (
            (s["churn"].get("chunk_hits") or 0)
            + (s["churn"].get("chunk_misses") or 0)
        )
    ]
    if not notes:
        return None
    logical = sum(int(c.get("chunk_logical_bytes") or 0) for c in notes)
    hit = sum(int(c.get("chunk_hit_bytes") or 0) for c in notes)
    clean = sum(int(c.get("leaf_clean_bytes") or 0) for c in notes)
    misses = sum(int(c.get("chunk_misses") or 0) for c in notes)
    floor = int(
        env_float(
            "TPUSNAPSHOT_DEDUP_MIN_BYTES", _DEDUP_MIN_LOGICAL_BYTES
        )
    )
    if logical < floor or hit + clean == 0:
        return None  # first take / thin evidence: silence
    if hit > clean:
        return None  # sub-leaf dedup saved bytes leaf dedup could not
    return Finding(
        rule="dedup-ineffective",
        severity="warn",
        title=(
            f"chunk-granular dedup saved {hit / (1 << 20):.1f} MiB, all "
            f"of it inside fully-clean leaves "
            f"({clean / (1 << 20):.1f} MiB) — chunking overhead without "
            f"sub-leaf savings"
        ),
        evidence={
            "chunk_hit_bytes": hit,
            "leaf_clean_bytes": clean,
            "chunk_logical_bytes": logical,
            "chunk_misses": misses,
        },
        remediation=(
            "every deduplicated byte came from leaves that were "
            "entirely unchanged — leaf-granular incremental takes "
            "(base=/manager incremental mode) would have saved the "
            "same bytes without per-chunk fingerprints, store lookups, "
            "and manifest chunk records. If partially-dirty leaves "
            "exist, shrink TPUSNAPSHOT_CHUNK_BYTES so the grid "
            "resolves their dirty regions; otherwise disable chunking "
            "(TPUSNAPSHOT_CHUNKS=0) for this workload."
        ),
    )


def wire_margin_warn_threshold() -> float:
    return env_float(_WIRE_MARGIN_WARN_ENV_VAR, _DEFAULT_WIRE_MARGIN_WARN)


def wire_pressure_finding(
    ops: Dict[str, Any], source: str = "report"
) -> Optional[Finding]:
    """The shared deadline-margin verdict over wiretap per-op blocks —
    flight-report ``wire`` blocks post-hoc (this module), sampler
    ``wire`` blocks live (telemetry/slo.py): same rule id both ways.

    Critical when the window recorded outright deadline misses; warn
    when an op's p99 consumed >= TPUSNAPSHOT_WIRE_MARGIN_WARN of its
    per-RPC deadline — the hand-tuned knob is one latency wobble from
    collapsing onto real latency."""
    if not ops:
        return None
    warn_at = wire_margin_warn_threshold()
    misses = 0
    pressured: List[Any] = []
    for op_key, entry in ops.items():
        if not isinstance(entry, dict):
            continue
        op_misses = int(entry.get("deadline_misses") or 0)
        misses += op_misses
        margin = entry.get("margin_p99")
        if op_misses > 0 or (
            margin is not None and float(margin) >= warn_at
        ):
            pressured.append(
                (op_misses, float(margin or 0.0), op_key, entry)
            )
    if not pressured:
        return None
    pressured.sort(reverse=True)
    evidence = {
        "source": source,
        "deadline_misses": misses,
        "margin_warn_at": warn_at,
        "pressured_ops": [
            {
                "op": op_key,
                "margin_p99": round(margin, 4) if margin else None,
                "p99_s": entry.get("p99_s"),
                "deadline_s": entry.get("deadline_s"),
                "deadline_misses": op_misses,
            }
            for op_misses, margin, op_key, entry in pressured[:5]
        ],
    }
    worst = pressured[0]
    if misses > 0:
        title = (
            f"{misses} wire RPC(s) missed their deadline "
            f"(worst op: {worst[2]})"
        )
        severity = "critical"
    else:
        title = (
            f"wire op {worst[2]} p99 is consuming "
            f"{worst[1]:.0%} of its RPC deadline "
            f"(warn threshold {warn_at:.0%})"
        )
        severity = "warn"
    return Finding(
        rule="deadline-margin-collapsing",
        severity=severity,
        title=title,
        evidence=evidence,
        remediation=(
            "the per-RPC deadline budget is collapsing onto real "
            "latency for the ops listed. Either the knob is mis-sized "
            "— raise TPUSNAPSHOT_REPLICATION_DEADLINE_S (snapwire "
            "ops) / TPUSNAPSHOT_SNAPSERVE_TIMEOUT_S (snapserve ops) — "
            "or the wire got slower: check peer placement and payload "
            "sizes (delta replication + codec settings shrink push "
            "frames). Misses already take the safe degradation paths "
            "(write-through before the ack, direct-backend fallback "
            "reads), so correctness held; latency is paying for it."
        ),
    )


def _rule_deadline_margin_collapsing(
    report: Dict[str, Any]
) -> Optional[Finding]:
    # Merge per-rank wire blocks per op: counts sum, quantiles take the
    # worst rank (a p99 cannot be averaged across ranks).
    ops: Dict[str, Dict[str, Any]] = {}
    for s in _ranks(report):
        for op_key, entry in (s.get("wire") or {}).items():
            if not isinstance(entry, dict):
                continue
            acc = ops.get(op_key)
            if acc is None:
                ops[op_key] = dict(entry)
                continue
            for k in ("count", "deadline_misses", "retries"):
                acc[k] = int(acc.get(k) or 0) + int(entry.get(k) or 0)
            for k in ("p99_s", "margin_p99", "margin_max"):
                v = entry.get(k)
                if v is not None:
                    acc[k] = max(float(acc.get(k) or 0.0), float(v))
    return wire_pressure_finding(ops, source="report")


# -------------------------------------------------- host memory (snapmem)
#
# The memory rules read memwatch blocks — flight-report ``memory``
# windows post-hoc (the _rule_* wrappers below), sampler ``memory``
# blocks live (telemetry/slo.py), fleet stats RPC blocks (ops --mem) —
# through the two shared helpers, so every surface renders the same
# verdict for the same numbers.

# Cache-misfit heuristics only speak once the cache saw real traffic.
_CACHE_MIN_LOOKUPS = 20


def memory_pressure_finding(
    mem: Dict[str, Any], source: str = "report"
) -> Optional[Finding]:
    """The shared ``host-memory-overcommit`` verdict over one memwatch
    block (flight-report window, sampler sample, or fleet stats).

    Critical when committed bytes actually landed past a limit — a
    domain's high-water above its cap, or the aggregate high-water
    past the host budget. Warn when only the pre-storm forecast
    predicted an overcommit (the storm may still have fit — RSS
    headroom is elastic; the point is to say so BEFORE the OOM
    killer does)."""
    if not mem:
        return None
    over_domains: List[Dict[str, Any]] = []
    for name, d in sorted((mem.get("domains") or {}).items()):
        if not isinstance(d, dict) or d.get("cap_bytes") is None:
            continue
        hwm = int(
            d.get("high_water_bytes")
            if d.get("high_water_bytes") is not None
            else d.get("used_bytes") or 0
        )
        cap = int(d["cap_bytes"])
        if hwm > cap:
            over_domains.append(
                {"domain": name, "high_water_bytes": hwm, "cap_bytes": cap}
            )
    budget = mem.get("budget_bytes")
    agg_hwm = int(mem.get("high_water_bytes") or 0)
    budget_over = budget is not None and agg_hwm > int(budget)
    forecasts = mem.get("forecasts")
    n_forecasts = (
        len(forecasts)
        if isinstance(forecasts, list)
        else int(forecasts or 0)
    )
    if not over_domains and not budget_over and not n_forecasts:
        return None
    evidence: Dict[str, Any] = {
        "source": source,
        "high_water_bytes": agg_hwm,
        "budget_bytes": budget,
    }
    if over_domains:
        evidence["over_cap_domains"] = over_domains[:5]
    if n_forecasts:
        evidence["overcommit_forecasts"] = n_forecasts
    if over_domains:
        worst = over_domains[0]
        title = (
            f"domain {worst['domain']} high-water "
            f"{worst['high_water_bytes']} bytes exceeds its "
            f"{worst['cap_bytes']}-byte cap"
        )
        severity = "critical"
    elif budget_over:
        title = (
            f"committed host memory high-water {agg_hwm} bytes exceeds "
            f"the {budget}-byte host budget"
        )
        severity = "critical"
    else:
        title = (
            f"{n_forecasts} pre-storm forecast(s) predicted the "
            f"operation's byte demand would not fit live host headroom"
        )
        severity = "warn"
    return Finding(
        rule="host-memory-overcommit",
        severity=severity,
        title=title,
        evidence=evidence,
        remediation=(
            "the process's byte-capped domains are collectively "
            "promising more host RAM than the host gives. Lower the "
            "overcommitting domain's cap (scheduler "
            "TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES, pool "
            "TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES, snapserve cache/"
            "flow knobs), or raise/verify TPUSNAPSHOT_HOST_MEM_BUDGET "
            "if the detected limit is wrong. `ops --mem` shows which "
            "process and domain is the offender."
        ),
    )


def cache_misfit_finding(
    cache: Dict[str, Any], source: str = "report"
) -> Optional[Finding]:
    """The shared ``cache-cap-misfit`` verdict over ByteLRU counters
    (windowed deltas from a memory block, or cumulative server stats).

    Warn on THRASH — the cache runs at its cap while evicting nearly
    as fast as it inserts with a sub-50% hit ratio (the cap is too
    small for the working set) — and on OVERSIZE — plenty of traffic
    but occupancy never reached a quarter of the cap (RAM promised to
    a cache that does not need it)."""
    if not cache:
        return None
    hits = int(cache.get("hits") or 0)
    misses = int(cache.get("misses") or 0)
    evictions = int(cache.get("evictions") or 0)
    inserts = int(cache.get("inserts") or 0)
    lookups = hits + misses
    cap = cache.get("cap_bytes")
    hwm = int(cache.get("high_water_bytes") or 0)
    if lookups < _CACHE_MIN_LOOKUPS or not cap:
        return None
    cap = int(cap)
    hit_ratio = hits / lookups
    evidence = {
        "source": source,
        "hits": hits,
        "misses": misses,
        "evictions": evictions,
        "inserts": inserts,
        "hit_ratio": round(hit_ratio, 3),
        "cap_bytes": cap,
        "high_water_bytes": hwm,
    }
    if (
        hwm >= 0.95 * cap
        and hit_ratio < 0.5
        and inserts > 0
        and evictions >= 0.5 * inserts
    ):
        return Finding(
            rule="cache-cap-misfit",
            severity="warn",
            title=(
                f"read cache is thrashing: {hit_ratio:.0%} hit ratio at "
                f"a full {cap}-byte cap with {evictions} evictions "
                f"against {inserts} inserts"
            ),
            evidence=evidence,
            remediation=(
                "the working set does not fit the cache — entries are "
                "evicted before they are re-read. Raise "
                "TPUSNAPSHOT_SNAPSERVE_CACHE_BYTES (watch `ops --mem` "
                "headroom first), or accept backend re-reads if RAM is "
                "the scarcer resource."
            ),
        )
    if hwm < 0.25 * cap and lookups >= 2 * _CACHE_MIN_LOOKUPS:
        return Finding(
            rule="cache-cap-misfit",
            severity="warn",
            title=(
                f"read cache cap is oversized: occupancy never passed "
                f"{hwm} bytes of a {cap}-byte cap across "
                f"{lookups} lookups"
            ),
            evidence=evidence,
            remediation=(
                "the cap promises RAM the working set never uses — "
                "lower TPUSNAPSHOT_SNAPSERVE_CACHE_BYTES and give the "
                "headroom back to the host budget."
            ),
        )
    return None


def _merged_memory(report: Dict[str, Any]) -> Dict[str, Any]:
    """Merge per-rank memory windows for the rule wrappers: per-domain
    high-waters/residuals take the worst rank, the aggregate high-water
    takes the worst rank, forecasts sum."""
    merged: Dict[str, Any] = {"domains": {}}
    agg = 0
    budget = None
    forecasts = 0
    seen = False
    for s in _ranks(report):
        mem = s.get("memory")
        if not mem:
            continue
        seen = True
        for name, d in (mem.get("domains") or {}).items():
            if not isinstance(d, dict):
                continue
            acc = merged["domains"].setdefault(name, {})
            for k in ("high_water_bytes", "residual_bytes"):
                if d.get(k) is not None:
                    acc[k] = max(int(acc.get(k) or 0), int(d[k]))
            if d.get("cap_bytes") is not None:
                acc["cap_bytes"] = int(d["cap_bytes"])
            for ck, cv in (d.get("counters") or {}).items():
                counters = acc.setdefault("counters", {})
                counters[ck] = int(counters.get(ck, 0)) + int(cv)
        agg = max(agg, int(mem.get("high_water_bytes") or 0))
        if mem.get("budget_bytes") is not None:
            b = int(mem["budget_bytes"])
            budget = b if budget is None else min(budget, b)
        forecasts += len(mem.get("forecasts") or [])
    if not seen:
        return {}
    merged["high_water_bytes"] = agg
    merged["budget_bytes"] = budget
    if forecasts:
        merged["forecasts"] = forecasts
    return merged


def _rule_host_memory_overcommit(
    report: Dict[str, Any]
) -> Optional[Finding]:
    return memory_pressure_finding(
        _merged_memory(report), source="report"
    )


def _rule_memory_leak(report: Dict[str, Any]) -> Optional[Finding]:
    # Single-report residual check: a completed operation whose
    # residual-watched domain still holds real bytes. The cross-record
    # TREND (the sentinel proper) lives in memwatch.leak_findings over
    # a ledger series; this rule catches the egregious single-shot
    # case — bytes a finished take/restore plainly never gave back.
    from .memwatch import LEAK_MIN_BYTES_ENV_VAR

    floor = env_int(LEAK_MIN_BYTES_ENV_VAR, 1 << 20)
    merged = _merged_memory(report)
    worst: Optional[Tuple[int, str]] = None
    for name, d in sorted((merged.get("domains") or {}).items()):
        residual = d.get("residual_bytes")
        if residual is not None and int(residual) >= max(1, floor):
            if worst is None or int(residual) > worst[0]:
                worst = (int(residual), name)
    if worst is None:
        return None
    residual, name = worst
    return Finding(
        rule="memory-leak-suspected",
        severity="warn",
        title=(
            f"domain {name} still holds {residual} bytes after the "
            f"operation completed"
        ),
        evidence={
            "source": "report",
            "domain": name,
            "residual_bytes": residual,
        },
        remediation=(
            "a completed operation left live bytes in a domain that "
            "should return to baseline. Run the sentinel over the "
            "ledger (python -m torchsnapshot_tpu.telemetry.memwatch "
            "<path>) to see whether the residual is growing across "
            "operations — a flat residual is retention, a growing one "
            "is a leak in the named domain's release path."
        ),
    )


def _rule_staging_pool_thrash(
    report: Dict[str, Any]
) -> Optional[Finding]:
    # Windowed pool counter deltas: waits mean acquisitions blocked at
    # the cap, and misses+waits dominating hits means the pool is too
    # small to ever serve its purpose — every acquire allocates or
    # stalls instead of reusing.
    merged = _merged_memory(report)
    pool = (merged.get("domains") or {}).get("staging_pool") or {}
    counters = pool.get("counters") or {}
    hits = int(counters.get("hits") or 0)
    misses = int(counters.get("misses") or 0)
    waits = int(counters.get("waits") or 0)
    if waits <= 0 or misses + waits <= hits:
        return None
    return Finding(
        rule="staging-pool-thrash",
        severity="warn",
        title=(
            f"staging pool thrashed this operation: {waits} capacity "
            f"wait(s), {misses} misses against {hits} hits"
        ),
        evidence={
            "source": "report",
            "hits": hits,
            "misses": misses,
            "waits": waits,
            "cap_bytes": pool.get("cap_bytes"),
            "high_water_bytes": pool.get("high_water_bytes"),
        },
        remediation=(
            "restore consumers blocked on the staging-pool cap and "
            "most acquisitions could not reuse a buffer. Raise "
            "TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES toward the "
            "restore's working set (watch `ops --mem` headroom), or "
            "lower read concurrency so fewer buffers are live at once."
        ),
    )


def _rule_cache_cap_misfit(report: Dict[str, Any]) -> Optional[Finding]:
    merged = _merged_memory(report)
    cache = (merged.get("domains") or {}).get("snapserve.cache") or {}
    counters = dict(cache.get("counters") or {})
    counters["cap_bytes"] = cache.get("cap_bytes")
    counters["high_water_bytes"] = cache.get("high_water_bytes")
    return cache_misfit_finding(counters, source="report")


RULES: List[Callable[[Dict[str, Any]], Optional[Finding]]] = [
    _rule_consume_dominated,
    _rule_read_dominated,
    _rule_stage_dominated,
    _rule_budget_stall,
    _rule_retry_storm,
    _rule_straggler,
    _rule_imbalanced_stripe,
    _rule_checkpoint_overhead,
    _rule_durability_lag,
    _rule_missing_summary,
    _rule_hot_tier_degraded,
    _rule_replication_degraded,
    _rule_read_plane_degraded,
    _rule_fleet_degraded,
    _rule_dedup_ineffective,
    _rule_deadline_margin_collapsing,
    _rule_host_memory_overcommit,
    _rule_memory_leak,
    _rule_staging_pool_thrash,
    _rule_cache_cap_misfit,
]

_SEVERITY_ORDER = {"critical": 0, "warn": 1}


def diagnose_report(report: Dict[str, Any]) -> List[Finding]:
    """Run the whole rule table over one flight report."""
    findings = [f for f in (rule(report) for rule in RULES) if f]
    findings.sort(key=lambda f: (_SEVERITY_ORDER.get(f.severity, 9), f.rule))
    return findings


def diagnose(
    reports: List[Dict[str, Any]],
    trace_summary: Optional[Dict[str, Any]] = None,
) -> List[Finding]:
    """Findings across several reports (a take report plus restore
    reports, as ``inspect --doctor`` collects them), plus the trace
    summarizer's dominance verdict when a summary is supplied and no
    report already made the same call."""
    findings: List[Finding] = []
    for report in reports:
        findings.extend(diagnose_report(report))
    verdict = (trace_summary or {}).get("verdict")
    if verdict and verdict.get("dominated"):
        rule = (
            f"{verdict['dominant_phase']}-dominated-"
            f"{verdict['pipeline']}"
        )
        if not any(f.rule.startswith(verdict["dominant_phase"]) for f in findings):
            findings.append(
                Finding(
                    rule=rule,
                    severity="warn",
                    title=(
                        f"trace: {verdict['pipeline']} is "
                        f"{verdict['dominant_phase']}-dominated "
                        f"({verdict['busy_s']:.2f}s busy vs "
                        f"{verdict['sibling']} "
                        f"{verdict['sibling_busy_s']:.2f}s)"
                    ),
                    evidence=dict(verdict),
                    remediation=(
                        "see telemetry.summarize's advice line for this "
                        "phase."
                    ),
                )
            )
    findings.sort(key=lambda f: (_SEVERITY_ORDER.get(f.severity, 9), f.rule))
    return findings


def render_findings(findings: List[Finding]) -> str:
    if not findings:
        return "doctor: no findings — nothing anomalous in the report(s)"
    lines = [f"doctor: {len(findings)} finding(s)"]
    for f in findings:
        lines.append(f"[{f.severity.upper():8s}] {f.rule}: {f.title}")
        if f.evidence:
            ev = ", ".join(f"{k}={v}" for k, v in sorted(f.evidence.items()))
            lines.append(f"           evidence: {ev}")
        if f.remediation:
            lines.append(f"           remediation: {f.remediation}")
    return "\n".join(lines)


def _collect_snapshot_reports(path: str) -> List[Dict[str, Any]]:
    """The take report + any restore reports a snapshot holds."""
    import asyncio

    from ..storage_plugin import url_to_storage_plugin
    from . import report as flight

    storage = url_to_storage_plugin(path)
    try:
        reports: List[Dict[str, Any]] = []
        take = asyncio.run(flight.aread_json(storage, flight.REPORT_FNAME))
        if take is not None:
            reports.append(take)
        for p in sorted(
            asyncio.run(storage.list_prefix(flight.REPORT_PREFIX)) or []
        ):
            if p.startswith(".report.restore."):
                doc = asyncio.run(flight.aread_json(storage, p))
                if doc is not None:
                    reports.append(doc)
        return reports
    finally:
        storage.close()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry.doctor",
        description="Diagnose a snapshot operation's flight report(s) "
        "against the anomaly rule table.",
    )
    parser.add_argument(
        "path",
        help="snapshot URL (reads its .report.json + restore reports) "
        "or a path to one report JSON file",
    )
    parser.add_argument(
        "--trace",
        help="optional Chrome trace to fold for a dominance verdict",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit findings as JSON"
    )
    args = parser.parse_args(argv)

    import os

    reports: List[Dict[str, Any]]
    if "://" not in args.path and os.path.isfile(args.path):
        try:
            with open(args.path) as f:
                reports = [json.load(f)]
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        try:
            reports = _collect_snapshot_reports(args.path)
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if not reports:
        print(f"no flight report at {args.path}", file=sys.stderr)
        return 2

    trace_summary = None
    if args.trace:
        from . import summarize as _summarize

        try:
            trace_summary = _summarize.summarize(
                _summarize.fold_spans(_summarize.load_events(args.trace))
            )
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    findings = diagnose(reports, trace_summary=trace_summary)
    if args.json:
        print(
            json.dumps(
                [f.as_dict() for f in findings], indent=2, sort_keys=True
            )
        )
    else:
        print(render_findings(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
