"""snapscope's reading half: the unified live-operations view.

``watch`` renders in-flight progress, ``doctor`` diagnoses reports,
``slo`` judges objectives, the sampler publishes runtime state — four
views an operator would have to correlate by hand during an incident.
This CLI merges them into one per-rank operational display::

    python -m torchsnapshot_tpu.telemetry.ops <path> [--json]

``<path>`` is either a snapshot/ledger URL (storage mode: reads
``.progress/<take_id>/<rank>`` progress objects, ``.scope/rank<N>``
sampler records, the telemetry ledger, and the committed flight
reports) or a local live-ops directory (``TPUSNAPSHOT_PROGRESS_DIR``
statusfiles: ``rank<N>.progress.json`` + ``rank<N>.scope.jsonl``).
When the hot tier is enabled IN THIS PROCESS the view additionally
samples the runtime directly, so an embedded caller (or a test) sees
the drain pipeline with no publishing round-trip.

Sections, each omitted when it has nothing to say:

- **in-flight operations** — ``watch``'s per-rank table (phase, bytes,
  throughput, ETA, heartbeat staleness), including the hot tier's
  background ``tierdown`` records, so a drain backlog is visible as a
  live operation rather than post-commit darkness;
- **drain pipeline** — per-rank sampler state: queue depth, in-flight,
  oldest pending-object age, at-risk bytes per committed root,
  stranded items, drain heartbeat age, per-host replica occupancy;
- **scheduler** — live memory-budget occupancy / stalled state;
- **SLOs & findings** — the SLO engine's burn-rate table over the
  ledger plus its live rules, and any doctor findings from the
  snapshot's committed reports.

Exit codes (watch-style, CI/pager-facing): 0 = healthy (live work may
be in flight — a draining backlog is normal operation); 1 = a CRITICAL
finding is active (stranded drains — the output names the roots —
durability-lag breach, an SLO burning across both windows, a doctor
critical); 2 = usage/storage error.
"""

import argparse
import asyncio
import json
import sys
import time
from typing import Any, Dict, List, Optional

from . import sampler as _sampler
from . import slo as _slo
from . import watch as _watch
from .doctor import Finding, render_findings

_HUMAN = _watch._human_bytes


def collect(path: str) -> Dict[str, Any]:
    """Everything observable at ``path``: progress groups, sampler
    samples per rank, ledger records, report-based doctor findings.
    Raises on an unusable path (the CLI maps that to exit 2)."""
    import os

    state: Dict[str, Any] = {
        "path": path,
        "progress": {},
        "samples_by_rank": {},
        "ledger_records": [],
        "report_findings": [],
    }
    if "://" not in path and not os.path.exists(path):
        raise FileNotFoundError(
            f"no such live-ops directory or snapshot: {path}"
        )
    if "://" not in path and os.path.isdir(path) and not os.path.exists(
        os.path.join(path, ".snapshot_metadata")
    ):
        # Local live-ops directory mode (statusfiles only).
        from . import progress as _progress

        grouped: Dict[str, Dict[int, Dict[str, Any]]] = {}
        for rank, rec in _progress.collect_statusfiles(path).items():
            key = f"{rec.get('kind', '?')}:{rec.get('take_id') or 'local'}"
            grouped.setdefault(key, {})[rank] = rec
        state["progress"] = grouped
        state["samples_by_rank"] = _sampler.collect_statusfiles(path)
    else:
        from ..storage_plugin import url_to_storage_plugin
        from . import progress as _progress

        storage = url_to_storage_plugin(path)
        try:
            state["progress"] = asyncio.run(
                _progress.acollect_storage_records(storage)
            )
            state["samples_by_rank"] = asyncio.run(
                _sampler.acollect_storage_records(storage)
            )
        finally:
            storage.close()
        from . import ledger as _ledger

        try:
            state["ledger_records"], _ = _ledger.read_records(path)
        except Exception:  # snapcheck: disable=swallowed-exception -- ledger optional in ops view
            pass
        try:
            from . import doctor as _doctor

            reports = _doctor._collect_snapshot_reports(path)
            state["report_findings"] = _doctor.diagnose(reports)
        except Exception:  # snapcheck: disable=swallowed-exception -- reports optional in ops view
            pass
    _merge_live_runtime(state)
    return state


def _merge_live_runtime(state: Dict[str, Any]) -> None:
    """Fold in a direct sample of THIS process's runtime when the hot
    tier is enabled here — the embedded/test path that needs no
    publishing round-trip."""
    from .. import hottier

    rt = hottier.runtime()
    if rt is None or not rt.active:
        return
    try:
        live = _sampler.RuntimeSampler(rank=rt.rank).build_sample()
    except Exception:  # snapcheck: disable=swallowed-exception -- live sample is a bonus, never a failure
        return
    live["live"] = True
    state["samples_by_rank"].setdefault(rt.rank, []).append(live)


# --------------------------------------------------------------- verdict


def findings_of(state: Dict[str, Any]) -> List[Finding]:
    """Active findings: SLO engine (ledger burn rates + live sampler
    rules, evaluated per rank — each rank is its own drain pipeline)
    plus the report-based doctor findings."""
    result = _slo.evaluate(
        records=state["ledger_records"],
        samples_by_rank=state["samples_by_rank"],
    )
    state["slo"] = result
    return list(result["findings"]) + list(state["report_findings"])


# -------------------------------------------------------------- rendering


def _render_repair_lines(repair: Optional[Dict[str, Any]]) -> List[str]:
    """The snapmend membership/repair block of the drain section:
    per-host generation + liveness from the supervisor's view, the
    at-risk (under-replicated) bytes with their age against the repair
    deadline, and the repair loop's cumulative work. Omitted entirely
    when the repair plane is off (``repair`` is None)."""
    if not isinstance(repair, dict):
        return []
    lines: List[str] = []
    under_objects = int(repair.get("underreplicated_objects") or 0)
    under_bytes = int(repair.get("underreplicated_bytes") or 0)
    oldest = repair.get("oldest_underreplicated_age_s")
    parts = [
        f"repair[{repair.get('mode', '?')}]:",
        f"under-replicated {under_objects} obj "
        f"({_HUMAN(under_bytes)} at risk)",
    ]
    if oldest is not None:
        parts.append(
            f"oldest {oldest:.1f}s/"
            f"{float(repair.get('deadline_s') or 0):g}s deadline"
        )
    stats = repair.get("stats") or {}
    if stats.get("objects_repaired"):
        parts.append(
            f"repaired {stats['objects_repaired']} obj "
            f"({_HUMAN(stats.get('bytes_repaired') or 0)})"
        )
    if stats.get("escalated_write_throughs"):
        parts.append(
            f"ESCALATED {stats['escalated_write_throughs']} "
            f"write-through(s)"
        )
    if stats.get("peer_restarts"):
        parts.append(f"restarted {stats['peer_restarts']} peer(s)")
    if repair.get("repair_error"):
        parts.append(f"REPAIR DEAD: {repair['repair_error']}")
    lines.append(" ".join(parts))
    membership = repair.get("membership") or {}
    if membership:
        lines.append(
            "membership: "
            + " ".join(
                f"h{h}:gen{v.get('current_generation', v.get('generation'))}"
                + ("" if v.get("alive") else "(LOST)")
                + (
                    ""
                    if v.get("restartable")
                    else "[external]"
                )
                for h, v in sorted(
                    membership.items(), key=lambda kv: int(kv[0])
                )
            )
        )
    return lines


def _render_drain_section(state: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    for rank, rank_samples in sorted(state["samples_by_rank"].items()):
        latest = rank_samples[-1]
        hot = latest.get("hot_tier")
        sched = latest.get("scheduler") or {}
        if hot:
            backlog = int(hot.get("queue_depth") or 0) + int(
                hot.get("inflight") or 0
            )
            age = hot.get("oldest_pending_age_s")
            beat = hot.get("drain_heartbeat_age_s")
            parts = [
                f"drain backlog {backlog} (queued "
                f"{hot.get('queue_depth', 0)} + in-flight "
                f"{hot.get('inflight', 0)})",
                f"at-risk {_HUMAN(hot.get('at_risk_bytes') or 0)}",
            ]
            if age is not None:
                parts.append(f"oldest item {age:.1f}s")
            if beat is not None:
                parts.append(f"drain beat {beat:.1f}s ago")
            if hot.get("stranded_objects"):
                parts.append(
                    f"STRANDED {hot['stranded_objects']} at "
                    f"{hot.get('stranded_roots')}"
                )
            if hot.get("drain_error"):
                parts.append(f"DRAIN DEAD: {hot['drain_error']}")
            lines.append(f"rank {rank}: " + ", ".join(parts))
            for root, nbytes in sorted(
                (hot.get("at_risk_by_root") or {}).items()
            ):
                lines.append(
                    f"    at-risk root {root}: {_HUMAN(nbytes)} undrained"
                )
            hosts = hot.get("hosts") or {}
            if hosts:
                occ = " ".join(
                    f"h{h}:{_HUMAN(o.get('used_bytes') or 0)}/"
                    f"{_HUMAN(o.get('capacity_bytes') or 0)}"
                    + ("" if o.get("alive") else "(DEAD)")
                    for h, o in sorted(hosts.items())
                )
                lines.append(f"    hosts: {occ}")
            lines.extend(
                f"    {line}"
                for line in _render_repair_lines(hot.get("repair"))
            )
        for pipeline, s in sorted(sched.items()):
            if s.get("budget_in_use_bytes") or s.get("stalled"):
                lines.append(
                    f"rank {rank}: scheduler {pipeline} budget in use "
                    f"{_HUMAN(s.get('budget_in_use_bytes') or 0)}"
                    + (" STALLED" if s.get("stalled") else "")
                )
    return lines


def render(state: Dict[str, Any], stale_after_s: float) -> str:
    lines: List[str] = [f"ops view of {state['path']}"]
    progress = state["progress"]
    if progress:
        for key in sorted(progress):
            lines.append("")
            lines.append(
                _watch.render_progress(
                    progress[key], stale_after_s=stale_after_s
                )
            )
    else:
        lines.append("no in-flight progress records")
    drain = _render_drain_section(state)
    if drain:
        lines.append("")
        lines.append("drain pipeline / scheduler:")
        lines.extend(f"  {line}" for line in drain)
    slo_result = state.get("slo")
    if slo_result is not None and slo_result.get("objectives"):
        lines.append("")
        lines.append(_slo.render(slo_result, with_findings=False))
    report_findings = state.get("report_findings") or []
    slo_findings = (slo_result or {}).get("findings") or []
    all_findings = list(slo_findings) + list(report_findings)
    lines.append("")
    lines.append(render_findings(all_findings))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry.ops",
        description="Unified live-operations view: in-flight progress, "
        "drain/sampler state, SLO burn rates, doctor findings.",
    )
    parser.add_argument(
        "path",
        help="snapshot/ledger URL (storage mode) or a local "
        "TPUSNAPSHOT_PROGRESS_DIR directory (statusfile mode)",
    )
    parser.add_argument(
        "--stale-after",
        type=float,
        default=None,
        metavar="S",
        help="progress staleness window (default: 3x publish interval)",
    )
    parser.add_argument(
        "--follow",
        action="store_true",
        help="keep polling and re-rendering instead of printing once",
    )
    parser.add_argument(
        "--interval",
        type=float,
        default=2.0,
        metavar="S",
        help="poll interval for --follow (default 2s)",
    )
    parser.add_argument("--json", action="store_true", help="emit JSON")
    args = parser.parse_args(argv)
    stale_after = _watch._stale_after_s(args.stale_after)
    while True:
        try:
            state = collect(args.path)
            findings = findings_of(state)
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        critical = [f for f in findings if f.severity == "critical"]
        if args.json:
            doc = {
                "path": state["path"],
                "progress": state["progress"],
                "samples_by_rank": {
                    str(r): s
                    for r, s in state["samples_by_rank"].items()
                },
                "slo": dict(
                    state.get("slo") or {},
                    findings=[
                        f.as_dict()
                        for f in (state.get("slo") or {}).get(
                            "findings", []
                        )
                    ],
                ),
                "report_findings": [
                    f.as_dict() for f in state["report_findings"]
                ],
                "critical": [f.as_dict() for f in critical],
            }
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(render(state, stale_after))
        if not args.follow:
            return 1 if critical else 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
