"""snapscope's reading half: the unified live-operations view.

``watch`` renders in-flight progress, ``doctor`` diagnoses reports,
``slo`` judges objectives, the sampler publishes runtime state — four
views an operator would have to correlate by hand during an incident.
This CLI merges them into one per-rank operational display::

    python -m torchsnapshot_tpu.telemetry.ops <path> [--json]

``<path>`` is either a snapshot/ledger URL (storage mode: reads
``.progress/<take_id>/<rank>`` progress objects, ``.scope/rank<N>``
sampler records, the telemetry ledger, and the committed flight
reports) or a local live-ops directory (``TPUSNAPSHOT_PROGRESS_DIR``
statusfiles: ``rank<N>.progress.json`` + ``rank<N>.scope.jsonl``).
When the hot tier is enabled IN THIS PROCESS the view additionally
samples the runtime directly, so an embedded caller (or a test) sees
the drain pipeline with no publishing round-trip.

Sections, each omitted when it has nothing to say:

- **in-flight operations** — ``watch``'s per-rank table (phase, bytes,
  throughput, ETA, heartbeat staleness), including the hot tier's
  background ``tierdown`` records, so a drain backlog is visible as a
  live operation rather than post-commit darkness;
- **drain pipeline** — per-rank sampler state: queue depth, in-flight,
  oldest pending-object age, at-risk bytes per committed root,
  stranded items, drain heartbeat age, per-host replica occupancy;
- **scheduler** — live memory-budget occupancy / stalled state;
- **SLOs & findings** — the SLO engine's burn-rate table over the
  ledger plus its live rules, and any doctor findings from the
  snapshot's committed reports.

Exit codes (watch-style, CI/pager-facing): 0 = healthy (live work may
be in flight — a draining backlog is normal operation); 1 = a CRITICAL
finding is active (stranded drains — the output names the roots —
durability-lag breach, an SLO burning across both windows, a doctor
critical); 2 = usage/storage error.

**Fleet wire mode** (snapflight): ``--wire addr,addr`` polls snapserve
servers and ``--wire-peers addr,addr`` polls snapwire hot-tier peers
for their wiretap sample blocks (piggybacked on the ``stats`` RPC),
merges the per-op latency/deadline-margin summaries fleet-wide, and
renders a ``fleet wire`` section: per-member RPC totals and the
slowest ops by p99. Exit contract: deadline misses anywhere in the
fleet (or an unreachable member) → 1; EVERY target unreachable → 2
(the view itself is unavailable).

**Fleet memory mode** (snapmem): ``--mem`` merges the host-memory
domain ledgers of every process in the job — trainer ranks from the
sampler records at ``PATH``, snapserve servers (``--wire``) and
snapwire hot-tier peers (``--wire-peers``) from the ``memory`` block
piggybacked on their ``stats`` RPCs — into one per-domain occupancy
view with fleet-wide sums. Exit contract: a member over a domain cap
or past the host budget (or an unreachable member) → 1; EVERY target
unreachable → 2.
"""

import argparse
import asyncio
import json
import sys
import time
from typing import Any, Dict, List, Optional

from . import sampler as _sampler
from . import slo as _slo
from . import watch as _watch
from .doctor import Finding, render_findings

_HUMAN = _watch._human_bytes


def collect(path: str) -> Dict[str, Any]:
    """Everything observable at ``path``: progress groups, sampler
    samples per rank, ledger records, report-based doctor findings.
    Raises on an unusable path (the CLI maps that to exit 2)."""
    import os

    state: Dict[str, Any] = {
        "path": path,
        "progress": {},
        "samples_by_rank": {},
        "ledger_records": [],
        "report_findings": [],
    }
    if "://" not in path and not os.path.exists(path):
        raise FileNotFoundError(
            f"no such live-ops directory or snapshot: {path}"
        )
    if "://" not in path and os.path.isdir(path) and not os.path.exists(
        os.path.join(path, ".snapshot_metadata")
    ):
        # Local live-ops directory mode (statusfiles only).
        from . import progress as _progress

        grouped: Dict[str, Dict[int, Dict[str, Any]]] = {}
        for rank, rec in _progress.collect_statusfiles(path).items():
            key = f"{rec.get('kind', '?')}:{rec.get('take_id') or 'local'}"
            grouped.setdefault(key, {})[rank] = rec
        state["progress"] = grouped
        state["samples_by_rank"] = _sampler.collect_statusfiles(path)
    else:
        from ..storage_plugin import url_to_storage_plugin
        from . import progress as _progress

        storage = url_to_storage_plugin(path)
        try:
            state["progress"] = asyncio.run(
                _progress.acollect_storage_records(storage)
            )
            state["samples_by_rank"] = asyncio.run(
                _sampler.acollect_storage_records(storage)
            )
        finally:
            storage.close()
        from . import ledger as _ledger

        try:
            state["ledger_records"], _ = _ledger.read_records(path)
        except Exception:  # snapcheck: disable=swallowed-exception -- ledger optional in ops view
            pass
        try:
            from . import doctor as _doctor

            reports = _doctor._collect_snapshot_reports(path)
            state["report_findings"] = _doctor.diagnose(reports)
        except Exception:  # snapcheck: disable=swallowed-exception -- reports optional in ops view
            pass
    _merge_live_runtime(state)
    return state


def _merge_live_runtime(state: Dict[str, Any]) -> None:
    """Fold in a direct sample of THIS process's runtime when the hot
    tier is enabled here — the embedded/test path that needs no
    publishing round-trip."""
    from .. import hottier

    rt = hottier.runtime()
    if rt is None or not rt.active:
        return
    try:
        live = _sampler.RuntimeSampler(rank=rt.rank).build_sample()
    except Exception:  # snapcheck: disable=swallowed-exception -- live sample is a bonus, never a failure
        return
    live["live"] = True
    state["samples_by_rank"].setdefault(rt.rank, []).append(live)


# --------------------------------------------------------------- verdict


def findings_of(state: Dict[str, Any]) -> List[Finding]:
    """Active findings: SLO engine (ledger burn rates + live sampler
    rules, evaluated per rank — each rank is its own drain pipeline)
    plus the report-based doctor findings."""
    result = _slo.evaluate(
        records=state["ledger_records"],
        samples_by_rank=state["samples_by_rank"],
    )
    state["slo"] = result
    return list(result["findings"]) + list(state["report_findings"])


# ----------------------------------------------------------- fleet wire


def collect_fleet_wire(
    server_addrs: List[str],
    peer_addrs: List[str],
    timeout_s: float = 10.0,
) -> Dict[str, Any]:
    """snapflight's fleet-wide wire view: poll every target's ``stats``
    RPC (snapserve servers via :func:`fetch_server_stats`, snapwire
    peers via :meth:`RemotePeer.wire_stats` — both piggyback the
    wiretap sample block) and merge the per-op summaries across the
    fleet. Per telemetry key: counts/misses/retries SUM across
    processes, latency/margin percentiles take the fleet-wide MAX (the
    question is "is any member's wire collapsing", not the average).
    Unreachable targets are recorded, not raised — the caller decides
    the exit-code verdict."""
    targets: List[Dict[str, Any]] = []
    for addr in server_addrs:
        entry: Dict[str, Any] = {"target": addr, "transport": "snapserve"}
        try:
            from ..snapserve.server import fetch_server_stats

            stats = fetch_server_stats(addr, timeout_s=timeout_s)
            entry["ok"] = True
            wire = stats.get("wire")
            if isinstance(wire, dict):
                entry["wire"] = wire
        except Exception as e:
            entry["ok"] = False
            entry["error"] = f"{type(e).__name__}: {e}"
        targets.append(entry)
    for i, addr in enumerate(peer_addrs):
        entry = {"target": addr, "transport": "snapwire"}
        try:
            from ..hottier.transport import RemotePeer

            peer = RemotePeer(-(i + 1), addr)
            wire = peer.wire_stats()
            if wire is None:
                raise ConnectionError("peer unreachable or down")
            entry["ok"] = True
            if wire.get("ops"):
                entry["wire"] = wire
        except Exception as e:
            entry["ok"] = False
            entry["error"] = f"{type(e).__name__}: {e}"
        targets.append(entry)
    ops: Dict[str, Dict[str, Any]] = {}
    for entry in targets:
        for key, block in ((entry.get("wire") or {}).get("ops") or {}).items():
            if not isinstance(block, dict):
                continue
            agg = ops.setdefault(key, {})
            for field in (
                "count",
                "deadline_misses",
                "retries",
                "bytes_in",
                "bytes_out",
            ):
                agg[field] = int(agg.get(field) or 0) + int(
                    block.get(field) or 0
                )
            for field in ("p50_s", "p99_s", "margin_p99", "margin_max"):
                v = block.get(field)
                if v is not None:
                    agg[field] = max(float(agg.get(field) or 0.0), float(v))
            if block.get("deadline_s") is not None:
                agg["deadline_s"] = block["deadline_s"]
    reachable = sum(1 for t in targets if t.get("ok"))
    return {
        "targets": targets,
        "ops": ops,
        "reachable": reachable,
        "unreachable": len(targets) - reachable,
    }


def fleet_wire_findings(fleet: Dict[str, Any]) -> List[Finding]:
    """The fleet wire verdict: unreachable members are critical (the
    probe WAS the liveness check), and the merged per-op blocks go
    through the same deadline-pressure rule the doctor and slo use."""
    findings: List[Finding] = []
    down = [t for t in fleet["targets"] if not t.get("ok")]
    if down:
        findings.append(
            Finding(
                rule="fleet-member-unreachable",
                severity="critical",
                title=(
                    f"{len(down)} of {len(fleet['targets'])} fleet "
                    f"target(s) unreachable"
                ),
                evidence={
                    "unreachable": [
                        {
                            "target": t["target"],
                            "transport": t["transport"],
                            "error": t.get("error"),
                        }
                        for t in down
                    ]
                },
                remediation=(
                    "the stats probe could not reach these members — "
                    "check process liveness (fleet supervisor / repair "
                    "membership view) and their blackbox dumps "
                    "(*.blackbox.jsonl under TPUSNAPSHOT_WIRETAP_DIR) "
                    "for their last recorded RPCs."
                ),
            )
        )
    from .doctor import wire_pressure_finding

    pressure = wire_pressure_finding(fleet["ops"], source="fleet")
    if pressure is not None:
        findings.append(pressure)
    return findings


def _render_fleet_wire(fleet: Dict[str, Any]) -> List[str]:
    lines: List[str] = ["fleet wire:"]
    for t in fleet["targets"]:
        if not t.get("ok"):
            lines.append(
                f"  {t['transport']} {t['target']}: UNREACHABLE "
                f"({t.get('error')})"
            )
            continue
        wire = t.get("wire") or {}
        ops = wire.get("ops") or {}
        rpcs = sum(int(b.get("count") or 0) for b in ops.values())
        parts = [f"{rpcs} rpc(s)", f"{len(ops)} op(s)"]
        if wire.get("deadline_misses"):
            parts.append(f"MISSES {wire['deadline_misses']}")
        if wire.get("retries"):
            parts.append(f"retries {wire['retries']}")
        if wire.get("worst_margin_p99") is not None:
            parts.append(
                f"worst margin p99 {wire['worst_margin_p99']:.0%} "
                f"({wire.get('worst_op')})"
            )
        lines.append(f"  {t['transport']} {t['target']}: " + ", ".join(parts))
    if fleet["ops"]:
        lines.append("  slowest ops (fleet-wide max p99):")
        by_p99 = sorted(
            fleet["ops"].items(),
            key=lambda kv: float(kv[1].get("p99_s") or 0.0),
            reverse=True,
        )
        for key, b in by_p99[:8]:
            parts = [
                f"n={b.get('count', 0)}",
                f"p50 {float(b.get('p50_s') or 0) * 1000:.1f}ms",
                f"p99 {float(b.get('p99_s') or 0) * 1000:.1f}ms",
            ]
            if b.get("margin_p99") is not None:
                parts.append(f"margin p99 {b['margin_p99']:.0%}")
            if b.get("deadline_misses"):
                parts.append(f"MISSES {b['deadline_misses']}")
            if b.get("retries"):
                parts.append(f"retries {b['retries']}")
            lines.append(f"    {key}: " + " ".join(parts))
    return lines


# ----------------------------------------------------------- fleet memory


def collect_fleet_mem(
    path: Optional[str],
    server_addrs: List[str],
    peer_addrs: List[str],
    timeout_s: float = 10.0,
) -> Dict[str, Any]:
    """snapmem's fleet-wide host-memory view: trainer ranks from the
    sampler records at ``path``, snapserve servers and snapwire peers
    from the ``memory`` block piggybacked on their ``stats`` RPCs.
    Per-domain occupancy/high-water/cap SUM across members (each
    process owns its own bytes, so the fleet total is the real host
    footprint); the per-member blocks are kept verbatim so the
    overcommit verdict stays per-process (one member over ITS cap is a
    finding even when the fleet sum looks healthy). Unreachable
    targets are recorded, not raised."""
    members: List[Dict[str, Any]] = []
    if path:
        try:
            state = collect(path)
        except Exception as e:
            members.append(
                {
                    "member": path,
                    "kind": "trainer",
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        else:
            for rank, rank_samples in sorted(
                state["samples_by_rank"].items()
            ):
                mem = None
                for sample in reversed(rank_samples):
                    if isinstance(sample.get("memory"), dict):
                        mem = sample["memory"]
                        break
                entry: Dict[str, Any] = {
                    "member": f"rank {rank}",
                    "kind": "trainer",
                    "ok": True,
                }
                if mem is not None:
                    entry["memory"] = mem
                members.append(entry)
    for addr in server_addrs:
        entry = {"member": addr, "kind": "snapserve", "ok": False}
        try:
            from ..snapserve.server import fetch_server_stats

            stats = fetch_server_stats(addr, timeout_s=timeout_s)
            entry["ok"] = True
            mem = stats.get("memory")
            if isinstance(mem, dict):
                entry["memory"] = mem
        except Exception as e:
            entry["error"] = f"{type(e).__name__}: {e}"
        members.append(entry)
    for i, addr in enumerate(peer_addrs):
        entry = {"member": addr, "kind": "snapwire", "ok": False}
        try:
            from ..hottier.transport import RemotePeer

            mem = RemotePeer(-(i + 1), addr).mem_stats()
            if mem is None:
                # Every peer process registers at least the wiretap
                # ring domain at import, so no block means no answer.
                raise ConnectionError("peer unreachable or down")
            entry["ok"] = True
            entry["memory"] = mem
        except Exception as e:
            entry["error"] = f"{type(e).__name__}: {e}"
        members.append(entry)
    domains: Dict[str, Dict[str, Any]] = {}
    committed = 0
    rss = 0
    for entry in members:
        mem = entry.get("memory")
        if not isinstance(mem, dict):
            continue
        committed += int(mem.get("committed_bytes") or 0)
        rss += int(mem.get("rss_bytes") or 0)
        for name, block in (mem.get("domains") or {}).items():
            if not isinstance(block, dict):
                continue
            agg = domains.setdefault(
                name,
                {
                    "used_bytes": 0,
                    "pinned_bytes": 0,
                    "high_water_bytes": 0,
                    "cap_bytes": None,
                    "members": 0,
                    "external": False,
                },
            )
            agg["used_bytes"] += int(block.get("used_bytes") or 0)
            agg["pinned_bytes"] += int(block.get("pinned_bytes") or 0)
            agg["high_water_bytes"] += int(
                block.get("high_water_bytes") or 0
            )
            if block.get("cap_bytes") is not None:
                agg["cap_bytes"] = int(agg["cap_bytes"] or 0) + int(
                    block["cap_bytes"]
                )
            agg["members"] += 1
            agg["external"] = bool(
                agg["external"] or block.get("external")
            )
    reachable = sum(1 for m in members if m.get("ok"))
    return {
        "members": members,
        "domains": domains,
        "committed_bytes": committed,
        "rss_bytes": rss,
        "reachable": reachable,
        "unreachable": len(members) - reachable,
    }


def fleet_mem_findings(fleet: Dict[str, Any]) -> List[Finding]:
    """The fleet memory verdict: unreachable members are critical (the
    probe WAS the liveness check), and every reachable member's block
    goes through the same overcommit rule the doctor and slo use — the
    finding names which process is over which domain's cap."""
    findings: List[Finding] = []
    down = [m for m in fleet["members"] if not m.get("ok")]
    if down:
        findings.append(
            Finding(
                rule="fleet-member-unreachable",
                severity="critical",
                title=(
                    f"{len(down)} of {len(fleet['members'])} fleet "
                    f"target(s) unreachable"
                ),
                evidence={
                    "unreachable": [
                        {
                            "member": m["member"],
                            "kind": m["kind"],
                            "error": m.get("error"),
                        }
                        for m in down
                    ]
                },
                remediation=(
                    "the stats probe could not reach these members — "
                    "check process liveness and their flight/blackbox "
                    "records for the last state they published."
                ),
            )
        )
    from .doctor import memory_pressure_finding

    for m in fleet["members"]:
        mem = m.get("memory")
        if not isinstance(mem, dict):
            continue
        pressure = memory_pressure_finding(
            mem, source=f"{m['kind']} {m['member']}"
        )
        if pressure is not None:
            findings.append(pressure)
    return findings


def _render_fleet_mem(fleet: Dict[str, Any]) -> List[str]:
    lines: List[str] = ["fleet memory:"]
    for m in fleet["members"]:
        if not m.get("ok"):
            lines.append(
                f"  {m['kind']} {m['member']}: UNREACHABLE "
                f"({m.get('error')})"
            )
            continue
        mem = m.get("memory")
        if not isinstance(mem, dict):
            lines.append(
                f"  {m['kind']} {m['member']}: no memory block published"
            )
            continue
        parts = [
            f"committed {_HUMAN(mem.get('committed_bytes') or 0)}",
            f"hwm {_HUMAN(mem.get('high_water_bytes') or 0)}",
        ]
        if mem.get("rss_bytes"):
            parts.append(f"rss {_HUMAN(mem['rss_bytes'])}")
        if mem.get("headroom_bytes") is not None:
            parts.append(
                f"headroom {_HUMAN(mem['headroom_bytes'])} "
                f"(budget: {mem.get('budget_source', '?')})"
            )
        lines.append(f"  {m['kind']} {m['member']}: " + ", ".join(parts))
        for name, d in sorted((mem.get("domains") or {}).items()):
            cap = d.get("cap_bytes")
            lines.append(
                f"    {name}: {_HUMAN(d.get('used_bytes') or 0)}"
                + (f" / {_HUMAN(cap)}" if cap is not None else "")
                + f" (hwm {_HUMAN(d.get('high_water_bytes') or 0)})"
                + (" [external]" if d.get("external") else "")
            )
    if fleet["domains"]:
        lines.append("  merged domains (fleet-wide sums):")
        by_used = sorted(
            fleet["domains"].items(),
            key=lambda kv: int(kv[1].get("used_bytes") or 0),
            reverse=True,
        )
        for name, d in by_used:
            cap = d.get("cap_bytes")
            lines.append(
                f"    {name}: used {_HUMAN(d['used_bytes'])}"
                + (f" / {_HUMAN(cap)}" if cap is not None else "")
                + f", hwm {_HUMAN(d['high_water_bytes'])} across "
                f"{d['members']} member(s)"
                + (" [external]" if d.get("external") else "")
            )
        lines.append(
            f"  fleet committed {_HUMAN(fleet['committed_bytes'])}, "
            f"rss {_HUMAN(fleet['rss_bytes'])} over "
            f"{fleet['reachable']} reachable member(s)"
        )
    return lines


# -------------------------------------------------------------- rendering


def _render_repair_lines(repair: Optional[Dict[str, Any]]) -> List[str]:
    """The snapmend membership/repair block of the drain section:
    per-host generation + liveness from the supervisor's view, the
    at-risk (under-replicated) bytes with their age against the repair
    deadline, and the repair loop's cumulative work. Omitted entirely
    when the repair plane is off (``repair`` is None)."""
    if not isinstance(repair, dict):
        return []
    lines: List[str] = []
    under_objects = int(repair.get("underreplicated_objects") or 0)
    under_bytes = int(repair.get("underreplicated_bytes") or 0)
    oldest = repair.get("oldest_underreplicated_age_s")
    parts = [
        f"repair[{repair.get('mode', '?')}]:",
        f"under-replicated {under_objects} obj "
        f"({_HUMAN(under_bytes)} at risk)",
    ]
    if oldest is not None:
        parts.append(
            f"oldest {oldest:.1f}s/"
            f"{float(repair.get('deadline_s') or 0):g}s deadline"
        )
    stats = repair.get("stats") or {}
    if stats.get("objects_repaired"):
        parts.append(
            f"repaired {stats['objects_repaired']} obj "
            f"({_HUMAN(stats.get('bytes_repaired') or 0)})"
        )
    if stats.get("escalated_write_throughs"):
        parts.append(
            f"ESCALATED {stats['escalated_write_throughs']} "
            f"write-through(s)"
        )
    if stats.get("peer_restarts"):
        parts.append(f"restarted {stats['peer_restarts']} peer(s)")
    if repair.get("repair_error"):
        parts.append(f"REPAIR DEAD: {repair['repair_error']}")
    lines.append(" ".join(parts))
    membership = repair.get("membership") or {}
    if membership:
        lines.append(
            "membership: "
            + " ".join(
                f"h{h}:gen{v.get('current_generation', v.get('generation'))}"
                + ("" if v.get("alive") else "(LOST)")
                + (
                    ""
                    if v.get("restartable")
                    else "[external]"
                )
                for h, v in sorted(
                    membership.items(), key=lambda kv: int(kv[0])
                )
            )
        )
    return lines


def _render_drain_section(state: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    for rank, rank_samples in sorted(state["samples_by_rank"].items()):
        latest = rank_samples[-1]
        hot = latest.get("hot_tier")
        sched = latest.get("scheduler") or {}
        if hot:
            backlog = int(hot.get("queue_depth") or 0) + int(
                hot.get("inflight") or 0
            )
            age = hot.get("oldest_pending_age_s")
            beat = hot.get("drain_heartbeat_age_s")
            parts = [
                f"drain backlog {backlog} (queued "
                f"{hot.get('queue_depth', 0)} + in-flight "
                f"{hot.get('inflight', 0)})",
                f"at-risk {_HUMAN(hot.get('at_risk_bytes') or 0)}",
            ]
            if age is not None:
                parts.append(f"oldest item {age:.1f}s")
            if beat is not None:
                parts.append(f"drain beat {beat:.1f}s ago")
            if hot.get("stranded_objects"):
                parts.append(
                    f"STRANDED {hot['stranded_objects']} at "
                    f"{hot.get('stranded_roots')}"
                )
            if hot.get("drain_error"):
                parts.append(f"DRAIN DEAD: {hot['drain_error']}")
            lines.append(f"rank {rank}: " + ", ".join(parts))
            for root, nbytes in sorted(
                (hot.get("at_risk_by_root") or {}).items()
            ):
                lines.append(
                    f"    at-risk root {root}: {_HUMAN(nbytes)} undrained"
                )
            hosts = hot.get("hosts") or {}
            if hosts:
                occ = " ".join(
                    f"h{h}:{_HUMAN(o.get('used_bytes') or 0)}/"
                    f"{_HUMAN(o.get('capacity_bytes') or 0)}"
                    + ("" if o.get("alive") else "(DEAD)")
                    for h, o in sorted(hosts.items())
                )
                lines.append(f"    hosts: {occ}")
            lines.extend(
                f"    {line}"
                for line in _render_repair_lines(hot.get("repair"))
            )
        for pipeline, s in sorted(sched.items()):
            if s.get("budget_in_use_bytes") or s.get("stalled"):
                lines.append(
                    f"rank {rank}: scheduler {pipeline} budget in use "
                    f"{_HUMAN(s.get('budget_in_use_bytes') or 0)}"
                    + (" STALLED" if s.get("stalled") else "")
                )
    return lines


def render(state: Dict[str, Any], stale_after_s: float) -> str:
    lines: List[str] = [f"ops view of {state['path']}"]
    progress = state["progress"]
    if progress:
        for key in sorted(progress):
            lines.append("")
            lines.append(
                _watch.render_progress(
                    progress[key], stale_after_s=stale_after_s
                )
            )
    else:
        lines.append("no in-flight progress records")
    drain = _render_drain_section(state)
    if drain:
        lines.append("")
        lines.append("drain pipeline / scheduler:")
        lines.extend(f"  {line}" for line in drain)
    slo_result = state.get("slo")
    if slo_result is not None and slo_result.get("objectives"):
        lines.append("")
        lines.append(_slo.render(slo_result, with_findings=False))
    report_findings = state.get("report_findings") or []
    slo_findings = (slo_result or {}).get("findings") or []
    all_findings = list(slo_findings) + list(report_findings)
    lines.append("")
    lines.append(render_findings(all_findings))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry.ops",
        description="Unified live-operations view: in-flight progress, "
        "drain/sampler state, SLO burn rates, doctor findings.",
    )
    parser.add_argument(
        "path",
        nargs="?",
        help="snapshot/ledger URL (storage mode) or a local "
        "TPUSNAPSHOT_PROGRESS_DIR directory (statusfile mode); "
        "optional in fleet wire mode (--wire / --wire-peers)",
    )
    parser.add_argument(
        "--wire",
        metavar="ADDR,ADDR",
        help="fleet wire mode: comma-separated snapserve server "
        "addresses to poll for their wiretap sample blocks",
    )
    parser.add_argument(
        "--wire-peers",
        metavar="ADDR,ADDR",
        help="fleet wire mode: comma-separated snapwire hot-tier peer "
        "addresses (host=addr entries also accepted) to poll",
    )
    parser.add_argument(
        "--mem",
        action="store_true",
        help="fleet memory mode (snapmem): merge the host-memory "
        "domain ledgers of trainer ranks (from PATH's sampler "
        "records), snapserve servers (--wire) and snapwire peers "
        "(--wire-peers) into one per-domain occupancy view",
    )
    parser.add_argument(
        "--wire-timeout",
        type=float,
        default=10.0,
        metavar="S",
        help="per-target probe timeout for fleet wire mode (default 10s)",
    )
    parser.add_argument(
        "--stale-after",
        type=float,
        default=None,
        metavar="S",
        help="progress staleness window (default: 3x publish interval)",
    )
    parser.add_argument(
        "--follow",
        action="store_true",
        help="keep polling and re-rendering instead of printing once",
    )
    parser.add_argument(
        "--interval",
        type=float,
        default=2.0,
        metavar="S",
        help="poll interval for --follow (default 2s)",
    )
    parser.add_argument("--json", action="store_true", help="emit JSON")
    args = parser.parse_args(argv)
    wire_mode = bool(args.wire or args.wire_peers)
    if not args.path and not wire_mode:
        parser.error("a path is required (or --wire / --wire-peers)")
    if args.mem:
        server_addrs = [
            a.strip() for a in (args.wire or "").split(",") if a.strip()
        ]
        peer_addrs = [
            a.strip().rpartition("=")[2]
            for a in (args.wire_peers or "").split(",")
            if a.strip()
        ]
        fleet = collect_fleet_mem(
            args.path,
            server_addrs,
            peer_addrs,
            timeout_s=args.wire_timeout,
        )
        mem_findings = fleet_mem_findings(fleet)
        if args.json:
            doc = dict(
                fleet, findings=[f.as_dict() for f in mem_findings]
            )
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print("\n".join(_render_fleet_mem(fleet)))
            print()
            print(render_findings(mem_findings))
        if fleet["members"] and fleet["reachable"] == 0:
            return 2  # the fleet memory view itself is unavailable
        return (
            1
            if any(f.severity == "critical" for f in mem_findings)
            else 0
        )
    if wire_mode:
        server_addrs = [
            a.strip() for a in (args.wire or "").split(",") if a.strip()
        ]
        peer_addrs = [
            # "host=addr" address-book entries are accepted for
            # copy-paste parity with TPUSNAPSHOT_REPLICA_ADDRS specs.
            a.strip().rpartition("=")[2]
            for a in (args.wire_peers or "").split(",")
            if a.strip()
        ]
        fleet = collect_fleet_wire(
            server_addrs, peer_addrs, timeout_s=args.wire_timeout
        )
        wire_findings = fleet_wire_findings(fleet)
        if args.json:
            doc = dict(
                fleet, findings=[f.as_dict() for f in wire_findings]
            )
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print("\n".join(_render_fleet_wire(fleet)))
            print()
            print(render_findings(wire_findings))
        if fleet["targets"] and fleet["reachable"] == 0:
            return 2  # the fleet wire view itself is unavailable
        return (
            1
            if any(f.severity == "critical" for f in wire_findings)
            else 0
        )
    stale_after = _watch._stale_after_s(args.stale_after)
    while True:
        try:
            state = collect(args.path)
            findings = findings_of(state)
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        critical = [f for f in findings if f.severity == "critical"]
        if args.json:
            doc = {
                "path": state["path"],
                "progress": state["progress"],
                "samples_by_rank": {
                    str(r): s
                    for r, s in state["samples_by_rank"].items()
                },
                "slo": dict(
                    state.get("slo") or {},
                    findings=[
                        f.as_dict()
                        for f in (state.get("slo") or {}).get(
                            "findings", []
                        )
                    ],
                ),
                "report_findings": [
                    f.as_dict() for f in state["report_findings"]
                ],
                "critical": [f.as_dict() for f in critical],
            }
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(render(state, stale_after))
        if not args.follow:
            return 1 if critical else 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
